#!/usr/bin/env bash
# C analysis gate over the native sources (mqtt_tpu/native/*.c).
#
# Runs every analyzer the host provides and fails on any finding:
#   - gcc -fanalyzer -Wall -Wextra -Werror  (gcc >= 10; the PR-1 UAF class
#     in accelmod.c is exactly what the analyzer's use-after-free and
#     refcount-shaped path checks cover)
#   - cppcheck --enable=warning,portability  (when installed; CI installs it)
#
# Every finding must be FIXED or suppressed in the source with a comment
# explaining why it is safe — this script takes no suppression flags by
# design.
#
# --san adds the ASAN/UBSAN leg (ISSUE 13 satellite): both native
# modules are REBUILT with -fsanitize=address,undefined (a distinct
# artifact tag, so the plain build's cache is never poisoned) and the
# native-facing test suite runs under them — the lazy-view/freelist C
# code needs runtime lifetime verification, not just -fanalyzer.
# detect_leaks stays off (CPython interns allocate for process lifetime
# by design); UBSan runs -fno-sanitize-recover so any finding is fatal.
#
# Usage: tools/c_gate.sh [--san] [output-log]
set -u
cd "$(dirname "$0")/.."

SAN=0
if [ "${1:-}" = "--san" ]; then
    SAN=1
    shift
fi

LOG="${1:-/tmp/c_gate.log}"
: > "$LOG"
NATIVE=mqtt_tpu/native
# honor the Makefile's interpreter choice (PY=...) so the headers match
# the Python actually running the suite
PY="${PY:-python}"
PY_INC="$("$PY" -c 'import sysconfig; print(sysconfig.get_paths()["include"])')"
if [ -z "$PY_INC" ] || [ ! -e "$PY_INC/Python.h" ]; then
    echo "c_gate: cannot locate Python.h via $PY (got: '$PY_INC')" >&2
    exit 2
fi
rc=0
ran=0

say() { echo "$@" | tee -a "$LOG"; }

if gcc -fanalyzer --version >/dev/null 2>&1; then
    ran=1
    say "== gcc -fanalyzer =="
    # mqtt_native.c is freestanding C; accelmod.c needs the CPython headers
    if ! gcc -fanalyzer -Wall -Wextra -Werror -O1 -c -o /tmp/_cgate_native.o \
            "$NATIVE/mqtt_native.c" >>"$LOG" 2>&1; then
        say "FAIL: gcc -fanalyzer on mqtt_native.c"; rc=1
    fi
    if ! gcc -fanalyzer -Wall -Wextra -Werror -O1 -I"$PY_INC" \
            -c -o /tmp/_cgate_accel.o "$NATIVE/accelmod.c" >>"$LOG" 2>&1; then
        say "FAIL: gcc -fanalyzer on accelmod.c"; rc=1
    fi
else
    say "gcc -fanalyzer unavailable (need gcc >= 10); skipping"
fi

if command -v cppcheck >/dev/null 2>&1; then
    ran=1
    say "== cppcheck =="
    # warning+portability only: style/perf on a CPython extension is noise;
    # missingIncludeSystem so Python.h resolution is not a finding
    if ! cppcheck --enable=warning,portability --error-exitcode=1 \
            --suppress=missingIncludeSystem --inline-suppr \
            -I "$PY_INC" "$NATIVE/mqtt_native.c" "$NATIVE/accelmod.c" \
            >>"$LOG" 2>&1; then
        say "FAIL: cppcheck"; rc=1
    fi
else
    say "cppcheck unavailable; skipping"
fi

if [ "$SAN" = 1 ]; then
    LIBASAN="$(gcc -print-file-name=libasan.so 2>/dev/null || true)"
    if [ -n "$LIBASAN" ] && [ -e "$LIBASAN" ]; then
        ran=1
        say "== ASAN/UBSAN native test leg =="
        # the sanitizer flags change the artifact tag (native/_so_tag),
        # so this leg builds its own .so pair and the plain build's
        # mtime cache stays untouched
        # MQTT_TPU_SAN=1 deselects the jax-backed e2e tests: jaxlib is
        # not ASAN-instrumented and its XLA compiler aborts under the
        # preloaded runtime — the leg verifies OUR C (views, pool,
        # flush, framing), not XLA
        if env \
            MQTT_TPU_NATIVE_CFLAGS="-fsanitize=address,undefined -fno-sanitize-recover=undefined -g" \
            ASAN_OPTIONS="detect_leaks=0:abort_on_error=1" \
            LD_PRELOAD="$LIBASAN" \
            MQTT_TPU_SAN=1 \
            "$PY" -m pytest tests/test_native.py tests/test_fanout.py \
                -q -m 'not slow' -p no:cacheprovider >>"$LOG" 2>&1; then
            say "sanitizer leg: clean"
        else
            say "FAIL: native tests under ASAN/UBSAN"; rc=1
        fi
        # sanitized artifacts are throwaway (tagged -x<hash>)
        rm -f mqtt_tpu/native/libmqtt_native-*-x????????.so \
              mqtt_tpu/native/mqtt_accel-*-x????????.so
    else
        say "libasan unavailable; sanitizer leg skipped"
    fi
fi

if [ "$ran" = 0 ]; then
    say "c_gate: NO analyzer available — gate vacuous on this host"
    # vacuous pass locally; CI always has gcc >= 10
fi
if [ "$rc" != 0 ]; then
    say "c_gate: findings above (full log: $LOG)"
else
    say "c_gate: clean"
fi
exit "$rc"
