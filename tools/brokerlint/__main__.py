"""CLI for brokerlint: ``python -m tools.brokerlint [paths...]``.

Exit status: 0 when no un-baselined findings, 1 otherwise, 2 on usage
error. ``--json`` emits machine-readable findings (the CI artifact)."""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import DEFAULT_BASELINE, RULE_DOC, lint_paths, save_baseline
from .core import load_baseline


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="brokerlint",
        description="repo-specific concurrency/invariant lint pass",
    )
    ap.add_argument("paths", nargs="*", default=["mqtt_tpu"],
                    help="files or directories to lint (default: mqtt_tpu)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the repo containing this tool)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline file (default: tools/brokerlint/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: report everything")
    ap.add_argument("--write-baseline", action="store_true",
                    help="grandfather current findings into the baseline "
                         "(discouraged: the target baseline is empty)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as JSON (CI artifact format)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--lock-graph", metavar="DIR", default=None,
                    help="also write the extracted whole-program "
                         "lock-order graph (R9) to DIR/lockgraph.dot and "
                         "DIR/lockgraph.json")
    ap.add_argument("--loop-graph", metavar="DIR", default=None,
                    help="also write the extracted loop-affinity model "
                         "(R10-R15) to DIR/loopgraph.dot and "
                         "DIR/loopgraph.json")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULE_DOC):
            print(f"{rid}  {RULE_DOC[rid]}")
        return 0

    root = args.root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    paths = args.paths or ["mqtt_tpu"]
    baseline_path = None if args.no_baseline else args.baseline
    new, baselined = lint_paths(paths, root=root, baseline_path=baseline_path)

    if args.lock_graph is not None:
        from .core import collect_files, load_ctx
        from .lockgraph import extract_lock_graph

        ctxs = []
        for p in collect_files(paths, root):
            try:
                ctxs.append(load_ctx(p, root))
            except SyntaxError:
                continue  # already reported as a PARSE finding above
        graph = extract_lock_graph(ctxs)
        os.makedirs(args.lock_graph, exist_ok=True)
        dot = os.path.join(args.lock_graph, "lockgraph.dot")
        with open(dot, "w", encoding="utf-8") as f:
            f.write(graph.to_dot())
        gj = os.path.join(args.lock_graph, "lockgraph.json")
        with open(gj, "w", encoding="utf-8") as f:
            json.dump(graph.as_dict(), f, indent=1)
            f.write("\n")
        print(f"lock graph written: {dot} {gj}", file=sys.stderr)

    if args.loop_graph is not None:
        from .core import collect_files, load_ctx
        from .loopgraph import extract_loop_graph

        ctxs = []
        for p in collect_files(paths, root):
            try:
                ctxs.append(load_ctx(p, root))
            except SyntaxError:
                continue  # already reported as a PARSE finding above
        graph = extract_loop_graph(ctxs)
        os.makedirs(args.loop_graph, exist_ok=True)
        dot = os.path.join(args.loop_graph, "loopgraph.dot")
        with open(dot, "w", encoding="utf-8") as f:
            f.write(graph.to_dot())
        gj = os.path.join(args.loop_graph, "loopgraph.json")
        with open(gj, "w", encoding="utf-8") as f:
            json.dump(graph.as_dict(), f, indent=1)
            f.write("\n")
        print(f"loop graph written: {dot} {gj}", file=sys.stderr)

    if args.write_baseline:
        save_baseline(args.baseline, new + baselined)
        print(f"baseline written: {len(new) + len(baselined)} findings "
              f"-> {args.baseline}")
        return 0

    if args.json:
        print(json.dumps(
            {
                "findings": [f.__dict__ for f in new],
                "baselined": len(baselined),
            },
            indent=1,
        ))
    else:
        for f in new:
            print(f.render())
        if baselined:
            print(f"({len(baselined)} baselined findings suppressed)",
                  file=sys.stderr)
    if new:
        print(f"brokerlint: {len(new)} finding(s)", file=sys.stderr)
        return 1
    if not args.json:
        print("brokerlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
