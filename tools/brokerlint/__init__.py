"""brokerlint: the repo-specific concurrency/invariant lint pass.

Usage (CLI)::

    python -m tools.brokerlint mqtt_tpu/            # lint the broker tree
    python -m tools.brokerlint --list-rules         # rule catalog
    python -m tools.brokerlint --write-baseline ... # (discouraged) grandfather

The tier-1 test suite (tests/test_lint.py) runs the same entry point and
asserts zero findings over the live tree, so the pass is enforcing, not
advisory. See README.md "Static analysis" for the rule rationale.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional

from .core import Finding, load_baseline, run, save_baseline
from .rules import FILE_RULES, PROJECT_RULES, RULE_DOC

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.json")


def lint_paths(
    paths: Iterable[str],
    root: Optional[str] = None,
    baseline_path: Optional[str] = DEFAULT_BASELINE,
) -> tuple[list[Finding], list[Finding]]:
    """Lint ``paths`` (files or directories). Returns ``(new, baselined)``
    findings; an enforcing caller fails when ``new`` is non-empty."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    baseline = load_baseline(baseline_path) if baseline_path else set()
    return run(paths, root, FILE_RULES, PROJECT_RULES, baseline)


__all__ = [
    "Finding",
    "FILE_RULES",
    "PROJECT_RULES",
    "RULE_DOC",
    "DEFAULT_BASELINE",
    "lint_paths",
    "save_baseline",
]
