"""Whole-program loop-affinity model + rules R10-R15 (ISSUE 19).

PR 15's event-loop shard fabric made cross-loop state the broker's
dominant concurrency hazard: per-client transport/QoS state is owned by
ONE shard loop, the staging pipeline parks futures created on OTHER
loops, and cluster writers marshal frames onto peer loops. Every recent
real bug in this class (the OutboundQueue cross-thread wake, the
takeover quiesce, futures parked on the submitter's loop) was found by
hand. This module applies the lockgraph recipe (ISSUE 10) to loop
affinity:

- a small blessed affinity table, ``LOOP_AFFINITY`` (analogous to
  ``LOCK_ORDER``): the catalog of loop-owned object KINDS and the
  legal SEAMS through which foreign threads/loops may touch them. The
  runtime witness (``mqtt_tpu/utils/loopwitness.py``) records every
  (kind, seam) traversal it observes; the tier-1 gate
  (tests/test_zz_loopwitness.py) asserts the witnessed set is a subset
  of this table AND that each cross seam's home module really contains
  an extracted marshal site — an unexplained runtime seam is a model
  gap and fails loudly;
- an extracted :class:`LoopGraph` over ``mqtt_tpu/``: which constructs
  OWN a loop (``LoopShard``/``MatchStage``/``Cluster`` constructors,
  ``connect_accepted_socket`` wrap sites, the ``net.loop`` attach
  seam) and where the marshal seams are (every
  ``call_soon_threadsafe``/``run_coroutine_threadsafe`` call site);
- rules R10-R14 riding the normal pragma/baseline machinery, plus R15
  (the device hot-path D2H rule, ROADMAP item 1's static complement to
  the PR 18 compile ledger).

Rule summary (see README "Static analysis" for the incident each
encodes):

- R10 foreign-thread mutations of loop-affine objects (futures beyond
  R2's set: asyncio Events, tasks, stream writers/transports) must
  route via ``call_soon_threadsafe``/``run_coroutine_threadsafe`` —
  R2's one-loop model generalized to N shards;
- R11 no blocking calls (``time.sleep``, fsync/file I/O, sync
  ``socket.*`` ops, untimed ``lock.acquire()``, storage-hook appends)
  inside ``async def`` bodies or functions scheduled as loop
  callbacks;
- R12 a Future must be resolved on its creation loop or through a
  marshal seam: ``set_result``/``set_exception`` on a parked future
  is legal only under a get_loop()/loop-identity guard, from a
  callback that is itself marshaled, or on a future the same function
  created;
- R13 every spawned task holds a tracking binding or registers in a
  tracked set (the PR 15 per-shard establish-task shape;
  fire-and-forget tasks are GC'd mid-flight);
- R14 ``await``/blocking calls inside functions whose every call site
  sits under a held lock (the one-level R5 propagation applied to
  R1's check — suspension points under locks are findings, not
  folklore);
- R15 no implicit device->host syncs (``.item()``,
  ``jax.device_get``, ``np.asarray`` on ``*_dev``-named device
  arrays, ``float()``/``bool()``/``int()`` over them) inside
  ``mqtt_tpu/ops/`` and ``parallel/sharded.py`` outside blessed
  resolve seams; every intentional D2H point carries a reasoned
  pragma.

Honest limits (the runtime witness is the backstop): ownership is
inferred from the repo's own conventions (``*_dev`` device-array
names, ``fut``/``waiter`` future names, the ``net.loop`` attach
seam), so renamed state evades the static pass; R10's reachability is
the same Thread-target BFS as R2 (dynamically dispatched thread
entries need ``THREAD_ENTRY_EXTRA``); and R12's guard recognition is
lexical, not data-flow.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Iterator, Optional

from .core import FileCtx, Finding
from .rules import (
    _dotted,
    _is_blocking_call,
    _is_lock_expr,
    _iter_scope,
    _module_functions,
    _terminal_name,
    _thread_entries,
    _called_names,
    _funcs_called_only_under_locks,
)

# The blessed loop-affinity catalog: (kind, seam) pairs the runtime
# witness may legally observe. ``*_local`` seams are owner-loop
# touches; ``*_cross``/``*_marshal`` seams are foreign-context touches
# that are legal ONLY because the object is thread-safe by design or
# the touch rides a call_soon_threadsafe/run_coroutine_threadsafe
# marshal. A witnessed (kind, seam) missing from this table is a model
# gap (fix the table or the code, never the gate); a NEW kind is a
# design decision made here, in review, like a new LOCK_ORDER entry.
LOOP_AFFINITY = (
    # clients.OutboundQueue: thread-safe bounded deque; any thread may
    # enqueue, the single consumer (the client's write loop) dequeues
    # on the owning shard's loop
    ("outbound_queue", "put_local"),
    ("outbound_queue", "put_cross"),
    ("outbound_queue", "get_owner"),
    # per-client loop-affine state (QoS packet ids, inflight, outbound
    # aliases): mutated only on cl.net.loop; cross-shard deliveries
    # marshal through server._deliver_to_client
    ("client_state", "owner_touch"),
    ("client_state", "deliver_marshal"),
    # staging.MatchStage: _pending is lock-guarded; submitters wake the
    # stage loop via call_soon_threadsafe, futures resolve on their
    # creation loop through _resolve's marshal seam
    ("match_stage", "submit_local"),
    ("match_stage", "submit_cross"),
    ("match_stage", "resolve_local"),
    ("match_stage", "resolve_marshal"),
    ("match_stage", "drain_owner"),
    # cluster peer writers: frames marshal onto the cluster loop
    ("cluster_writer", "dispatch_local"),
    ("cluster_writer", "dispatch_cross"),
    # shards.LoopShard: establish tasks register in the shard's tracked
    # set (the R13 shape, witnessed)
    ("shard_task", "tracked"),
)

# kind -> the module that must host its marshal seam: a *_cross/_marshal
# seam for a kind whose home module has NO extracted
# call_soon_threadsafe/run_coroutine_threadsafe site would mean the
# witness observed a crossing the source cannot explain
AFFINITY_HOME = {
    "outbound_queue": "mqtt_tpu/clients.py",
    "client_state": "mqtt_tpu/server.py",
    "match_stage": "mqtt_tpu/staging.py",
    "cluster_writer": "mqtt_tpu/cluster.py",
    "shard_task": "mqtt_tpu/shards.py",
}

_MARSHAL_APIS = ("call_soon_threadsafe", "run_coroutine_threadsafe")

# loop-owning construct signatures: (class ctor | call attr) -> kind
_OWNER_CTORS = {
    "LoopShard": "shard_task",
    "MatchStage": "match_stage",
    "Cluster": "cluster_writer",
    "OutboundQueue": "outbound_queue",
}
_OWNER_ATTACH_RE = re.compile(r"^(net\.loop|_loop|loop)$")


@dataclass(frozen=True)
class SeamSite:
    path: str
    line: int
    context: str
    api: str  # which marshal API (or owner construct) anchors the site


class LoopGraph:
    """The extracted loop-affinity model: loop-owning construct sites,
    marshal-seam sites per module, and the blessed-catalog join."""

    def __init__(self) -> None:
        # kind -> definition sites (ctor/attach seams)
        self.owners: dict[str, list[SeamSite]] = {}
        # module rel -> marshal call sites
        self.marshals: dict[str, list[SeamSite]] = {}

    def add_owner(self, kind: str, site: SeamSite) -> None:
        sites = self.owners.setdefault(kind, [])
        if site not in sites:
            sites.append(site)

    def add_marshal(self, rel: str, site: SeamSite) -> None:
        sites = self.marshals.setdefault(rel, [])
        if site not in sites:
            sites.append(site)

    def seams(self) -> set[tuple[str, str]]:
        """The witness-comparable set: every blessed (kind, seam) whose
        requirements the extracted model satisfies — local/owner seams
        need the kind's owning construct extracted; cross/marshal seams
        additionally need a marshal call site in the kind's home
        module. A blessed pair whose evidence is missing is EXCLUDED,
        so a witnessed traversal of it fails the gate until the source
        really carries the seam."""
        out: set[tuple[str, str]] = set()
        for kind, seam in LOOP_AFFINITY:
            if kind not in self.owners:
                continue
            if seam.endswith(("_cross", "_marshal")):
                home = AFFINITY_HOME.get(kind)
                if home is None or not self.marshals.get(home):
                    continue
            out.add((kind, seam))
        return out

    def as_dict(self) -> dict:
        return {
            "affinity": [list(p) for p in LOOP_AFFINITY],
            "owners": {
                kind: [
                    {"path": s.path, "line": s.line, "context": s.context,
                     "api": s.api}
                    for s in sites
                ]
                for kind, sites in sorted(self.owners.items())
            },
            "marshals": {
                rel: [
                    {"line": s.line, "context": s.context, "api": s.api}
                    for s in sites
                ]
                for rel, sites in sorted(self.marshals.items())
            },
            "seams": sorted(list(p) for p in self.seams()),
        }

    def to_dot(self) -> str:
        """GraphViz rendering: one box per kind, one edge per blessed
        seam; cross seams whose marshal evidence is missing are red."""
        live = self.seams()
        lines = [
            "digraph loopaffinity {",
            '  rankdir="LR";',
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        kinds = sorted({k for k, _ in LOOP_AFFINITY})
        for kind in kinds:
            style = "" if kind in self.owners else ", style=dashed"
            lines.append(f'  "{kind}" [label="{kind}"{style}];')
        for kind, seam in LOOP_AFFINITY:
            attrs = [f'label="{seam}"']
            if (kind, seam) not in live:
                attrs.append('color="red"')
            src = "foreign" if seam.endswith(("_cross", "_marshal")) else kind
            if src == "foreign":
                lines.append(
                    f'  "foreign ctx" -> "{kind}" [{", ".join(attrs)}];'
                )
            else:
                lines.append(f'  "{kind}" -> "{kind}" [{", ".join(attrs)}];')
        lines.append("}")
        return "\n".join(lines) + "\n"


def extract_loop_graph(ctxs: list[FileCtx]) -> LoopGraph:
    """Extract (or reuse) the affinity model for this exact source set
    (same single-slot memo discipline as ``extract_lock_graph``: one
    CLI run extracts once for the rules and once for --loop-graph)."""
    key = tuple(sorted((c.rel, hash(c.source)) for c in ctxs))
    memo = getattr(extract_loop_graph, "_memo", None)
    if memo is not None and memo[0] == key:
        return memo[1]
    graph = LoopGraph()
    for ctx in ctxs:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Attribute)
                ):
                    # the attach seam: cl.net.loop = get_running_loop()
                    tgt = node.targets[0]
                    d = _dotted(tgt) or ""
                    leaf = ".".join(d.split(".")[-2:]) if "." in d else d
                    if _OWNER_ATTACH_RE.match(leaf) or _OWNER_ATTACH_RE.match(
                        tgt.attr
                    ):
                        val = node.value
                        vd = _dotted(val.func) if isinstance(val, ast.Call) else None
                        if vd is not None and vd.endswith("get_running_loop"):
                            graph.add_owner(
                                "client_state"
                                if "net" in d
                                else "match_stage"
                                if ctx.rel.endswith("staging.py")
                                else "cluster_writer"
                                if ctx.rel.endswith("cluster.py")
                                else "shard_task",
                                SeamSite(
                                    ctx.rel, node.lineno,
                                    ctx.context_line(node.lineno), "attach",
                                ),
                            )
                continue
            name = _terminal_name(node.func)
            if name in _OWNER_CTORS:
                graph.add_owner(
                    _OWNER_CTORS[name],
                    SeamSite(
                        ctx.rel, node.lineno,
                        ctx.context_line(node.lineno), name,
                    ),
                )
            elif name == "connect_accepted_socket":
                graph.add_owner(
                    "client_state",
                    SeamSite(
                        ctx.rel, node.lineno,
                        ctx.context_line(node.lineno), name,
                    ),
                )
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _MARSHAL_APIS
            ) or _dotted(node.func) in (
                "asyncio.run_coroutine_threadsafe",
            ):
                api = (
                    node.func.attr
                    if isinstance(node.func, ast.Attribute)
                    else "run_coroutine_threadsafe"
                )
                graph.add_marshal(
                    ctx.rel,
                    SeamSite(
                        ctx.rel, node.lineno,
                        ctx.context_line(node.lineno), api,
                    ),
                )
    extract_loop_graph._memo = (key, graph)  # type: ignore[attr-defined]
    return graph


# -- R10: foreign-thread mutation of loop-affine objects ---------------------

# receiver-name conventions marking loop-affine objects beyond R2's
# future/loop set: asyncio Events, tasks, stream writers and transports
_AFFINE_EVENT_RE = re.compile(r"(^|_)(event|wake|ready|done|stopped)$", re.I)
_AFFINE_TASK_RE = re.compile(r"(^|_)(task|tick)s?$|_task$", re.I)
_AFFINE_WRITER_RE = re.compile(r"(^|_)(writer|transport)$", re.I)


def _threading_constructed(tree: ast.Module) -> set[str]:
    """Terminal names assigned a ``threading.Event()`` (or bare
    ``Event()``) anywhere in the file: those are thread-safe by
    construction, so foreign-thread set()/clear() is the intended use,
    not an affinity violation."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        val = node.value
        if not isinstance(val, ast.Call):
            continue
        d = _dotted(val.func)
        if d in ("threading.Event", "Event") or (
            _terminal_name(val.func) == "Event"
        ):
            name = _terminal_name(node.targets[0])
            if name:
                out.add(name)
    return out


def _affine_mutation(call: ast.Call, threading_safe: set[str]) -> Optional[str]:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return None
    recv = _terminal_name(fn.value) or ""
    if recv in threading_safe:
        return None
    if fn.attr in ("set", "clear") and _AFFINE_EVENT_RE.search(recv):
        return f"{recv}.{fn.attr}"
    if fn.attr == "cancel" and _AFFINE_TASK_RE.search(recv):
        return f"{recv}.cancel"
    if fn.attr in ("write", "close", "drain") and _AFFINE_WRITER_RE.search(
        recv
    ):
        return f"{recv}.{fn.attr}"
    return None


def check_r10(ctx: FileCtx) -> list[Finding]:
    """Mutations of loop-affine objects from thread-reachable sync code
    must route via call_soon_threadsafe/run_coroutine_threadsafe. R2
    covers futures and the loop itself; R10 generalizes the one-loop
    model to the N-shard fabric's object kinds: asyncio Events, tasks,
    and stream writers/transports."""
    funcs = _module_functions(ctx.tree)
    entries = _thread_entries(ctx) & set(funcs)
    if not entries:
        return []
    reachable: set[str] = set()
    frontier = list(entries)
    while frontier:
        fn = frontier.pop()
        if fn in reachable:
            continue
        reachable.add(fn)
        for callee in _called_names(funcs[fn]):
            if callee in funcs and callee not in reachable:
                frontier.append(callee)
    threading_safe = _threading_constructed(ctx.tree)
    out = []
    for fname in sorted(reachable):
        node = funcs[fname]
        if isinstance(node, ast.AsyncFunctionDef):
            continue  # coroutines run on the loop, never as Thread targets
        for sub in _iter_scope(node.body):
            if not isinstance(sub, ast.Call):
                continue
            what = _affine_mutation(sub, threading_safe)
            if what is not None:
                out.append(
                    ctx.finding(
                        "R10", sub,
                        f"{what}() inside `{fname}`, reachable from a "
                        "thread entry point: loop-affine objects (events, "
                        "tasks, writers) owned by a shard loop must be "
                        "touched via loop.call_soon_threadsafe/"
                        "run_coroutine_threadsafe",
                    )
                )
    return out


# -- R11: blocking calls in async bodies / loop callbacks --------------------

_STORE_RECV_RE = re.compile(r"(^|_)(store|storage|kv|logkv)$", re.I)
_STORE_BLOCKING_ATTRS = {
    "append", "put", "delete", "sync", "snapshot", "compact", "fsync",
}
_LOOP_CB_SCHEDULERS = {
    "call_soon", "call_soon_threadsafe", "call_later", "call_at",
}


def _blocking_in_async(call: ast.Call) -> Optional[str]:
    what = _is_blocking_call(call)
    if what is not None:
        return what
    fn = call.func
    if isinstance(fn, ast.Attribute):
        recv = fn.value
        if fn.attr == "acquire" and _is_lock_expr(recv):
            # untimed/blocking acquire stalls the whole loop; a
            # non-blocking probe or timeout-bounded acquire passes
            blocking_false = any(
                (k.arg == "blocking" and isinstance(k.value, ast.Constant)
                 and k.value.value is False)
                for k in call.keywords
            ) or (
                call.args
                and isinstance(call.args[0], ast.Constant)
                and call.args[0].value is False
            )
            has_timeout = any(k.arg == "timeout" for k in call.keywords) or (
                len(call.args) >= 2
            )
            if not blocking_false and not has_timeout:
                return f"{_terminal_name(recv)}.acquire"
        if fn.attr in _STORE_BLOCKING_ATTRS and _STORE_RECV_RE.search(
            _terminal_name(recv) or ""
        ):
            # storage-hook appends hit the durability path (fsync under
            # durability_fsync=always): never inline on a loop
            return f"{_terminal_name(recv)}.{fn.attr}"
    return None


def _loop_callback_funcs(ctx: FileCtx) -> set[str]:
    """Names of same-file functions passed BY REFERENCE to
    call_soon/call_later/... — they execute as loop callbacks, so the
    async-context blocking rules apply to their sync bodies too."""
    out: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOOP_CB_SCHEDULERS
        ):
            continue
        for arg in node.args[:1]:  # the callback is the first argument
            name = _terminal_name(arg)
            if name:
                out.add(name)
    return out


def check_r11(ctx: FileCtx) -> list[Finding]:
    """No blocking calls inside ``async def`` bodies or functions
    scheduled as loop callbacks: one blocked coroutine stalls every
    connection that loop owns (under the shard fabric, a whole shard's
    worth)."""
    out = []
    cb_names = _loop_callback_funcs(ctx)
    scopes: list[tuple[list, str]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.AsyncFunctionDef):
            scopes.append((node.body, f"async {node.name}()"))
        elif isinstance(node, ast.FunctionDef) and node.name in cb_names:
            scopes.append((node.body, f"loop callback {node.name}()"))
    flagged: set[int] = set()
    for body, desc in scopes:
        for node in _iter_scope(body):
            if not isinstance(node, ast.Call) or id(node) in flagged:
                continue
            what = _blocking_in_async(node)
            if what is not None:
                flagged.add(id(node))
                out.append(
                    ctx.finding(
                        "R11", node,
                        f"blocking call {what}() inside {desc}: it stalls "
                        "the owning event loop (every connection on that "
                        "shard); run it in an executor or use the async "
                        "variant",
                    )
                )
    return out


# -- R12: future resolution loop discipline ----------------------------------

_FUT_NAME_RE = re.compile(r"(^|_)(fut|future|waiter)s?$|^f$", re.I)
_LOOPISH_RE = re.compile(r"(^|_)loop$|^running$", re.I)


def _has_loop_guard(fn_node: ast.AST) -> bool:
    """True when the function carries the marshal-seam guard shape: a
    ``.get_loop()`` call, or an ``is``/``is not`` comparison between
    two loop-named operands (``loop is self._loop``,
    ``loop is running``)."""
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get_loop"
            ):
                return True
        elif isinstance(node, ast.Compare):
            if not any(
                isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops
            ):
                continue
            operands = [node.left] + list(node.comparators)
            loopish = sum(
                1
                for o in operands
                if _LOOPISH_RE.search(_terminal_name(o) or "")
            )
            if loopish >= 2:
                return True
    return False


def _callback_referenced_funcs(ctx: FileCtx) -> set[str]:
    """Function/method names passed by reference (not called) anywhere
    in this file to a loop scheduler — their bodies run on the target
    loop, so resolving a future inside them IS the marshal seam."""
    out: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOOP_CB_SCHEDULERS
        ):
            continue
        for arg in node.args:
            name = _terminal_name(arg)
            if name:
                out.add(name)
    return out


def _creates_future_locally(fn_node: ast.AST) -> bool:
    for node in _iter_scope(list(getattr(fn_node, "body", []))):
        if isinstance(node, ast.Call):
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "create_future"
            ):
                return True
    return False


def check_r12(ctx: FileCtx) -> list[Finding]:
    """A Future must be resolved on its creation loop or through a
    marshal seam (the staging submit/resolve contract, checked
    statically): ``set_result``/``set_exception`` on a parked future
    from the wrong loop schedules its done-callbacks cross-thread. A
    resolution passes when its function (a) guards on loop identity
    (``fut.get_loop()`` / ``loop is self._loop``), (b) is itself
    marshaled (passed by reference to call_soon*/call_later), or (c)
    resolves a future it created in the same scope."""
    out = []
    marshaled = _callback_referenced_funcs(ctx)
    # nested defs: a closure defined inside a guarded/marshaling parent
    # inherits the seam (_resolve's `_set` shape)
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if (
            node.name in marshaled
            or _has_loop_guard(node)
            or _creates_future_locally(node)
        ):
            continue
        for sub in _iter_scope(node.body):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("set_result", "set_exception")
            ):
                continue
            recv = _terminal_name(fn.value) or ""
            if not _FUT_NAME_RE.search(recv):
                continue
            out.append(
                ctx.finding(
                    "R12", sub,
                    f"{recv}.{fn.attr}() in `{node.name}` without a loop "
                    "guard: a future parked by another loop's submitter "
                    "must resolve on ITS loop (compare fut.get_loop(), or "
                    "marshal via call_soon_threadsafe) — the staging "
                    "submit/resolve contract",
                )
            )
    return out


# -- R13: spawned tasks must be tracked --------------------------------------


def check_r13(ctx: FileCtx) -> list[Finding]:
    """Every spawned task holds a tracking binding or registers in a
    tracked set: asyncio keeps only a WEAK reference to running tasks,
    so a fire-and-forget ``create_task`` can be garbage-collected
    mid-flight (the PR 15 per-shard establish-task shape exists for
    exactly this)."""
    out = []
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        spawned = None
        if isinstance(fn, ast.Attribute) and fn.attr == "create_task":
            spawned = "create_task"
        elif _dotted(fn) in ("asyncio.ensure_future", "ensure_future"):
            spawned = "ensure_future"
        if spawned is None:
            continue
        parent = parents.get(node)
        tracked = False
        if isinstance(
            parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr, ast.Return,
                     ast.Await),
        ):
            tracked = True
        elif isinstance(parent, ast.Call):
            # shard.track(loop.create_task(...)), tasks.append(...),
            # gather(...), setattr(...) — any enclosing call holds a
            # reference the spawner can account for
            tracked = True
        elif isinstance(
            parent,
            (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.ListComp,
             ast.SetComp, ast.DictComp, ast.GeneratorExp),
        ):
            # the container (or the comprehension's result) holds the
            # reference; its own binding is the spawner's problem
            tracked = True
        if not tracked:
            out.append(
                ctx.finding(
                    "R13", node,
                    f"fire-and-forget {spawned}(): bind the task or "
                    "register it in a tracked set (asyncio holds only a "
                    "weak reference; an untracked task can be GC'd "
                    "mid-flight and its failures vanish)",
                )
            )
    return out


# -- R14: await/blocking under a lock, one call level deep -------------------


def check_r14(ctx: FileCtx) -> list[Finding]:
    """The one-level propagation of R1: a function whose EVERY call
    site sits under a held lock is itself a lock-held scope (the R5
    machinery), so ``await`` or a blocking call inside it suspends the
    loop while the lock pins every other holder — the same finding R1
    raises for the lexical case."""
    out = []
    funcs = _module_functions(ctx.tree)
    for name in sorted(_funcs_called_only_under_locks(ctx)):
        node = funcs[name]
        desc = f"{name}() [only ever called under a lock]"
        for sub in _iter_scope(node.body):
            if isinstance(sub, ast.Await):
                out.append(
                    ctx.finding(
                        "R14", sub,
                        f"`await` inside {desc}: the caller's lock is "
                        "held across the suspension point (R1, one call "
                        "level deep)",
                    )
                )
            elif isinstance(sub, ast.Call):
                what = _is_blocking_call(sub)
                if what is not None:
                    out.append(
                        ctx.finding(
                            "R14", sub,
                            f"blocking call {what}() inside {desc}: the "
                            "caller's lock is held across it (R1, one "
                            "call level deep)",
                        )
                    )
    return out


# -- R15: implicit device->host syncs on the device hot path -----------------

_R15_SCOPES = ("mqtt_tpu/ops/", "mqtt_tpu/parallel/sharded.py")
_DEV_NAME_RE = re.compile(r"(_dev|_device)$|^dev_", re.I)
_HOST_CASTS = {"float", "bool", "int"}


def _is_dev_expr(node: ast.AST) -> bool:
    """Heuristic: the repo names device-resident arrays ``*_dev`` (the
    matcher/predicates/recrypt convention); a Subscript/Attribute/Call
    chain rooted at one stays device-resident."""
    while isinstance(node, (ast.Subscript, ast.Attribute, ast.Call)):
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Attribute):
            node = node.value
        else:
            node = node.func
    name = _terminal_name(node)
    return name is not None and bool(_DEV_NAME_RE.search(name))


def check_r15(ctx: FileCtx) -> list[Finding]:
    """No implicit device->host syncs inside the device hot path
    (``mqtt_tpu/ops/``, ``parallel/sharded.py``): ``.item()``,
    ``jax.device_get``, ``np.asarray`` over a device array, and host
    casts (``float``/``bool``/``int``) over one each force a blocking
    transfer that serializes the dispatch pipeline — the static
    complement to the PR 18 compile ledger. Intentional resolve seams
    (the ONE-D2H batched reads) carry reasoned pragmas."""
    if not any(
        ctx.rel.startswith(p) or ctx.rel == p.rstrip("/") for p in _R15_SCOPES
    ):
        return []
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "item" and not node.args:
            out.append(
                ctx.finding(
                    "R15", node,
                    ".item() is an implicit blocking device->host sync; "
                    "batch the read through one np.asarray at a blessed "
                    "resolve seam (reasoned pragma if intentional)",
                )
            )
            continue
        d = _dotted(fn)
        if d in ("jax.device_get",):
            out.append(
                ctx.finding(
                    "R15", node,
                    "jax.device_get() blocks on the transfer; prefer "
                    "copy_to_host_async + one np.asarray at the resolve "
                    "seam",
                )
            )
            continue
        if (
            d in ("np.asarray", "numpy.asarray")
            and node.args
            and _is_dev_expr(node.args[0])
        ):
            out.append(
                ctx.finding(
                    "R15", node,
                    "np.asarray over a device array is a blocking D2H "
                    "sync; blessed resolve seams carry a reasoned pragma "
                    "naming the ONE transfer they batch",
                )
            )
            continue
        if (
            isinstance(fn, ast.Name)
            and fn.id in _HOST_CASTS
            and len(node.args) == 1
            and _is_dev_expr(node.args[0])
        ):
            out.append(
                ctx.finding(
                    "R15", node,
                    f"{fn.id}() over a device array forces an implicit "
                    "per-element D2H sync; resolve the batch once and "
                    "cast on the host",
                )
            )
    return out
