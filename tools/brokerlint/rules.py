"""brokerlint rules: the concurrency/invariant contract this codebase
has repeatedly hand-fixed in review, encoded as ~8 AST checks.

Each rule exists because the defect class it catches has actually
shipped (or nearly shipped) in this repo — see README.md "Static
analysis" for the incident each rule encodes. The rules are heuristic
by design: they key on the repo's own conventions (``*_lock``
attribute names, ``*_locked`` method-name suffix meaning "caller holds
the lock", the ``mqtt_tpu_`` metric prefix) rather than attempting
whole-program analysis. Genuine exceptions carry an inline
``# brokerlint: ok=<RULE> <reason>`` pragma, so every suppression is a
documented decision at the site it covers.

Rule index
----------

- R1  no blocking calls / ``await`` under a held lock
- R2  thread-reachable code must not touch the event loop directly
- R3  no wall-clock ``time.time()`` (monotonic/perf_counter only)
- R4  no silent exception swallows (``except Exception: pass``)
- R5  no observer/hook callback invocation under a held lock
- R6  metric names: catalog drift + Prometheus naming scheme
- R7  every ``threading.Thread`` is explicit about ``daemon=`` and is
      bound somewhere it can be tracked/joined
- R8  no mutable default args; no module-level mutable-container
      singletons
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Iterator, Optional

from .core import FileCtx, Finding

# -- shared helpers ---------------------------------------------------------

_LOCK_NAME_RE = re.compile(r"(^|_)(lock|mutex)$", re.IGNORECASE)

# functions whose NAME declares "caller holds the lock" — the repo's
# convention (_trip_locked, _transition_locked); their whole body is
# treated as a lock-held scope
_LOCKED_SUFFIX = "_locked"


def _terminal_name(node: ast.AST) -> Optional[str]:
    """The final identifier of a Name/Attribute chain (``self._lock`` ->
    ``_lock``), or None."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_expr(node: ast.AST) -> bool:
    name = _terminal_name(node)
    return name is not None and bool(_LOCK_NAME_RE.search(name))


def _iter_scope(body: list) -> Iterator[ast.AST]:
    """Walk statements without descending into nested function/class
    definitions or lambdas — their bodies execute later, outside the
    enclosing lock scope. The prune happens at pop so a ``def`` sitting
    DIRECTLY in ``body`` (a callback defined inside a ``with`` block)
    is skipped exactly like one nested deeper."""
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _lock_scopes(ctx: FileCtx) -> Iterator[tuple[list, str]]:
    """Yield ``(body, lock_desc)`` for every lock-held scope: the body of
    each synchronous ``with <lock>:`` and the whole body of every
    ``*_locked`` function."""
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.With):
            for item in node.items:
                if _is_lock_expr(item.context_expr):
                    yield node.body, _dotted(item.context_expr) or "lock"
                    break
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.endswith(_LOCKED_SUFFIX):
                yield node.body, f"{node.name}() [caller-held lock]"


# -- R1: blocking calls under a held lock -----------------------------------

_BLOCKING_DOTTED = {
    "time.sleep",
    "tempfile.mkdtemp",
    "tempfile.mkstemp",
    "os.makedirs",
    "os.replace",
    "os.unlink",
    "os.remove",
    "os.rename",
    "os.fsync",
    "shutil.rmtree",
    "shutil.copy",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.Popen",
    "json.dump",
    "json.load",
    "socket.create_connection",
}
_BLOCKING_BARE = {"open", "sleep"}
_BLOCKING_METHODS = {"recv", "recv_into", "sendall", "accept"}
_THREADISH_RE = re.compile(r"thread|worker|writer|proc|^t$|^w$", re.IGNORECASE)


def _is_blocking_call(call: ast.Call) -> Optional[str]:
    fn = call.func
    dotted = _dotted(fn)
    if dotted is not None and dotted in _BLOCKING_DOTTED:
        return dotted
    if isinstance(fn, ast.Name) and fn.id in _BLOCKING_BARE:
        return fn.id
    if isinstance(fn, ast.Attribute):
        if fn.attr in _BLOCKING_METHODS:
            return f"<sock>.{fn.attr}"
        if fn.attr == "join" and not isinstance(fn.value, ast.Constant):
            recv = _terminal_name(fn.value) or ""
            has_timeout = any(k.arg == "timeout" for k in call.keywords)
            if not call.args and not call.keywords:
                return f"{recv}.join"
            if has_timeout or _THREADISH_RE.search(recv):
                return f"{recv}.join"
    return None


def check_r1(ctx: FileCtx) -> list[Finding]:
    out = []
    flagged: set[int] = set()  # a node in nested lock scopes flags once
    for body, desc in _lock_scopes(ctx):
        for node in _iter_scope(body):
            if id(node) in flagged:
                continue
            if isinstance(node, ast.Await):
                out.append(
                    ctx.finding(
                        "R1", node,
                        f"`await` while holding {desc}: the lock pins the "
                        "event loop for every other holder",
                    )
                )
            elif isinstance(node, ast.Call):
                what = _is_blocking_call(node)
                if what is not None:
                    out.append(
                        ctx.finding(
                            "R1", node,
                            f"blocking call {what}() while holding {desc}; "
                            "move the I/O outside the critical section",
                        )
                    )
    return out


# -- R2: thread-reachable code touching the event loop ----------------------

# extra thread entry points the AST cannot see (queue-dispatched workers,
# executor targets resolved dynamically); keyed on the file's basename
THREAD_ENTRY_EXTRA: dict[str, set[str]] = {}

_LOOP_MUTATORS = {
    "call_soon",
    "call_later",
    "call_at",
    "create_task",
    "set_result",
    "set_exception",
}


def _module_functions(tree: ast.Module) -> dict[str, ast.AST]:
    out: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.setdefault(node.name, node)
    return out


def _thread_entries(ctx: FileCtx) -> set[str]:
    entries = set(THREAD_ENTRY_EXTRA.get(os.path.basename(ctx.rel), ()))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted not in ("threading.Thread", "Thread"):
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                name = _terminal_name(kw.value)
                if name:
                    entries.add(name)
    return entries


def _called_names(fn_node: ast.AST) -> set[str]:
    names: set[str] = set()
    for node in _iter_scope(getattr(fn_node, "body", [])):
        if isinstance(node, ast.Call):
            name = _terminal_name(node.func)
            if name:
                names.add(name)
    return names


def check_r2(ctx: FileCtx) -> list[Finding]:
    funcs = _module_functions(ctx.tree)
    entries = _thread_entries(ctx) & set(funcs)
    if not entries:
        return []
    # NOTE deliberately no exemption for functions passed to
    # call_soon_threadsafe: being *scheduled* onto the loop never puts a
    # function in `reachable` (an argument reference is not a call), and
    # a function a thread ALSO calls directly is exactly the
    # partial-fix shape this rule exists to catch
    reachable: set[str] = set()
    frontier = list(entries)
    while frontier:
        fn = frontier.pop()
        if fn in reachable:
            continue
        reachable.add(fn)
        for callee in _called_names(funcs[fn]):
            if callee in funcs and callee not in reachable:
                frontier.append(callee)
    out = []
    for fn in sorted(reachable):
        node = funcs[fn]
        if isinstance(node, ast.AsyncFunctionDef):
            continue  # coroutines run on the loop; Thread targets cannot be
        for sub in _iter_scope(node.body):
            if not isinstance(sub, ast.Call):
                continue
            attr = (
                sub.func.attr if isinstance(sub.func, ast.Attribute) else None
            )
            if attr in _LOOP_MUTATORS:
                out.append(
                    ctx.finding(
                        "R2", sub,
                        f"{attr}() inside `{fn}`, which is reachable from a "
                        "thread entry point; cross-thread loop wakes must go "
                        "through loop.call_soon_threadsafe",
                    )
                )
            elif _dotted(sub.func) in ("asyncio.ensure_future",):
                out.append(
                    ctx.finding(
                        "R2", sub,
                        f"asyncio.ensure_future() inside thread-reachable "
                        f"`{fn}`; use run_coroutine_threadsafe",
                    )
                )
    return out


# -- R3: wall-clock time in timing code -------------------------------------


def check_r3(ctx: FileCtx) -> list[Finding]:
    out = []
    # `from time import time` would hide the call behind a bare name
    bare_time = any(
        isinstance(n, ast.ImportFrom)
        and n.module == "time"
        and any(a.name == "time" for a in n.names)
        for n in ast.walk(ctx.tree)
    )
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        hit = dotted == "time.time" or (
            bare_time and isinstance(node.func, ast.Name)
            and node.func.id == "time"
        )
        if hit:
            out.append(
                ctx.finding(
                    "R3", node,
                    "wall-clock time.time(): latency/uptime/deadline math "
                    "must use time.monotonic()/perf_counter() (NTP steps "
                    "bend wall time); genuine wall-time uses take a "
                    "reasoned pragma",
                )
            )
    return out


# -- R4: silent exception swallows ------------------------------------------


def _body_is_silent(body: list) -> bool:
    """True when a handler body does nothing observable: only pass /
    continue / docstring."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue  # docstring/ellipsis
        return False
    return True


def check_r4(ctx: FileCtx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(
                ctx.finding(
                    "R4", node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt "
                    "too; name the exceptions",
                )
            )
            continue
        names = []
        types = (
            node.type.elts if isinstance(node.type, ast.Tuple) else [node.type]
        )
        for t in types:
            name = _terminal_name(t)
            if name:
                names.append(name)
        if any(n in ("Exception", "BaseException") for n in names):
            if _body_is_silent(node.body):
                out.append(
                    ctx.finding(
                        "R4", node,
                        "`except Exception` swallowed without a counter or "
                        "log; count it, log it, or pragma it with the reason "
                        "it is safe to drop",
                    )
                )
    return out


# -- R5: observer/hook callbacks invoked under a held lock ------------------

_OBSERVER_ATTR_RE = re.compile(
    r"^on_[a-z_]+$|(_observer|_callback|_hook|_listener)s?$"
)
_OBSERVER_CONTAINER_RE = re.compile(r"(_observers|_callbacks|_hooks|_listeners)$")


def _observer_locals(body: list) -> set[str]:
    """Names bound (in this scope) from observer-ish attributes or by
    iterating an observer container: ``cb = self.on_trip`` /
    ``for fn in self._observers``."""
    out: set[str] = set()
    for node in _iter_scope(body):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Attribute):
            if _OBSERVER_ATTR_RE.search(node.value.attr):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        elif isinstance(node, ast.For):
            src = _terminal_name(node.iter)
            if src and _OBSERVER_CONTAINER_RE.search(src):
                if isinstance(node.target, ast.Name):
                    out.add(node.target.id)
    return out


def _funcs_called_only_under_locks(ctx: FileCtx) -> set[str]:
    """One level of interprocedural propagation: a module function whose
    every same-module call site sits inside a lock scope is itself a
    lock-held scope (the trie's ``_notify`` pattern)."""
    funcs = _module_functions(ctx.tree)
    lock_bodies = [body for body, _ in _lock_scopes(ctx)]

    def calls_in(nodes: Iterator[ast.AST]) -> set[str]:
        out = set()
        for node in nodes:
            if isinstance(node, ast.Call):
                name = _terminal_name(node.func)
                if name in funcs:
                    out.add(name)
        return out

    in_lock: set[str] = set()
    for body in lock_bodies:
        in_lock |= calls_in(_iter_scope(body))
    # called at least once, and only ever from lock scopes
    out = set()
    for name in in_lock:
        total = sum(
            1
            for node in ast.walk(ctx.tree)
            if isinstance(node, ast.Call) and _terminal_name(node.func) == name
        )
        locked = sum(
            1
            for body in lock_bodies
            for node in _iter_scope(body)
            if isinstance(node, ast.Call) and _terminal_name(node.func) == name
        )
        if total and total == locked:
            out.add(name)
    return out


def check_r5(ctx: FileCtx) -> list[Finding]:
    out = []
    scopes = [(body, desc) for body, desc in _lock_scopes(ctx)]
    for name in _funcs_called_only_under_locks(ctx):
        node = _module_functions(ctx.tree)[name]
        scopes.append(
            (node.body, f"{name}() [only ever called under a lock]")
        )
    for body, desc in scopes:
        local_cbs = _observer_locals(body)
        for node in _iter_scope(body):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and _OBSERVER_ATTR_RE.search(fn.attr):
                out.append(
                    ctx.finding(
                        "R5", node,
                        f"observer callback `{fn.attr}` invoked while "
                        f"holding {desc}; capture it under the lock, call "
                        "it after release (a re-registering or slow "
                        "observer deadlocks/stalls every other holder)",
                    )
                )
            elif isinstance(fn, ast.Name) and fn.id in local_cbs:
                out.append(
                    ctx.finding(
                        "R5", node,
                        f"observer callback `{fn.id}` invoked while holding "
                        f"{desc}; call it after the lock is released",
                    )
                )
    return out


# -- R6: metric-name catalog drift + naming scheme (project rule) -----------

_METRIC_PREFIX = "mqtt_tpu_"
_METRIC_NAME_RE = re.compile(r"^mqtt_tpu_[a-z][a-z0-9_]*$")
_METRIC_FACTORIES = {"counter", "gauge", "histogram"}


def _factory_calls(tree: ast.Module) -> Iterator[ast.Call]:
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_FACTORIES
            and node.args
        ):
            yield node


def _code_metrics(ctxs: list[FileCtx]) -> list[tuple[FileCtx, ast.Call, str, str]]:
    """Every metric name the code registers, as ``(ctx, call-node, kind,
    name)``. Covers the direct literal form and the repo's loop form::

        for name, attr in (("mqtt_tpu_x", "x"), ...):
            r.counter(name, ...)
    """
    out = []
    for ctx in ctxs:
        for node in _factory_calls(ctx.tree):
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value.startswith(_METRIC_PREFIX):
                    out.append((ctx, node, node.func.attr, arg.value))
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, ast.For):
                continue
            # which loop-target name feeds a factory's first argument?
            tgt_names = []
            if isinstance(loop.target, ast.Name):
                tgt_names = [(loop.target.id, None)]
            elif isinstance(loop.target, ast.Tuple):
                tgt_names = [
                    (e.id, i)
                    for i, e in enumerate(loop.target.elts)
                    if isinstance(e, ast.Name)
                ]
            for call in _factory_calls(ast.Module(body=loop.body, type_ignores=[])):
                arg = call.args[0]
                if not isinstance(arg, ast.Name):
                    continue
                idx = next(
                    (i for n, i in tgt_names if n == arg.id), "missing"
                )
                if idx == "missing":
                    continue
                if not isinstance(loop.iter, (ast.Tuple, ast.List)):
                    continue
                for item in loop.iter.elts:
                    elem = item
                    if idx is not None:
                        if not isinstance(item, (ast.Tuple, ast.List)):
                            continue
                        if idx >= len(item.elts):
                            continue
                        elem = item.elts[idx]
                    if isinstance(elem, ast.Constant) and isinstance(
                        elem.value, str
                    ) and elem.value.startswith(_METRIC_PREFIX):
                        out.append((ctx, call, call.func.attr, elem.value))
    return out


def _catalog_patterns(root: str) -> Optional[set[str]]:
    """Metric names/globs from the README catalog table: every backticked
    token in the lines between the "Metrics catalog" heading and the next
    blank-after-table. Returns None when the catalog cannot be found."""
    readme = os.path.join(root, "README.md")
    if not os.path.exists(readme):
        return None
    with open(readme, encoding="utf-8") as f:
        text = f.read()
    m = re.search(r"Metrics catalog[^\n]*\n\s*\n?((?:\|[^\n]*\n)+)", text)
    if m is None:
        return None
    pats: set[str] = set()
    for row in m.group(1).splitlines():
        cells = row.split("|")
        if len(cells) < 2:
            continue
        first = cells[1]
        for tok in re.findall(r"`([^`]+)`", first):
            tok = tok.strip()
            if not tok or tok in ("name",):
                continue
            if not tok.startswith(_METRIC_PREFIX):
                tok = _METRIC_PREFIX + tok
            pats.add(tok)
    return pats or None


def check_r6(ctxs: list[FileCtx], root: str) -> list[Finding]:
    metrics = _code_metrics(ctxs)
    out: list[Finding] = []
    for ctx, node, kind, name in metrics:
        if not _METRIC_NAME_RE.match(name):
            out.append(
                ctx.finding(
                    "R6", node,
                    f"metric {name!r} violates the naming scheme "
                    "(^mqtt_tpu_[a-z][a-z0-9_]*$)",
                )
            )
            continue
        if kind == "counter" and not name.endswith("_total"):
            out.append(
                ctx.finding(
                    "R6", node,
                    f"counter {name!r} must end in `_total` (Prometheus "
                    "counter convention)",
                )
            )
        elif kind == "histogram" and not name.endswith(
            ("_seconds", "_ratio", "_bytes")
        ):
            out.append(
                ctx.finding(
                    "R6", node,
                    f"histogram {name!r} must carry a unit suffix "
                    "(_seconds/_ratio/_bytes)",
                )
            )
        elif kind == "gauge" and name.endswith("_total"):
            out.append(
                ctx.finding(
                    "R6", node,
                    f"gauge {name!r} must not end in `_total` (reads as a "
                    "counter on the scrape side)",
                )
            )
    pats = _catalog_patterns(root)
    if pats is None:
        if metrics:
            ctx = metrics[0][0]
            out.append(
                Finding(
                    "R6", "README.md", 1, 0,
                    "README metrics catalog not found (expected a table "
                    "under a 'Metrics catalog' heading)", "",
                )
            )
        return out
    code_names = {name for _, _, _, name in metrics}
    for ctx, node, _, name in metrics:
        if not any(fnmatch.fnmatchcase(name, p) for p in pats):
            out.append(
                ctx.finding(
                    "R6", node,
                    f"metric {name!r} missing from the README metrics "
                    "catalog (doc drift)",
                )
            )
    # the reverse direction covers globs too: a catalog row (literal or
    # wildcard) that no registered metric matches is stale documentation
    for p in sorted(pats):
        if not any(fnmatch.fnmatchcase(n, p) for n in code_names):
            out.append(
                Finding(
                    "R6", "README.md", 1, 0,
                    f"catalog lists {p!r} but no code registers a "
                    "matching metric (doc drift)", "",
                )
            )
    return out


# -- R7: threads without explicit daemon= / any tracking binding ------------


def _thread_calls(ctx: FileCtx) -> Iterator[ast.Call]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            if _dotted(node.func) in ("threading.Thread", "Thread"):
                yield node


def check_r7(ctx: FileCtx) -> list[Finding]:
    out = []
    # map each Thread(...) call to its parent statement context
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    for call in _thread_calls(ctx):
        if not any(k.arg == "daemon" for k in call.keywords):
            out.append(
                ctx.finding(
                    "R7", call,
                    "threading.Thread without explicit daemon=: decide "
                    "whether interpreter exit may abandon this thread",
                )
            )
        parent = parents.get(call)
        tracked = False
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
            tracked = True
        elif isinstance(parent, ast.Call):
            fn = parent.func
            if isinstance(fn, ast.Attribute) and fn.attr in ("append", "add"):
                tracked = True
        elif isinstance(parent, ast.Attribute):
            # threading.Thread(...).start() — constructed and dropped
            tracked = False
        if not tracked:
            out.append(
                ctx.finding(
                    "R7", call,
                    "thread constructed without a binding (no join/tracking "
                    "path); assign it so shutdown can account for it",
                )
            )
    return out


# -- R8: mutable defaults / module-level mutable singletons -----------------


def _is_mutable_literal(node: ast.AST, empty_only: bool) -> bool:
    if isinstance(node, (ast.List, ast.Set)):
        return not node.elts if empty_only else True
    if isinstance(node, ast.Dict):
        return not node.keys if empty_only else True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        if node.func.id in ("list", "dict", "set", "deque", "defaultdict"):
            return not node.args and not node.keywords if empty_only else True
    return False


def check_r8(ctx: FileCtx) -> list[Finding]:
    out = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None
            ]:
                if _is_mutable_literal(default, empty_only=False):
                    out.append(
                        ctx.finding(
                            "R8", default,
                            f"mutable default argument in {node.name}(): "
                            "shared across every call; default to None",
                        )
                    )
    for stmt in ctx.tree.body:
        targets: list = []
        value = None
        if isinstance(stmt, ast.Assign):
            targets, value = stmt.targets, stmt.value
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets, value = [stmt.target], stmt.value
        if value is None or not _is_mutable_literal(value, empty_only=True):
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if names:
            out.append(
                ctx.finding(
                    "R8", stmt,
                    f"module-level mutable singleton {names[0]!r}: shared "
                    "unlocked state across every importer/thread; scope it "
                    "to an owner object",
                )
            )
    return out


def _loop_rule(name: str):
    # late import: loopgraph imports helpers from this module
    def run(ctx: FileCtx) -> list[Finding]:
        from . import loopgraph

        return getattr(loopgraph, name)(ctx)

    run.__name__ = name
    return run


FILE_RULES = {
    "R1": check_r1,
    "R2": check_r2,
    "R3": check_r3,
    "R4": check_r4,
    "R5": check_r5,
    "R7": check_r7,
    "R8": check_r8,
    "R10": _loop_rule("check_r10"),
    "R11": _loop_rule("check_r11"),
    "R12": _loop_rule("check_r12"),
    "R13": _loop_rule("check_r13"),
    "R14": _loop_rule("check_r14"),
    "R15": _loop_rule("check_r15"),
}

def _check_r9(ctxs: list[FileCtx], root: str) -> list[Finding]:
    # late import: lockgraph imports helpers from this module
    from .lockgraph import check_r9

    return check_r9(ctxs, root)


PROJECT_RULES = {
    "R6": check_r6,
    "R9": _check_r9,
}

RULE_DOC = {
    "R1": "no blocking I/O or await while holding a lock",
    "R2": "thread-reachable code must use call_soon_threadsafe",
    "R3": "no wall-clock time.time() in timing code",
    "R4": "no silent exception swallows",
    "R5": "no observer callbacks invoked under a held lock",
    "R6": "metric names: README catalog sync + naming scheme",
    "R7": "threads: explicit daemon= and a tracking binding",
    "R8": "no mutable default args / module-level mutable singletons",
    "R9": "lock-order graph: acyclic and consistent with LOCK_ORDER",
    "R10": "loop-affine objects: foreign threads marshal via "
           "call_soon_threadsafe (N-shard generalization of R2)",
    "R11": "no blocking calls inside async bodies or loop callbacks",
    "R12": "futures resolve on their creation loop or via a marshal seam",
    "R13": "every spawned task is bound or registered in a tracked set",
    "R14": "no await/blocking calls in functions only called under locks",
    "R15": "no implicit device->host syncs on the device hot path",
}
