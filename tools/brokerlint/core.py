"""brokerlint core: file loading, pragma parsing, baseline handling, and
the runner that applies every registered rule.

The tool is a repo-specific concurrency/invariant linter (see
tools/brokerlint/rules.py for the rule catalog and README.md "Static
analysis" for the rationale behind each rule). It is deliberately
dependency-free — stdlib ``ast`` only — so it runs in every environment
the broker itself runs in, including the tier-1 CI gate.

Suppression pragma
------------------

A finding is suppressed by an explicit, *reasoned* pragma on the
offending line (or the line directly above it)::

    now = int(time.time())  # brokerlint: ok=R3 wall-clock expiry stamp

    # brokerlint: ok=R1,R4 teardown path; the transport is already gone
    sock.close()

The reason text is mandatory: a pragma without one is itself reported
(rule ``PRAGMA``), so every grandfathered decision is documented where
it lives. ``ok=*`` suppresses every rule on that line (reserved for
generated code; avoid).

Baseline
--------

``baseline.json`` holds grandfathered findings keyed on
``(rule, path, stripped source line)`` — line numbers churn, source
lines rarely do. The checked-in baseline is EMPTY and the CI gate keeps
it that way: new violations fail the build, they do not get baselined.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tokenize
from dataclasses import dataclass
from io import StringIO
from typing import Callable, Iterable, Optional

PRAGMA_RE = re.compile(r"#\s*brokerlint:\s*ok=([A-Z0-9*,]+)\s*(.*)$")


@dataclass(frozen=True)
class Finding:
    """One lint violation: rule id, location, message, and the stripped
    source line (the baseline key)."""

    rule: str
    path: str
    line: int
    col: int
    msg: str
    context: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.msg}"

    def baseline_key(self) -> tuple:
        return (self.rule, self.path, self.context)


class FileCtx:
    """One parsed source file plus its pragma map."""

    def __init__(self, path: str, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line -> set of rule ids allowed there ("*" = all)
        self.allows: dict[int, set[str]] = {}
        # pragma lines missing a reason (reported by the runner)
        self.bad_pragmas: list[int] = []
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        try:
            tokens = tokenize.generate_tokens(StringIO(self.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m is None:
                    continue
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                if not m.group(2).strip():
                    self.bad_pragmas.append(tok.start[0])
                    continue
                self.allows.setdefault(tok.start[0], set()).update(rules)
        except tokenize.TokenError:  # unterminated strings etc: no pragmas
            pass

    def allowed(self, rule: str, line: int) -> bool:
        """True when a pragma on this line (or the line above — for
        statements whose pragma sits on its own comment line) covers
        ``rule``."""
        for ln in (line, line - 1):
            rules = self.allows.get(ln)
            if rules and (rule in rules or "*" in rules):
                return True
        return False

    def context_line(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, msg: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.rel, line, col, msg, self.context_line(line))


def collect_files(paths: Iterable[str], root: str) -> list[str]:
    """Expand the CLI paths into a sorted .py file list (skips caches and
    the checked-in test fixture trees)."""
    out: set[str] = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isfile(ap) and ap.endswith(".py"):
            out.add(ap)
            continue
        for dirpath, dirnames, filenames in os.walk(ap):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in filenames:
                if fn.endswith(".py"):
                    out.add(os.path.join(dirpath, fn))
    return sorted(out)


def load_ctx(path: str, root: str) -> FileCtx:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    rel = os.path.relpath(path, root)
    return FileCtx(path, rel, source)


def load_baseline(path: str) -> set[tuple]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {
        (e["rule"], e["path"], e["context"]) for e in data.get("findings", [])
    }


def save_baseline(path: str, findings: list[Finding]) -> None:
    data = {
        "comment": (
            "Grandfathered brokerlint findings. The target state is an "
            "EMPTY list: fix violations instead of baselining them."
        ),
        "findings": [
            {"rule": f.rule, "path": f.path, "context": f.context}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1)
        f.write("\n")


def run(
    paths: Iterable[str],
    root: str,
    file_rules: dict[str, Callable[[FileCtx], list[Finding]]],
    project_rules: dict[str, Callable[[list[FileCtx], str], list[Finding]]],
    baseline: Optional[set] = None,
) -> tuple[list[Finding], list[Finding]]:
    """Apply every rule to every file. Returns ``(new, baselined)``:
    findings not covered / covered by the baseline. Pragma-suppressed
    findings are dropped entirely; a pragma without a reason is itself a
    finding."""
    ctxs: list[FileCtx] = []
    findings: list[Finding] = []
    for path in collect_files(paths, root):
        try:
            ctx = load_ctx(path, root)
        except SyntaxError as e:
            findings.append(
                Finding("PARSE", os.path.relpath(path, root),
                        e.lineno or 1, 0, f"syntax error: {e.msg}", "")
            )
            continue
        ctxs.append(ctx)
        for ln in ctx.bad_pragmas:
            findings.append(
                Finding("PRAGMA", ctx.rel, ln, 0,
                        "suppression pragma without a reason "
                        "(write `# brokerlint: ok=<RULES> <why>`)",
                        ctx.context_line(ln))
            )
        for rule_id, fn in file_rules.items():
            for f in fn(ctx):
                if not ctx.allowed(f.rule, f.line):
                    findings.append(f)
    for rule_id, fn in project_rules.items():
        for f in fn(ctxs, root):
            ctx = next((c for c in ctxs if c.rel == f.path), None)
            if ctx is None or not ctx.allowed(f.rule, f.line):
                findings.append(f)
    # dedupe exact repeats (msg included: one node CAN carry two distinct
    # violations of the same rule — e.g. R7's daemon= and binding checks)
    seen: set[tuple] = set()
    uniq: list[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.line, f.col, f.msg)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    base = baseline or set()
    new = [f for f in uniq if f.baseline_key() not in base]
    old = [f for f in uniq if f.baseline_key() in base]
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return new, old
