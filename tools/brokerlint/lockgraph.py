"""Whole-program lock-acquisition-order graph extraction (ISSUE 10).

The broker is a dozen named locks (``mqtt_tpu/utils/locked.py``
``LOCK_NAMES``) plus a constellation of anonymous ``threading.Lock``s,
and PRs 1-9 each shipped at least one review-caught lock bug. This
module turns "we hope the acquisition order is consistent" into a
checked property:

- every lock DEFINITION is resolved to a canonical name: the
  ``InstrumentedLock("name")`` literal, the ``LockedMap(name=...)`` /
  ``PacketStore(name="retained")`` family (name kwarg, including
  ``super().__init__(name=...)`` in subclasses), parameter-named locks
  (``TopicsIndex(lock_name=...)`` resolves to the default PLUS every
  call-site override), and raw ``threading.Lock()`` attributes, which
  get stable anonymous names like ``ops/delta.py:DeltaMatcher._lock``;
- every lock-held SCOPE is walked for nested acquisitions: lexical
  ``with a: with b:`` nesting, the ``*_locked``-suffix convention
  (the whole body runs under the class's ``_lock``), and ONE level of
  call propagation — ``self.m()``, same-module ``f()``, and
  ``self.attr.m()`` where ``attr``'s class is known from a constructor
  assignment or an annotated ``__init__`` parameter (the existing R5
  machinery, grown cross-module through attribute types);
- the resulting directed graph (edge = "held src, acquired dst") is
  checked against the blessed total order ``LOCK_ORDER`` below and for
  cycles; violations surface as rule R9 findings through the normal
  brokerlint pragma/baseline workflow, anchored at the acquisition (or
  call) site so a reasoned ``# brokerlint: ok=R9 why`` documents every
  deliberate exception where it lives.

The runtime half lives in ``mqtt_tpu/utils/locked.py``
(``LockWitness``): the tier-1 gate asserts every edge the witness
observes across the suite appears in this statically extracted graph,
so an extraction gap here fails loudly instead of rotting silently.

Known honest limits (the witness gate is the backstop for all of
them): callbacks registered under one lock and fired under another ARE
followed one level — the ISSUE 10 residual — but only for statically
resolvable targets through the observer shapes (``obj.on_x =
self.meth``/``= module_fn``, ``*_observers.append(fn)``) fired as
``recv.on_x(...)`` or ``recv._observers[k](...)``; a lambda, a foreign
bound method, or a fire through a loop variable (``for cb in
self._observers: cb()``) stays invisible to the static pass (R5
independently flags that fire shape under a held lock); locals
(``task = self._tasks[k]; task._lock``) resolve
to a per-site anonymous node unless the attribute name is unique
project-wide; propagation is one call level deep; and cross-module
NAME-based class resolution (base classes, annotated attribute types)
prefers a same-file definition, else the first-indexed one — every
class BODY is always scanned under its own file's definition, but an
ambiguous cross-module reference may resolve to the wrong namesake.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterable, Optional

from .core import FileCtx, Finding
from .rules import (
    _LOCK_NAME_RE,
    _LOCKED_SUFFIX,
    _OBSERVER_ATTR_RE,
    _OBSERVER_CONTAINER_RE,
    _dotted,
    _iter_scope,
    _terminal_name,
)

# The blessed whole-program acquisition order, OUTERMOST FIRST: an edge
# (a -> b) is legal iff position(a) < position(b). Every named lock
# (utils/locked.py LOCK_NAMES) must appear here — a new named lock
# without a blessed position is itself an R9 finding, so ordering
# decisions are made deliberately, in review, in this file. Anonymous
# locks participate in cycle detection but not in order checking.
LOCK_ORDER = (
    # control plane / registries first: these are taken at the top of
    # call chains and may reach into the data-plane stores below
    "overload_governor",
    "overload_peer_pressure",
    "matcher_breaker",
    "clients",
    # the tenant plane (mqtt_tpu.tenancy): CONNECT-time resolution and
    # per-tenant counters; the key registry is a leaf beside it — both
    # are registries consulted before any trie/retained work
    "tenants",
    "recrypt_keys",
    # the tries and their retained stores: the trie lock wraps
    # subscribe/unsubscribe/set_retained, which touch the retained
    # PacketStore (both the local and the cluster's remote trie share
    # the "retained" stats name)
    "topics_trie",
    "cluster_remote_trie",
    # the interned predicate registry (mqtt_tpu.predicates): SUBSCRIBE /
    # UNSUBSCRIBE interning runs while the trie mutation completes, so
    # the registry lock nests inside the tries and takes nothing further
    "predicate_rules",
    "retained",
    # per-client QoS windows (mqtt_tpu.inflight): delivery paths touch
    # the window before the durable hooks persist it, so it sits above
    # the store lock and below the registries that route to it
    "inflight",
    # the durable session plane (hooks/storage/logkv.py): storage-hook
    # events fire while trie/retained work completes, so the store lock
    # nests inside them and above the observability leaves; its append
    # path takes nothing further (the maintenance serializer beside it
    # is anonymous and ordered before it by construction)
    "durable_store",
    # observability rings/registries last: leaf locks that must never
    # call back out into the planes above; the compile-event ledger's
    # device_stats lock sits before the metrics registry (it never
    # registers children while held, but a future edge in that
    # direction is the legal one)
    "flight_ring",
    "trace_ring",
    "device_stats",
    "metrics_registry",
    # the shard router's dispatch counter lock (mqtt_tpu.shards): a pure
    # leaf — nothing is ever acquired under it
    "shard_fabric",
    # the mesh topology plane (mqtt_tpu.mesh_topology): the cluster
    # loop's adopt/propose and the forward path's neighbor reads — pure
    # leaves (no topology method ever calls back out), ordered after
    # everything that may consult the tree mid-operation
    "mesh_topology",
    "interest_bloom",
    "dup_suppressor",
)

_LOCK_CTORS = {"Lock", "RLock", "threading.Lock", "threading.RLock"}


@dataclass
class LockDef:
    """One lock attribute definition site."""

    names: frozenset  # canonical name(s) this attribute can carry
    kind: str  # "named" | "param" | "anon"
    site: str  # "module.py:Class.attr"


@dataclass
class ClassInfo:
    rel: str
    name: str
    bases: tuple
    methods: dict = field(default_factory=dict)  # name -> ast node
    lock_attrs: dict = field(default_factory=dict)  # attr -> LockDef
    # attr -> (class name, {ctor kwarg -> literal}) for self.x = C(...)
    # and annotated __init__ params assigned to self
    obj_attrs: dict = field(default_factory=dict)
    # attr -> ctor param name, for InstrumentedLock(<param>) /
    # LockedMap-family name= params resolved per call site
    param_locks: dict = field(default_factory=dict)
    param_defaults: dict = field(default_factory=dict)  # param -> literal


@dataclass(frozen=True)
class EdgeSite:
    path: str
    line: int
    context: str


class LockGraph:
    """The extracted graph: nodes (canonical names), edges with their
    acquisition sites, and the definition index for the catalog."""

    def __init__(self) -> None:
        self.defs: dict[str, list[str]] = {}  # name -> definition sites
        self.edges: dict[tuple, list[EdgeSite]] = {}

    def add_def(self, name: str, site: str) -> None:
        sites = self.defs.setdefault(name, [])
        if site not in sites:
            sites.append(site)

    def add_edge(self, src: str, dst: str, site: EdgeSite) -> None:
        if src == dst:
            return  # same-name nesting is re-entrancy by convention
        self.edges.setdefault((src, dst), []).append(site)

    def nodes(self) -> list[str]:
        out = set(self.defs)
        for a, b in self.edges:
            out.add(a)
            out.add(b)
        return sorted(out)

    def named_edges(self) -> set:
        """Edges between two NAMED locks — the witness-comparable set."""
        order = set(LOCK_ORDER)
        return {(a, b) for a, b in self.edges if a in order and b in order}

    def cycles(self) -> list[list[str]]:
        """Strongly connected components with >= 2 nodes (each is at
        least one acquisition-order cycle), via iterative Tarjan."""
        adj: dict[str, list[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on_stack: set = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        for root in sorted(adj):
            if root in index:
                continue
            work = [(root, iter(adj[root]))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    scc = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        scc.append(w)
                        if w == node:
                            break
                    if len(scc) > 1:
                        sccs.append(sorted(scc))
        return sccs

    def as_dict(self) -> dict:
        order = {n: i for i, n in enumerate(LOCK_ORDER)}
        return {
            "order": list(LOCK_ORDER),
            "nodes": [
                {
                    "name": n,
                    "kind": "named" if n in order else "anon",
                    "position": order.get(n),
                    "defined": self.defs.get(n, []),
                }
                for n in self.nodes()
            ],
            "edges": [
                {
                    "src": a,
                    "dst": b,
                    "sites": [
                        {"path": s.path, "line": s.line, "context": s.context}
                        for s in sites
                    ],
                }
                for (a, b), sites in sorted(self.edges.items())
            ],
            "cycles": self.cycles(),
        }

    def to_dot(self) -> str:
        """GraphViz rendering: blessed locks ranked by order position,
        anonymous locks dashed, cycle edges red."""
        order = {n: i for i, n in enumerate(LOCK_ORDER)}
        in_cycle = {n for scc in self.cycles() for n in scc}
        lines = [
            "digraph lockorder {",
            '  rankdir="TB";',
            '  node [shape=box, fontname="monospace", fontsize=10];',
        ]
        for n in self.nodes():
            attrs = []
            if n in order:
                attrs.append(f'xlabel="#{order[n]}"')
            else:
                attrs.append("style=dashed")
            if n in in_cycle:
                attrs.append('color="red"')
            lines.append(f'  "{n}" [{", ".join(attrs)}];')
        for (a, b), sites in sorted(self.edges.items()):
            attrs = [f'label="{len(sites)} site{"s" if len(sites) > 1 else ""}"']
            if a in in_cycle and b in in_cycle:
                attrs.append('color="red", penwidth=2')
            elif a in order and b in order and order[a] > order[b]:
                attrs.append('color="orange", style=bold')
            lines.append(f'  "{a}" -> "{b}" [{", ".join(attrs)}];')
        lines.append("}")
        return "\n".join(lines) + "\n"


# -- extraction -------------------------------------------------------------


def _literal_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _ctor_kwargs(call: ast.Call) -> dict:
    out = {}
    for kw in call.keywords:
        if kw.arg is not None:
            lit = _literal_str(kw.value)
            if lit is not None:
                out[kw.arg] = lit
    return out


def _init_params(cls_node: ast.ClassDef) -> tuple[dict, dict, list]:
    """(param -> default literal, param -> annotation name, ordered
    param names) from the class's ``__init__``."""
    defaults: dict = {}
    annots: dict = {}
    names: list = []
    for node in cls_node.body:
        if isinstance(node, ast.FunctionDef) and node.name == "__init__":
            args = node.args.args[1:]  # drop self
            names = [a.arg for a in args]
            for a in args:
                if a.annotation is not None:
                    ann = a.annotation
                    # unwrap Optional[X] / "X" strings
                    if isinstance(ann, ast.Subscript):
                        ann = ann.slice
                    t = _terminal_name(ann)
                    if t is None:
                        lit = _literal_str(ann)
                        t = lit
                    if t:
                        annots[a.arg] = t
            ds = node.args.defaults
            for a, d in zip(args[len(args) - len(ds):], ds):
                lit = _literal_str(d)
                if lit is not None or (
                    isinstance(d, ast.Constant) and d.value is None
                ):
                    defaults[a.arg] = lit  # None stays None (= anonymous)
            break
    return defaults, annots, names


class _Project:
    """Project-wide symbol tables feeding edge extraction."""

    def __init__(self, ctxs: list[FileCtx]) -> None:
        self.ctxs = ctxs
        # class name -> every definition, in index order: duplicate class
        # names across modules are all kept (and all scanned); NAME-based
        # resolution prefers a same-file definition, else the first
        self.classes: dict[str, list[ClassInfo]] = {}
        self.module_funcs: dict[str, dict[str, ast.AST]] = {}
        self.module_locks: dict[str, dict[str, str]] = {}  # rel -> var -> name
        # (class, ctor param) -> set of literal overrides seen at call sites
        self.ctor_overrides: dict[tuple, set] = {}
        for ctx in ctxs:
            self._index_file(ctx)
        self._collect_overrides()

    def cls_info(
        self, name: Optional[str], rel: Optional[str] = None
    ) -> Optional[ClassInfo]:
        infos = self.classes.get(name) if name is not None else None
        if not infos:
            return None
        if rel is not None:
            for info in infos:
                if info.rel == rel:
                    return info
        return infos[0]

    # -- pass 1: definitions ------------------------------------------------

    def _index_file(self, ctx: FileCtx) -> None:
        funcs: dict[str, ast.AST] = {}
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                d = _dotted(node.value.func)
                if d in _LOCK_CTORS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            self.module_locks.setdefault(ctx.rel, {})[
                                tgt.id
                            ] = f"{ctx.rel}:{tgt.id}"
        self.module_funcs[ctx.rel] = funcs
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                self._index_class(ctx, node)

    def _index_class(self, ctx: FileCtx, cls_node: ast.ClassDef) -> None:
        base_exprs = [
            # unwrap generic bases: LockedMap[str, Packet] -> LockedMap
            b.value if isinstance(b, ast.Subscript) else b
            for b in cls_node.bases
        ]
        bases = tuple(
            t for t in (_terminal_name(b) for b in base_exprs) if t
        )
        info = ClassInfo(ctx.rel, cls_node.name, bases)
        defaults, annots, ordered = _init_params(cls_node)
        info.param_defaults = defaults
        for node in cls_node.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods.setdefault(node.name, node)
        anchor = f"{ctx.rel}:{cls_node.name}"
        for meth in info.methods.values():
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign):
                    tgt = node.targets[0] if len(node.targets) == 1 else None
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    tgt = node.target  # self._lock: Any = ... (LockedMap)
                else:
                    continue
                if not (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    continue
                attr = tgt.attr
                val = node.value
                if isinstance(val, ast.Call):
                    d = _dotted(val.func)
                    if d in _LOCK_CTORS:
                        info.lock_attrs[attr] = LockDef(
                            frozenset([f"{anchor}.{attr}"]),
                            "anon",
                            f"{anchor}.{attr}",
                        )
                        continue
                    if d is not None and d.split(".")[-1] == "InstrumentedLock":
                        if val.args:
                            lit = _literal_str(val.args[0])
                            if lit is not None:
                                info.lock_attrs[attr] = LockDef(
                                    frozenset([lit]), "named",
                                    f"{anchor}.{attr}",
                                )
                                continue
                            pname = (
                                val.args[0].id
                                if isinstance(val.args[0], ast.Name)
                                else None
                            )
                            if pname is not None:
                                info.param_locks[attr] = pname
                                continue
                    ctor = d.split(".")[-1] if d else None
                    if ctor and ctor[:1].isupper():
                        info.obj_attrs[attr] = (ctor, _ctor_kwargs(val))
                        continue
                elif isinstance(val, ast.Name) and val.id in annots:
                    info.obj_attrs[attr] = (annots[val.id], {})
                elif isinstance(val, ast.IfExp):
                    # the LockedMap shape: RLock() if name is None else
                    # InstrumentedLock(name) — a parameter-named lock
                    for arm in (val.body, val.orelse):
                        if isinstance(arm, ast.Call):
                            d = _dotted(arm.func)
                            if (
                                d is not None
                                and d.split(".")[-1] == "InstrumentedLock"
                                and arm.args
                                and isinstance(arm.args[0], ast.Name)
                            ):
                                info.param_locks[attr] = arm.args[0].id
        # LockedMap-family subclasses: super().__init__(name="clients")
        init = info.methods.get("__init__")
        if init is not None and "_lock" not in info.lock_attrs:
            for node in ast.walk(init):
                if (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "__init__"
                ):
                    kw = _ctor_kwargs(node)
                    if "name" in kw:
                        info.lock_attrs["_lock"] = LockDef(
                            frozenset([kw["name"]]), "named",
                            f"{anchor}._lock",
                        )
        self.classes.setdefault(cls_node.name, []).append(info)

    def _collect_overrides(self) -> None:
        """Literal arguments at every call site of a class whose lock is
        parameter-named: ``TopicsIndex(lock_name="cluster_remote_trie")``
        adds that name to TopicsIndex._lock's set. Positional arguments
        are matched through the __init__ signature."""
        interesting: dict[str, dict[str, list]] = {}
        for cname, infos in self.classes.items():
            info = next((i for i in infos if i.param_locks), None)
            if info is not None:
                _, _, ordered = _init_params(
                    self._class_node(info) or ast.ClassDef(
                        name=cname, bases=[], keywords=[], body=[],
                        decorator_list=[],
                    )
                )
                interesting[cname] = {"params": ordered}
        if not interesting:
            return
        for ctx in self.ctxs:
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _terminal_name(node.func)
                if name not in interesting:
                    continue
                ordered = interesting[name]["params"]
                got: dict[str, str] = {}
                for i, arg in enumerate(node.args):
                    lit = _literal_str(arg)
                    if lit is not None and i < len(ordered):
                        got[ordered[i]] = lit
                for kw in node.keywords:
                    lit = _literal_str(kw.value)
                    if kw.arg and lit is not None:
                        got[kw.arg] = lit
                for pname, lit in got.items():
                    self.ctor_overrides.setdefault((name, pname), set()).add(
                        lit
                    )

    def _class_node(self, info: ClassInfo) -> Optional[ast.ClassDef]:
        for ctx in self.ctxs:
            if ctx.rel != info.rel:
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef) and node.name == info.name:
                    return node
        return None

    # -- resolution ---------------------------------------------------------

    def resolve_lock_attr(
        self,
        cls: Optional[str],
        attr: str,
        override: Optional[dict] = None,
        rel: Optional[str] = None,
    ) -> Optional[frozenset]:
        """Canonical names for ``self.<attr>`` in class ``cls`` (walking
        name-resolved bases; ``rel`` anchors a duplicated class name to
        its defining file). ``override`` carries instance-level ctor
        literals (``PacketStore(name="retained")``)."""
        seen: set = set()
        walked: set = {cls} if cls else set()
        info = self.cls_info(cls, rel)
        while info is not None and id(info) not in seen:
            seen.add(id(info))
            walked.add(info.name)
            if attr in info.lock_attrs:
                return info.lock_attrs[attr].names
            if attr in info.param_locks:
                pname = info.param_locks[attr]
                names: set = set()
                if override and pname in override:
                    names.add(override[pname])
                else:
                    default = info.param_defaults.get(pname)
                    if default is not None:
                        names.add(default)
                    # call-site overrides keyed on any class name along
                    # the walk (subclass ctor calls collect under the
                    # subclass name, LockedMap's own under the base)
                    for c in walked:
                        names |= self.ctor_overrides.get((c, pname), set())
                if not names:
                    return frozenset([f"{info.rel}:{info.name}.{attr}"])
                return frozenset(names)
            info = self.cls_info(info.bases[0]) if info.bases else None
        return None

    def attr_type(
        self, cls: Optional[str], attr: str, rel: Optional[str] = None
    ) -> Optional[tuple[str, dict]]:
        seen: set = set()
        info = self.cls_info(cls, rel)
        while info is not None and id(info) not in seen:
            seen.add(id(info))
            if attr in info.obj_attrs:
                return info.obj_attrs[attr]
            info = self.cls_info(info.bases[0]) if info.bases else None
        return None

    def find_method(
        self, cls: Optional[str], name: str, rel: Optional[str] = None
    ) -> Optional[tuple[ClassInfo, ast.AST]]:
        """(defining class info, node) for ``cls.name`` walking bases."""
        seen: set = set()
        info = self.cls_info(cls, rel)
        while info is not None and id(info) not in seen:
            seen.add(id(info))
            if name in info.methods:
                return info, info.methods[name]
            info = self.cls_info(info.bases[0]) if info.bases else None
        return None


class _Extractor:
    """Walks every function with a held-lock stack, recording edges."""

    def __init__(self, project: _Project) -> None:
        self.project = project
        self.graph = LockGraph()
        # observer-attr / container name -> registered callback targets
        # (defining ctx, fn node, owner class, owner rel); built once,
        # consulted at fire sites so edges propagate one level through
        # callbacks registered under one lock and fired under another
        self.callbacks: dict[str, list[tuple]] = self._index_callbacks()

    def _index_callbacks(self) -> dict[str, list[tuple]]:
        """Project-wide registry of the observer/`on_transition`/
        `rebuild_observer` shapes: ``obj.on_x = self.meth`` /
        ``obj.on_x = module_fn`` (attr matching the R5 observer
        convention) and ``container.append(fn_ref)`` /
        ``.add``/``.register`` on ``*_observers``-style containers.
        Only statically resolvable targets register (same-file module
        functions, self-methods through the class index); a lambda or a
        foreign object's bound method stays invisible — the LockWitness
        gate remains the backstop for those."""
        reg: dict[str, list[tuple]] = {}
        for ctx in self.project.ctxs:
            for top in ctx.tree.body:
                cls = top.name if isinstance(top, ast.ClassDef) else None
                for node in ast.walk(top):
                    self._note_registration(ctx, node, cls, reg)
        return reg

    def _note_registration(
        self, ctx: FileCtx, node: ast.AST, cls: Optional[str],
        reg: dict[str, list[tuple]],
    ) -> None:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Attribute)
        ):
            attr = node.targets[0].attr
            if _OBSERVER_ATTR_RE.search(attr):
                self._register_callback(ctx, cls, node.value, attr, reg)
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("append", "add", "register")
            and node.args
        ):
            base = node.func.value
            container = (
                base.attr
                if isinstance(base, ast.Attribute)
                else base.id
                if isinstance(base, ast.Name)
                else None
            )
            if container and _OBSERVER_CONTAINER_RE.search(container):
                self._register_callback(
                    ctx, cls, node.args[0], container, reg
                )

    def _register_callback(
        self, ctx: FileCtx, cls: Optional[str], value: ast.AST, key: str,
        reg: dict[str, list[tuple]],
    ) -> None:
        p = self.project
        if (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
            and cls is not None
        ):
            found = p.find_method(cls, value.attr, rel=ctx.rel)
            if found is not None:
                owner, fn = found
                octx = self._ctx_for(owner.rel) or ctx
                reg.setdefault(key, []).append(
                    (octx, fn, owner.name, owner.rel)
                )
        elif isinstance(value, ast.Name):
            fn = p.module_funcs.get(ctx.rel, {}).get(value.id)
            if fn is not None:
                reg.setdefault(key, []).append((ctx, fn, None, None))

    def _callback_acquisitions(self, key: str) -> list[tuple[frozenset, str]]:
        out: list[tuple[frozenset, str]] = []
        seen: set[int] = set()
        for octx, fn, owner_cls, rel in self.callbacks.get(key, ()):
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            out.extend(
                self._direct_acquisitions(octx, fn, owner_cls, None, rel=rel)
            )
        return out

    def run(self) -> LockGraph:
        p = self.project
        for ctx in p.ctxs:
            for var, anon in p.module_locks.get(ctx.rel, {}).items():
                self.graph.add_def(anon, anon)
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    info = p.cls_info(node.name, ctx.rel)
                    if info is None or info.rel != ctx.rel:
                        continue
                    for attr, ld in info.lock_attrs.items():
                        for n in ld.names:
                            self.graph.add_def(n, ld.site)
                    for attr, pname in info.param_locks.items():
                        names = p.resolve_lock_attr(
                            node.name, attr, rel=ctx.rel
                        ) or ()
                        for n in names:
                            self.graph.add_def(
                                n, f"{ctx.rel}:{node.name}.{attr}"
                            )
                    for attr, (tcls, override) in info.obj_attrs.items():
                        # instance-named stores: self.retained =
                        # PacketStore(name="retained")
                        if not override:
                            continue
                        names = p.resolve_lock_attr(tcls, "_lock", override)
                        for n in names or ():
                            if ":" not in n:
                                self.graph.add_def(
                                    n, f"{ctx.rel}:{node.name}.{attr}"
                                )
                    for meth in info.methods.values():
                        self._scan_function(ctx, meth, node.name)
            for fn in p.module_funcs.get(ctx.rel, {}).values():
                self._scan_function(ctx, fn, None)
            # module-level statements execute at import time: a
            # top-level `with _g_lock:` ordering is as real as any
            # other (defs/classes excluded — their members are already
            # scanned above, and rescanning would duplicate edge sites)
            module_stmts = [
                s
                for s in ctx.tree.body
                if not isinstance(
                    s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ]
            self._scan_body(ctx, module_stmts, None, [])
        return self.graph

    # -- per-function walk --------------------------------------------------

    def _scan_function(
        self, ctx: FileCtx, fn: ast.AST, cls: Optional[str]
    ) -> None:
        # `held` is a list of GROUPS (frozensets): a parameter-named
        # lock resolves to every name it can carry (topics_trie AND
        # cluster_remote_trie for TopicsIndex._lock), but one scope only
        # ever holds ONE of them — acquiring the SAME group again is
        # same-instance re-entry (legal on an RLock), never a
        # cross-name edge pair, so edges are emitted per group and a
        # group never edges into itself
        held: list[frozenset] = []
        if getattr(fn, "name", "").endswith(_LOCKED_SUFFIX) and cls:
            names = self.project.resolve_lock_attr(cls, "_lock", rel=ctx.rel)
            if names:
                held = [names]
        self._scan_body(ctx, list(getattr(fn, "body", [])), cls, held)

    def _scan_body(
        self, ctx: FileCtx, body: list, cls: Optional[str], held: list
    ) -> None:
        for stmt in body:
            self._scan_stmt(ctx, stmt, cls, held)

    def _scan_stmt(
        self, ctx: FileCtx, node: ast.AST, cls: Optional[str], held: list
    ) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a def statement under a held lock only DEFINES the inner
            # function — its body runs later, under whatever its caller
            # holds, so it is scanned with a FRESH held stack (the same
            # reason _direct_acquisitions prunes nested defs)
            self._scan_body(ctx, list(node.body), cls, [])
            return
        if isinstance(node, ast.Lambda):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: list[frozenset] = []
            for item in node.items:
                names = self._resolve_lock_expr(ctx, item.context_expr, cls)
                if names:
                    site = EdgeSite(
                        ctx.rel, node.lineno, ctx.context_line(node.lineno)
                    )
                    # `with a, b:` acquires left-to-right, so earlier
                    # items in THIS statement are already held when a
                    # later item acquires — they join the edge sources
                    self._add_edges(held + acquired, names, site)
                    acquired.append(names)
                else:
                    self._scan_expr(ctx, item.context_expr, cls, held)
            self._scan_body(ctx, node.body, cls, held + acquired)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef),
            ):
                continue
            if isinstance(child, ast.stmt):
                self._scan_stmt(ctx, child, cls, held)
            else:
                self._scan_expr(ctx, child, cls, held)

    def _scan_expr(
        self, ctx: FileCtx, expr: ast.AST, cls: Optional[str], held: list
    ) -> None:
        if not held:
            return
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            if isinstance(node, ast.Call):
                for names, _desc in self._call_acquisitions(ctx, node, cls):
                    site = EdgeSite(
                        ctx.rel, node.lineno, ctx.context_line(node.lineno)
                    )
                    self._add_edges(held, names, site)
            stack.extend(ast.iter_child_nodes(node))

    def _add_edges(
        self, held: list, names: frozenset, site: EdgeSite
    ) -> None:
        """Edges from every held GROUP to the acquired name set — except
        a group acquiring itself: one scope holds exactly one of a
        parameter-named lock's alternative names, so re-acquiring the
        same attribute (RLock re-entry through a helper) must not
        fabricate cross-name edge pairs between the alternatives."""
        for group in held:
            if group == names:
                continue
            for h in group:
                for n in names:
                    self.graph.add_edge(h, n, site)

    # -- lock expression resolution -----------------------------------------

    def _resolve_lock_expr(
        self, ctx: FileCtx, expr: ast.AST, cls: Optional[str]
    ) -> Optional[frozenset]:
        term = _terminal_name(expr)
        if term is None or not _LOCK_NAME_RE.search(term):
            return None
        p = self.project
        if isinstance(expr, ast.Name):
            mod = p.module_locks.get(ctx.rel, {})
            if expr.id in mod:
                return frozenset([mod[expr.id]])
            return frozenset([f"{ctx.rel}:<local>.{expr.id}"])
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self":
                names = p.resolve_lock_attr(cls, expr.attr, rel=ctx.rel)
                if names:
                    return names
                return frozenset([f"{ctx.rel}:{cls}.{expr.attr}"])
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
            ):
                # self.attr._lock: resolve through the attribute's type
                at = p.attr_type(cls, base.attr, rel=ctx.rel)
                if at is not None:
                    tcls, override = at
                    names = p.resolve_lock_attr(tcls, expr.attr, override)
                    if names:
                        return names
                return frozenset(
                    [f"{ctx.rel}:{cls}.{base.attr}.{expr.attr}"]
                )
            # x._lock on a local: resolvable only when the attr name is
            # unique among all project lock attributes
            owners = [
                (info.name, info.lock_attrs[expr.attr])
                for infos in p.classes.values()
                for info in infos
                if expr.attr in info.lock_attrs
            ]
            if len(owners) == 1:
                return owners[0][1].names
            d = _dotted(expr) or term
            return frozenset([f"{ctx.rel}:<local>.{d}"])
        return None

    # -- one-level call propagation -----------------------------------------

    def _call_acquisitions(
        self, ctx: FileCtx, call: ast.Call, cls: Optional[str]
    ) -> list[tuple[frozenset, str]]:
        out = self._resolved_call_acquisitions(ctx, call, cls)
        if out:
            return out
        # unresolvable receiver: if the call SHAPE is an observer fire
        # (`self.on_transition(...)`, `self._observers[k](...)`), charge
        # the one-level acquisitions of every callback registered under
        # that name project-wide — the "registered under one lock, fired
        # under another" residual from ISSUE 10
        f = call.func
        key = None
        if isinstance(f, ast.Attribute) and _OBSERVER_ATTR_RE.search(f.attr):
            key = f.attr
        elif (
            isinstance(f, ast.Subscript)
            and isinstance(f.value, ast.Attribute)
            and _OBSERVER_CONTAINER_RE.search(f.value.attr)
        ):
            key = f.value.attr
        if key is not None:
            return self._callback_acquisitions(key)
        return []

    def _resolved_call_acquisitions(
        self, ctx: FileCtx, call: ast.Call, cls: Optional[str]
    ) -> list[tuple[frozenset, str]]:
        f = call.func
        p = self.project
        if isinstance(f, ast.Name):
            fn = p.module_funcs.get(ctx.rel, {}).get(f.id)
            if fn is not None:
                return self._direct_acquisitions(ctx, fn, None, None)
            return []
        if not isinstance(f, ast.Attribute):
            return []
        base = f.value
        if isinstance(base, ast.Name) and base.id == "self":
            found = p.find_method(cls, f.attr, rel=ctx.rel)
            if found is not None:
                owner, node = found
                octx = self._ctx_for(owner.rel) or ctx
                return self._direct_acquisitions(
                    octx, node, owner.name, None, rel=owner.rel
                )
            return []
        if (
            isinstance(base, ast.Attribute)
            and isinstance(base.value, ast.Name)
            and base.value.id == "self"
        ):
            at = p.attr_type(cls, base.attr, rel=ctx.rel)
            if at is None:
                return []
            tcls, override = at
            found = p.find_method(tcls, f.attr)
            if found is None:
                return []
            owner, node = found
            octx = self._ctx_for(owner.rel) or ctx
            tinfo = p.cls_info(tcls)
            return self._direct_acquisitions(
                octx, node, tcls, override,
                rel=tinfo.rel if tinfo is not None else None,
            )
        return []

    def _ctx_for(self, rel: str) -> Optional[FileCtx]:
        for c in self.project.ctxs:
            if c.rel == rel:
                return c
        return None

    def _direct_acquisitions(
        self,
        ctx: FileCtx,
        fn: ast.AST,
        cls: Optional[str],
        override: Optional[dict],
        rel: Optional[str] = None,
    ) -> list[tuple[frozenset, str]]:
        """The with-acquisitions lexically inside ``fn`` (one level: no
        recursion into ITS calls), resolved in the receiver's context."""
        out = []
        name = getattr(fn, "name", "?")
        # rules._iter_scope PRUNES nested function/lambda/class bodies:
        # a with-acquisition inside a merely-DEFINED callback (the
        # _trip_dump registration shape) runs later, under whatever
        # locks its eventual caller holds — attributing it to this
        # callee would fabricate edges and false R9 cycles
        for node in _iter_scope(list(getattr(fn, "body", []))):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    term = _terminal_name(item.context_expr)
                    if term is None or not _LOCK_NAME_RE.search(term):
                        continue
                    names = None
                    expr = item.context_expr
                    if (
                        isinstance(expr, ast.Attribute)
                        and isinstance(expr.value, ast.Name)
                        and expr.value.id == "self"
                    ):
                        names = self.project.resolve_lock_attr(
                            cls, expr.attr, override, rel=rel
                        )
                    if names is None:
                        names = self._resolve_lock_expr(ctx, expr, cls)
                    if names:
                        out.append((names, f"{cls or ctx.rel}.{name}"))
        return out


# -- public API -------------------------------------------------------------


def extract_lock_graph(ctxs: list[FileCtx]) -> LockGraph:
    """Extract (or reuse) the graph for this exact source set. The
    single-slot memo (a function attribute, keyed on every file's rel
    path + source hash) exists because one CLI invocation runs
    extraction twice over identical sources — once inside the R9 rule,
    once for the --lock-graph DOT/JSON export."""
    key = tuple(sorted((c.rel, hash(c.source)) for c in ctxs))
    memo = getattr(extract_lock_graph, "_memo", None)
    if memo is not None and memo[0] == key:
        return memo[1]
    graph = _Extractor(_Project(ctxs)).run()
    extract_lock_graph._memo = (key, graph)  # type: ignore[attr-defined]
    return graph


def _lock_names_from_source(root: str) -> Optional[list[str]]:
    """The LOCK_NAMES catalog parsed (AST, no import) out of
    mqtt_tpu/utils/locked.py; None when the file is absent (fixture
    trees)."""
    path = os.path.join(root, "mqtt_tpu", "utils", "locked.py")
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError:
            return None
    for node in tree.body:
        if isinstance(node, ast.Assign):
            tgt = node.targets[0] if len(node.targets) == 1 else None
            if isinstance(tgt, ast.Name) and tgt.id == "LOCK_NAMES":
                if isinstance(node.value, (ast.Tuple, ast.List)):
                    out = []
                    for e in node.value.elts:
                        lit = _literal_str(e)
                        if lit is not None:
                            out.append(lit)
                    return out
    return None


def check_r9(ctxs: list[FileCtx], root: str) -> list[Finding]:
    """R9: the whole-program lock graph must be acyclic and consistent
    with the blessed LOCK_ORDER; every named lock must hold a blessed
    position. Findings anchor at acquisition/call sites so the reasoned
    pragma workflow applies."""
    graph = extract_lock_graph(ctxs)
    out: list[Finding] = []
    pos = {n: i for i, n in enumerate(LOCK_ORDER)}

    # catalog sync: utils/locked.py LOCK_NAMES <-> LOCK_ORDER
    names = _lock_names_from_source(root)
    if names is not None:
        for n in names:
            if n not in pos:
                # context carries the lock name: the baseline key is
                # (rule, path, context), so two DIFFERENT unblessed
                # locks in one file must never share a baseline entry
                out.append(
                    Finding(
                        "R9", "mqtt_tpu/utils/locked.py", 1, 0,
                        f"named lock {n!r} (LOCK_NAMES) has no blessed "
                        "position in tools/brokerlint/lockgraph.py "
                        "LOCK_ORDER; add it where it belongs in the "
                        "acquisition order", f"lock:{n}",
                    )
                )
    # a named lock extracted from the tree but absent from the order is
    # the same drift in the other direction (e.g. a new
    # InstrumentedLock("x") nobody blessed)
    for n in sorted(graph.defs):
        if ":" not in n and n not in pos:
            site = graph.defs[n][0]
            out.append(
                Finding(
                    "R9", site.split(":")[0], 1, 0,
                    f"named lock {n!r} ({site}) is missing from the "
                    "blessed LOCK_ORDER in tools/brokerlint/lockgraph.py",
                    f"lock:{n}",
                )
            )

    # reversed edges against the blessed order
    for (a, b), sites in sorted(graph.edges.items()):
        if a in pos and b in pos and pos[a] > pos[b]:
            for s in sites:
                out.append(
                    Finding(
                        "R9", s.path, s.line, 0,
                        f"lock order reversed: {b!r} (position {pos[b]}) "
                        f"must never be acquired while holding {a!r} "
                        f"(position {pos[a]}); see LOCK_ORDER in "
                        "tools/brokerlint/lockgraph.py", s.context,
                    )
                )

    # cycles (potential deadlocks) anywhere in the graph, anonymous
    # locks included
    for scc in graph.cycles():
        member = set(scc)
        cyc = " -> ".join(scc + [scc[0]])
        for (a, b), sites in sorted(graph.edges.items()):
            if a in member and b in member:
                for s in sites:
                    out.append(
                        Finding(
                            "R9", s.path, s.line, 0,
                            f"lock-order cycle {cyc}: this acquisition of "
                            f"{b!r} under {a!r} participates; break the "
                            "cycle or document why it cannot deadlock",
                            s.context,
                        )
                    )
    return out
