"""The inline client: publish and subscribe in-process, no sockets
(reference examples/direct/main.go)."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook


async def main() -> None:
    server = Server(Options(inline_client=True))
    server.add_hook(AllowHook())
    await server.serve()

    got = []

    def on_message(cl, sub, pk):
        got.append((pk.topic_name, bytes(pk.payload)))
        print(f"inline handler: {pk.topic_name} -> {bytes(pk.payload)!r}")

    server.subscribe("direct/#", 1, on_message)
    server.publish("direct/hello", b"from the embedding app", False, 0)
    server.publish("direct/retained", b"sticky", True, 0)
    await asyncio.sleep(0.1)
    assert got, "inline delivery failed"
    server.unsubscribe("direct/#", 1)
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
