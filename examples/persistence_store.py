"""Write-through persistence + restart restore, using the sqlite store
(reference examples/persistence/*; badger/bolt/pebble analogs are the
logkv and sqlite stores, redis via hooks.storage.redis)."""

import asyncio
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.hooks.storage.sqlite import SqliteOptions, SqliteStore


async def main() -> None:
    path = os.path.join(tempfile.mkdtemp(), "broker.db")

    # first life: accept state
    server = Server(Options(inline_client=True))
    server.add_hook(AllowHook())
    server.add_hook(SqliteStore(), SqliteOptions(path=path))
    await server.serve()
    server.publish("persist/retained", b"still here", True, 0)
    await asyncio.sleep(0.1)
    await server.close()

    # second life: restore on boot
    server2 = Server(Options(inline_client=True))
    server2.add_hook(AllowHook())
    server2.add_hook(SqliteStore(), SqliteOptions(path=path))
    await server2.serve()
    msgs = server2.topics.messages("persist/#")
    print(f"restored retained: {[(p.topic_name, bytes(p.payload)) for p in msgs]}")
    assert msgs, "restore failed"
    await server2.close()


if __name__ == "__main__":
    asyncio.run(main())
