"""Rule-based auth + ACL via the ledger hook (reference
examples/auth/basic/main.go)."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth.auth import AuthHook, AuthOptions
from mqtt_tpu.hooks.auth.ledger import (
    ACCESS_READ_ONLY,
    ACCESS_READ_WRITE,
    ACLRule,
    AuthRule,
    Ledger,
    RString,
)
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP


def build_ledger() -> Ledger:
    return Ledger(
        auth=[
            AuthRule(username=RString("peach"), password=RString("password1"), allow=True),
            AuthRule(remote=RString("127.0.0.1"), allow=True),
        ],
        acl=[
            # melon may read everything but write only to melon/#
            ACLRule(
                username=RString("melon"),
                filters={
                    RString("melon/#"): ACCESS_READ_WRITE,
                    RString("#"): ACCESS_READ_ONLY,
                },
            ),
            ACLRule(filters={RString("#"): ACCESS_READ_WRITE}),
        ],
    )


async def main() -> None:
    server = Server(Options())
    hook = AuthHook()
    server.add_hook(hook, AuthOptions(ledger=build_ledger()))
    server.add_listener(TCP(Config(type="tcp", id="t1", address=":1883")))
    await server.serve()
    print("ledger-auth broker on :1883")
    try:
        await asyncio.Event().wait()
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())
