"""MQTT over WebSocket end to end (reference examples/websocket/main.go):
serve the ws listener and drive connect/subscribe/publish through a
minimal RFC 6455 client written inline — handshake, client-side masking,
binary frames — so the example proves the whole upgrade + framing path
without any external client."""

import asyncio
import base64
import hashlib
import os
import secrets
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.websocket import Websocket

PORT = 18894
WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
CONNECT_V4 = bytes.fromhex("100c00044d5154540402003c0000")


def _mask(payload: bytes) -> bytes:
    key = secrets.token_bytes(4)
    return key + bytes(b ^ key[i % 4] for i, b in enumerate(payload))


def ws_frame(payload: bytes) -> bytes:
    """One masked binary frame (client frames MUST be masked, RFC 6455 5.3)."""
    head = b"\x82"  # FIN + binary opcode
    n = len(payload)
    if n < 126:
        head += bytes([0x80 | n])
    elif n < 65536:
        head += bytes([0x80 | 126]) + n.to_bytes(2, "big")
    else:
        head += bytes([0x80 | 127]) + n.to_bytes(8, "big")
    return head + _mask(payload)


async def ws_read_frame(reader) -> bytes:
    b1, b2 = await reader.readexactly(2)
    n = b2 & 0x7F
    if n == 126:
        n = int.from_bytes(await reader.readexactly(2), "big")
    elif n == 127:
        n = int.from_bytes(await reader.readexactly(8), "big")
    assert not (b2 & 0x80), "server frames must not be masked"
    return await reader.readexactly(n) if n else b""


async def main() -> None:
    server = Server(Options())
    server.add_hook(AllowHook())
    server.add_listener(
        Websocket(Config(type="ws", id="ws", address=f"127.0.0.1:{PORT}"))
    )
    await server.serve()

    reader, writer = await asyncio.open_connection("127.0.0.1", PORT)
    key = base64.b64encode(secrets.token_bytes(16)).decode()
    writer.write(
        (
            f"GET /mqtt HTTP/1.1\r\nHost: 127.0.0.1:{PORT}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n"
            "Sec-WebSocket-Protocol: mqtt\r\n\r\n"
        ).encode()
    )
    await writer.drain()
    response = await reader.readuntil(b"\r\n\r\n")
    assert b"101" in response.split(b"\r\n", 1)[0], response
    want = base64.b64encode(
        hashlib.sha1((key + WS_GUID).encode()).digest()
    ).decode()
    assert f"Sec-WebSocket-Accept: {want}".encode() in response
    assert b"Sec-WebSocket-Protocol: mqtt" in response

    writer.write(ws_frame(CONNECT_V4))
    await writer.drain()
    connack = await ws_read_frame(reader)
    assert connack[0] == 0x20, connack.hex()

    filt = b"ws/topic"
    var = b"\x00\x01" + len(filt).to_bytes(2, "big") + filt + b"\x00"
    writer.write(ws_frame(b"\x82" + bytes([len(var)]) + var))
    await writer.drain()
    suback = await ws_read_frame(reader)
    assert suback[0] == 0x90, suback.hex()

    body = len(filt).to_bytes(2, "big") + filt + b"over-websocket"
    writer.write(ws_frame(b"\x30" + bytes([len(body)]) + body))
    await writer.drain()
    echo = await asyncio.wait_for(ws_read_frame(reader), 5)
    assert b"over-websocket" in echo, echo.hex()
    print("delivered over websocket:", echo.hex())

    writer.close()
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
