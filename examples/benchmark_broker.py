"""A broker tuned for the in-repo stresser (reference
examples/benchmark/main.go: MaximumClientWritesPending=16K). Run:

    python examples/benchmark_broker.py &
    python -m mqtt_tpu.stress --broker 127.0.0.1:1883 -c 10 -m 10000
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP


async def main() -> None:
    options = Options()
    options.capabilities.maximum_client_writes_pending = 16 * 1024
    server = Server(options)
    server.add_hook(AllowHook())
    server.add_listener(TCP(Config(type="tcp", id="bench", address=":1883")))
    await server.serve()
    print("benchmark broker up on :1883")
    try:
        await asyncio.Event().wait()
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())
