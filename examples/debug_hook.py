"""Packet-flow visualisation with the debug hook
(reference examples/debug/main.go)."""

import asyncio
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.hooks.debug import DebugHook, DebugOptions


async def main() -> None:
    logging.basicConfig(level=logging.DEBUG, format="%(message)s")
    server = Server(Options(inline_client=True))
    server.add_hook(AllowHook())
    server.add_hook(DebugHook(), DebugOptions(show_packet_data=True))
    await server.serve()
    server.subscribe("debug/#", 1, lambda cl, sub, pk: None)
    server.publish("debug/demo", b"watch the log", False, 0)
    await asyncio.sleep(0.1)
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
