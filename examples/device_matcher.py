"""The TPU-native path: flat-hash device matcher + publish staging loop.

No analog in the reference — this is the rebuild's north-star component
(SURVEY.md §7): PUBLISH topics match against a device-resident flat-hash
index in micro-batches, bit-identical to the host trie.
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP


async def main() -> None:
    options = Options(
        device_matcher=True,  # DeltaMatcher snapshot + host delta overlay
        matcher_stage_window_ms=2.0,  # publish micro-batch window
        matcher_opts={"max_levels": 8, "window": 16},
    )
    server = Server(options)
    server.add_hook(AllowHook())
    server.add_listener(TCP(Config(type="tcp", id="t1", address=":1883")))
    await server.serve()
    print("device-matcher broker up on :1883")
    print("matcher stats:", server.matcher.stats.as_dict())
    try:
        await asyncio.Event().wait()
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())
