"""File-driven broker: YAML/JSON config -> Options -> Server
(reference examples/config/main.go, cmd/docker/main.go)."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Server
from mqtt_tpu.config import from_file


async def main() -> None:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "config.yaml")
    options = from_file(path)
    server = Server(options)
    await server.serve()
    print("config-driven broker up (tcp :1883, ws :1882, health :1880)")
    try:
        await asyncio.Event().wait()
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())
