"""TLS broker end to end (reference examples/tls/main.go): generate an
ECC root + server certificate with the CLI's genecc generator, serve MQTT
over TLS, and drive a connect/subscribe/publish round trip through a
verifying TLS client socket."""

import asyncio
import os
import ssl
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP

PORT = 18893

CONNECT_V4 = bytes.fromhex("100c00044d5154540402003c0000")


async def main() -> None:
    workdir = tempfile.mkdtemp(prefix="mqtt-tpu-tls-")
    cwd = os.getcwd()
    os.chdir(workdir)
    try:
        from mqtt_tpu.__main__ import cmd_genecc

        assert cmd_genecc(None) == 0, "certificate generation failed"
    finally:
        os.chdir(cwd)

    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(
        os.path.join(workdir, "cert.ec.pem"), os.path.join(workdir, "cert-key.ec.pem")
    )
    server = Server(Options())
    server.add_hook(AllowHook())
    server.add_listener(
        TCP(
            Config(
                type="tcp", id="tls", address=f"127.0.0.1:{PORT}", tls_config=server_ctx
            )
        )
    )
    await server.serve()

    # the client VERIFIES the server against the generated root CA
    client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    client_ctx.load_verify_locations(os.path.join(workdir, "root.ec.pem"))
    reader, writer = await asyncio.open_connection(
        "127.0.0.1", PORT, ssl=client_ctx, server_hostname="localhost"
    )
    writer.write(CONNECT_V4)
    await writer.drain()
    connack = await reader.read(64)
    assert connack[0] == 0x20, connack.hex()

    filt = b"secure/topic"
    var = b"\x00\x01" + len(filt).to_bytes(2, "big") + filt + b"\x00"
    writer.write(b"\x82" + bytes([len(var)]) + var)
    await writer.drain()
    suback = await reader.read(64)
    assert suback[0] == 0x90, suback.hex()

    body = len(filt).to_bytes(2, "big") + filt + b"over-tls"
    writer.write(b"\x30" + bytes([len(body)]) + body)
    await writer.drain()
    echo = await asyncio.wait_for(reader.read(256), 5)
    assert b"over-tls" in echo, echo.hex()
    print("delivered over verified TLS:", echo.hex())

    writer.close()
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
