"""The Paho interoperability harness configuration (reference
examples/paho.testing/main.go:29-31,77): a broker with the
ObscureNotAuthorized / PassiveClientDisconnect /
NoInheritedPropertiesOnAck compat flags and an ACL denying subscriptions
to 'test/nosubscribe'.

When the external Paho MQTT v5 conformance client (client_test5.py,
reference README.md:468-471) or the paho-mqtt package is available, point
it at this broker. Neither ships in this image, so the example also
self-verifies the two harness-specific behaviors with an independent
from-spec client (tests/test_interop.py carries the full version):
the denied filter SUBACKs with the obscured unspecified-error code, and
an allowed round trip works.
"""

import asyncio
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks import ON_ACL_CHECK, ON_CONNECT_AUTHENTICATE, Hook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP

PORT = 18895


class PahoTestingACL(Hook):
    """Allow everything except subscribing to test/nosubscribe
    (examples/paho.testing/main.go:77)."""

    def id(self):
        return "paho-acl"

    def provides(self, b):
        return b in (ON_CONNECT_AUTHENTICATE, ON_ACL_CHECK)

    def on_connect_authenticate(self, cl, pk):
        return True

    def on_acl_check(self, cl, topic, write):
        return not (not write and topic == "test/nosubscribe")


def _utf8(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


async def main() -> None:
    opts = Options()
    # the three compat flags the reference paho harness enables
    opts.capabilities.compatibilities.obscure_not_authorized = True
    opts.capabilities.compatibilities.passive_client_disconnect = True
    opts.capabilities.compatibilities.no_inherited_properties_on_ack = True
    server = Server(opts)
    server.add_hook(PahoTestingACL())
    server.add_listener(TCP(Config(type="tcp", id="paho", address=f"127.0.0.1:{PORT}")))
    await server.serve()
    print(f"paho-testing broker up on 127.0.0.1:{PORT}")

    try:
        import paho.mqtt.client  # noqa: F401

        print("paho-mqtt detected: run the Paho v5 suite against this broker")
    except ImportError:
        pass

    # self-verification with a from-spec v5 client
    reader, writer = await asyncio.open_connection("127.0.0.1", PORT)
    body = _utf8("MQTT") + b"\x05\x02" + struct.pack(">H", 60) + b"\x00" + _utf8("paho1")
    writer.write(b"\x10" + bytes([len(body)]) + body)
    await writer.drain()
    connack = await reader.read(64)
    assert connack[0] == 0x20 and connack[3] == 0, connack.hex()

    # denied filter: the reason code must be the OBSCURED 0x80, not 0x87
    var = struct.pack(">H", 1) + b"\x00" + _utf8("test/nosubscribe") + b"\x00"
    writer.write(b"\x82" + bytes([len(var)]) + var)
    await writer.drain()
    suback = await reader.read(64)
    assert suback[0] == 0x90 and suback[-1] == 0x80, suback.hex()
    print("denied filter obscured to unspecified error:", hex(suback[-1]))

    # allowed round trip still works
    var = struct.pack(">H", 2) + b"\x00" + _utf8("test/allowed") + b"\x00"
    writer.write(b"\x82" + bytes([len(var)]) + var)
    await writer.drain()
    suback = await reader.read(64)
    assert suback[-1] == 0x00, suback.hex()
    pub = _utf8("test/allowed") + b"\x00" + b"harness-ok"
    writer.write(b"\x30" + bytes([len(pub)]) + pub)
    await writer.drain()
    echo = await asyncio.wait_for(reader.read(256), 5)
    assert b"harness-ok" in echo, echo.hex()
    print("allowed round trip:", echo.hex())

    writer.close()
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
