"""Minimal embeddable broker: one TCP listener, allow-all auth
(reference examples/tcp/main.go)."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.listeners import Config
from mqtt_tpu.listeners.tcp import TCP


async def main() -> None:
    server = Server(Options())
    server.add_hook(AllowHook())
    server.add_listener(TCP(Config(type="tcp", id="t1", address=":1883")))
    await server.serve()
    print("broker up on :1883 — ctrl-c to stop")
    try:
        await asyncio.Event().wait()
    except asyncio.CancelledError:
        pass
    finally:
        await server.close()


if __name__ == "__main__":
    asyncio.run(main())
