"""Writing a custom hook: observe connects, modify publishes, veto topics
(reference examples/hooks/main.go)."""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mqtt_tpu import Options, Server
from mqtt_tpu.hooks import (
    ON_CONNECT,
    ON_DISCONNECT,
    ON_PUBLISH,
    ON_SUBSCRIBED,
    Hook,
)
from mqtt_tpu.hooks.auth import AllowHook
from mqtt_tpu.packets import ERR_REJECT_PACKET


class ExampleHook(Hook):
    def id(self):
        return "events-example"

    def provides(self, b):
        return b in (ON_CONNECT, ON_DISCONNECT, ON_PUBLISH, ON_SUBSCRIBED)

    def on_connect(self, cl, pk):
        print(f"client connected: {cl.id}")

    def on_disconnect(self, cl, err, expire):
        print(f"client disconnected: {cl.id} expire={expire}")

    def on_subscribed(self, cl, pk, reason_codes):
        print(f"subscribed: {cl.id} {[s.filter for s in pk.filters]}")

    def on_publish(self, cl, pk):
        if pk.topic_name == "forbidden/topic":
            raise ERR_REJECT_PACKET()  # silently dropped
        if pk.topic_name == "rewrite/me":
            pk.payload = b"[modified] " + bytes(pk.payload)
        return pk


async def main() -> None:
    server = Server(Options(inline_client=True))
    server.add_hook(AllowHook())
    server.add_hook(ExampleHook())
    await server.serve()

    server.subscribe("#", 1, lambda cl, sub, pk: print(f"seen: {pk.topic_name} {bytes(pk.payload)!r}"))
    server.publish("rewrite/me", b"hello", False, 0)
    server.publish("forbidden/topic", b"nope", False, 0)
    await asyncio.sleep(0.1)
    await server.close()


if __name__ == "__main__":
    asyncio.run(main())
