#!/usr/bin/env python
"""Benchmark: batched publish-topic matching against large subscription
indexes on the real device — the five BASELINE.md device configs plus the
broker and host-materializer configs, timed end to end.

Per config the timed loop covers the full seam: host tokenization, H2D
transfer, the device flat-hash match, D2H transfer, and host expansion into
bit-identical ``Subscribers`` sets (including host-fallback re-walks for
overflowed topics) — i.e. exactly what ``publish_to_subscribers`` pays when
the device matcher is enabled. A separate pipeline rate isolates the device
path (tokenize -> H2D -> match -> D2H as numpy sub-id sets) to show where
the remaining host cost sits.

Configs (BASELINE.md "Our target"):
  1. 10k exact subs — host-trie parity baseline (reference topics.go:583)
  2. 1M subs, 3-level topics, 10% ``+`` — the north-star config
  3. 1M subs, 8-level topics, 5% ``#`` — deep/fan-in stress (out_slots=256)
  4. 100k ``$share`` groups x 16 members — shared selection included
  5. 200k subs w/ v5 subscription-identifiers + retained scans under live
     subscribe/unsubscribe churn (DeltaMatcher, background rebuilds)
  6. broker: the mqtt-stresser analog over real TCP (README.md:474-508
     scenarios), one SO_REUSEPORT worker per core on multi-core hosts
  7. host materializer in isolation (no device needed): the C extension
     vs the pure-Python oracle on cfg2-shaped synthetic result rows
  8. publish storm (no device needed): offered load >> sustainable against
     an in-process broker with the overload governor (mqtt_tpu.overload)
     active — records shed rate, eviction count, peak staging pending
     depth, and admitted-traffic delivery p99

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "configs"}.
The headline value is config #2's end-to-end matches/sec vs the 10M north
star. Environment overrides: BENCH_SUBS, BENCH_BATCH, BENCH_ITERS,
BENCH_FAST=1 (small sizes, smoke), BENCH_CONFIGS=2,4 (subset),
BENCH_P99_BUDGET_MS, BENCH_PROBE_RETRIES / BENCH_PROBE_WAIT /
BENCH_PROBE_TIMEOUT (device-probe cadence; tests shrink the timeout to
exercise the dead-tunnel path quickly).
"""

import json
import os
import random
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_MATCHES_PER_SEC = 10_000_000  # the BASELINE.json north star


def log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def canon(s):
    """Order-free digest of a Subscribers set for parity checks."""
    return (
        {c: (sub.qos, tuple(sorted(sub.identifiers.items()))) for c, sub in s.subscriptions.items()},
        {f: set(m) for f, m in s.shared.items()},
        set(s.inline_subscriptions),
    )


def pctl(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, int(len(xs) * q) - 1))]


def telemetry_block(stage_lat, stage_name, fallbacks=None, fill=None):
    """The per-config BENCH telemetry block (ISSUE 3): stage latencies
    folded through the broker's own log-scale histogram so the p50/p99
    here and the live /metrics percentiles share bucket math — future
    PRs diff stage-level regressions, not just the end-to-end rate."""
    from mqtt_tpu.telemetry import Histogram

    h = Histogram()
    for v in stage_lat:
        h.observe(v)
    block = {
        "stages": {
            stage_name: {
                "count": h.count,
                "p50_ms": round(h.percentile(0.5) * 1e3, 3),
                "p99_ms": round(h.percentile(0.99) * 1e3, 3),
            }
        }
    }
    if fill is not None:
        block["batch_fill"] = fill
    if fallbacks:
        block["fallbacks"] = fallbacks
    return block


def probe_link():
    """Measure the host<->device link: round-trip latency and H2D/D2H
    bandwidth. Through a direct PCIe attachment these are ~10us / >8GB/s;
    through a tunneled device (axon) they can be ~70ms / ~30-60MB/s, which
    makes result transfer — not the match kernel — the e2e wall. Reported
    alongside the results so the numbers are interpretable."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda v, i: v + i)
    tiny = jnp.zeros((8,), jnp.int32)
    big = jnp.zeros((2 * 1024 * 1024,), jnp.int32)  # 8MB
    jax.block_until_ready([f(tiny, 0), f(big, 0)])
    rtts = []
    for i in range(1, 4):
        y = f(tiny, i)
        y.block_until_ready()
        t0 = time.perf_counter()
        np.asarray(y)
        rtts.append(time.perf_counter() - t0)
    y = f(big, 9)
    y.block_until_ready()
    t0 = time.perf_counter()
    np.asarray(y)
    d2h_s = time.perf_counter() - t0
    a = np.zeros((2 * 1024 * 1024,), dtype=np.int32)
    t0 = time.perf_counter()
    jnp.asarray(a).block_until_ready()
    h2d_s = time.perf_counter() - t0
    rtt = min(rtts)
    return {
        "d2h_rtt_ms": round(rtt * 1e3, 2),
        "d2h_mb_per_s": round(8 / max(1e-9, d2h_s - rtt), 1),
        "h2d_mb_per_s": round(8 / max(1e-9, h2d_s - rtt), 1),
    }


def bench_lazy() -> bool:
    """BENCH_LAZY=0 disables the zero-materialization fan-out A/B-wide:
    matchers return eager Subscribers dicts (no lazy views) and the
    in-process + serve-side brokers take the legacy per-subscriber
    encode path instead of the batched variant flush (ISSUE 13)."""
    return os.environ.get("BENCH_LAZY", "1") != "0"


def bench_compact() -> bool:
    """BENCH_COMPACT=0 disables device-resident hit compaction for an
    A/B against the padded-ranges transfer (default: on, the production
    posture)."""
    return os.environ.get("BENCH_COMPACT", "1") != "0"


# -- index builders ---------------------------------------------------------


def build_cfg1(rng):
    """10k exact-match subs over 3-level topics (examples/benchmark parity)."""
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import TopicsIndex

    v = [f"seg{i}" for i in range(40)]
    index = TopicsIndex()
    filters = set()
    while len(filters) < 10_000:
        filters.add("/".join(rng.choice(v) for _ in range(3)))
    for i, f in enumerate(sorted(filters)):
        index.subscribe(f"cl{i}", Subscription(filter=f, qos=0))
    pool = sorted(filters)

    def topic_gen():
        return rng.choice(pool)

    return index, topic_gen


def build_cfg2(n_subs, rng):
    """3-level topics, 10% single-level + wildcards (north star)."""
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import TopicsIndex

    v0 = [f"region{i}" for i in range(100)]
    v1 = [f"device{i}" for i in range(100)]
    v2 = [f"metric{i}" for i in range(100)]
    index = TopicsIndex()
    for i in range(n_subs):
        parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
        if rng.random() < 0.10:
            parts[rng.randrange(3)] = "+"
        index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))

    def topic_gen():
        return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

    return index, topic_gen


def build_cfg3(n_subs, rng):
    """Deep 8-level topics, 5% multi-level # wildcards."""
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import TopicsIndex

    v_top = [f"t{i}" for i in range(1000)]
    v = [f"s{i}" for i in range(30)]

    def rand_parts():
        return [rng.choice(v_top)] + [rng.choice(v) for _ in range(7)]

    index = TopicsIndex()
    for i in range(n_subs):
        parts = rand_parts()
        if rng.random() < 0.05:
            depth = rng.randint(1, 7)
            parts = parts[:depth] + ["#"]
        index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))

    def topic_gen():
        return "/".join(rand_parts())

    return index, topic_gen


def build_cfg4(n_groups, members, rng):
    """100k $share groups x 16 members, QoS1 (shared selection included)."""
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import SHARE_PREFIX, TopicsIndex

    v0 = [f"region{i}" for i in range(100)]
    v1 = [f"device{i}" for i in range(100)]
    v2 = [f"metric{i}" for i in range(100)]
    index = TopicsIndex()
    for g in range(n_groups):
        flt = f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"
        for m in range(members):
            index.subscribe(
                f"g{g}m{m}",
                Subscription(filter=f"{SHARE_PREFIX}/grp{g}/{flt}", qos=1),
            )

    def topic_gen():
        return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

    return index, topic_gen


# -- timing harness ---------------------------------------------------------


def parity_check(matcher, index, topic_gen, n=32):
    topics = [topic_gen() for _ in range(n)]
    for topic, dev in zip(topics, matcher.match_topics(topics)):
        host = index.subscribers(topic)
        assert canon(dev) == canon(host), f"parity mismatch on {topic!r}"


def time_host(index, topic_gen, iters):
    """The host trie walk rate — the CPU-reference path (topics.go:583)."""
    topics = [topic_gen() for _ in range(iters)]
    t0 = time.perf_counter()
    for t in topics:
        index.subscribers(t)
    dt = time.perf_counter() - t0
    return iters / dt


def time_matcher(matcher, index, topic_gen, batch, iters, select_shared=False):
    """Full-path timing through matcher.match_topics (tokenize + H2D +
    device match + D2H + expand + host fallback), plus an isolated device
    pipeline rate. Returns a metrics dict."""
    import jax
    import jax.numpy as jnp

    from mqtt_tpu.ops.hashing import tokenize_topics

    batches = [[topic_gen() for _ in range(batch)] for _ in range(4)]

    # threshold tuning matches the broker's runtime posture (Server.serve
    # applies the same); the freeze is bench-only — here the just-built
    # index is the entire object graph, while a live broker must not
    # freeze transient asyncio state (see gctune.freeze_index)
    from mqtt_tpu.utils.gctune import freeze_index, tune_for_throughput

    tune_for_throughput()
    freeze_index()

    # warmup / compile both paths
    matcher.match_topics(batches[0])

    # end-to-end THROUGHPUT: depth-2 software pipeline (issue batch i+1,
    # resolve batch i) — exactly the broker staging-loop shape; hides the
    # host<->device round trip but pays every byte and every expand
    s0_fall, s0_ovf, s0_topics = (
        matcher.stats.host_fallbacks,
        matcher.stats.overflows,
        matcher.stats.topics,
    )
    # device pipeline profiler (mqtt_tpu.tracing): duty cycle / overlap /
    # idle-gap over the pipelined loop — the exact numbers ROADMAP item
    # 1's overlapped-staging work must move, baselined per round.
    # Attached AFTER warmup so the cold compile doesn't skew the windows.
    from mqtt_tpu.tracing import DeviceProfiler

    profiler = DeviceProfiler()
    if hasattr(matcher, "profiler"):
        matcher.profiler = profiler
    # compile-ledger watermark (ISSUE 18, the PR 11 regression guard):
    # the warmup above compiled every executable this loop needs, so any
    # ledger growth across the steady-state window below IS a recompile
    # — the silent one-recompile-per-step failure mode, now a scalar the
    # bench-history ledger diffs round over round
    from mqtt_tpu.ops.devicestats import LEDGER

    ledger_t0 = LEDGER.total()
    hits = 0
    t_start = time.perf_counter()
    pending = matcher.match_topics_async(batches[0])
    for i in range(1, iters + 1):
        nxt = (
            matcher.match_topics_async(batches[i % len(batches)])
            if i < iters
            else None
        )
        results = pending()
        if select_shared:
            for r in results:
                for members in r.shared.values():
                    next(iter(members), None)  # SelectShared analog
        else:
            # consume every result the way _fan_out does (ISSUE 13): a
            # lazy SubscribersView yields its (client, sub) plan, an
            # eager dict is already built — either way the e2e number
            # includes the cost fan-out actually pays
            for r in results:
                consume = getattr(r, "targets", None)
                if consume is not None:
                    consume()
        if i == 1:
            hits = sum(
                len(r.subscriptions) + sum(len(m) for m in r.shared.values())
                for r in results
            )
        pending = nxt
    e2e_dt = time.perf_counter() - t_start
    steady_recompiles = LEDGER.total() - ledger_t0
    device_pipeline = profiler.bench_block()
    if hasattr(matcher, "profiler"):
        matcher.profiler = None  # the latency loops below stay unprofiled
    n_topics = matcher.stats.topics - s0_topics
    fallbacks = matcher.stats.host_fallbacks - s0_fall
    overflows = matcher.stats.overflows - s0_ovf

    # single-batch LATENCY: unpipelined issue->resolve round trips
    lat = []
    for i in range(min(iters, 8)):
        t1 = time.perf_counter()
        matcher.match_topics(batches[i % len(batches)])
        lat.append(time.perf_counter() - t1)

    # the LATENCY-BOUNDED operating point (SURVEY §7 hard part 4 /
    # VERDICT r4 item 4): the largest batch whose single-batch p99 fits
    # the budget, and the pipelined rate it sustains there — the number a
    # latency-sensitive deployment would run at (the staging loop's
    # adaptive controller converges to this point on its own)
    p99_bounded = None
    budget_s = float(os.environ.get("BENCH_P99_BUDGET_MS", "250")) / 1e3
    # sparse size ladder (each new bucket size costs a fresh JIT compile —
    # 20-40s over a tunneled link, so halving all the way down is ruinous);
    # floor matches the staging controller's min_batch
    for bb in (batch, batch // 4, batch // 16, batch // 64):
        if bb < 64:
            break
        bl = []
        sub = [batches[0][:bb], batches[1][:bb]]
        matcher.match_topics(sub[0])  # warm this bucket's executable (JIT)
        for i in range(4):
            t1 = time.perf_counter()
            matcher.match_topics(sub[i % 2])
            bl.append(time.perf_counter() - t1)
        if max(bl) <= budget_s:
            t1 = time.perf_counter()
            n_it = max(6, min(20, int(2.0 / max(bl))))
            pend = matcher.match_topics_async(sub[0])
            for i in range(1, n_it + 1):
                nxt = matcher.match_topics_async(sub[i % 2]) if i < n_it else None
                pend()
                pend = nxt
            dt = time.perf_counter() - t1
            p99_bounded = {
                "batch": bb,
                "e2e_matches_per_sec": round(n_it * bb / dt),
                "p99_batch_ms": round(pctl(bl, 0.99) * 1e3, 3),
                "budget_ms": round(budget_s * 1e3),
            }
            break
    if p99_bounded is None:
        p99_bounded = {
            "batch": None,
            "note": f"no batch size on the ladder down from {batch} fits "
            f"p99 < {budget_s*1e3:.0f}ms on this link",
        }

    # LINK-NORMALIZED host resolve rate: materialize one already-fetched
    # packed result batch repeatedly (no device dispatch, no transfer) —
    # the rate the host side would sustain on a directly-attached device,
    # i.e. the e2e ceiling once the tunnel's RTT/bandwidth tax is removed
    # (VERDICT r4 item 1: "report the link-normalized number too")
    resolve_rate = None
    materialization_cost = None
    from mqtt_tpu.ops.matcher import _accel

    acc = _accel()
    if (
        acc is not None
        and hasattr(matcher, "csr")
        and matcher.csr is not None
        and matcher.csr.exact_map is None  # exact-map configs never take
        # the device+resolve path in production; this ceiling is theirs
    ):
        from mqtt_tpu.ops.flat import flat_match_packed, pack_tokens
        from mqtt_tpu.topics import Subscribers as _Subscribers

        flat = matcher.csr
        tok = tokenize_topics(batches[0], flat.max_levels, flat.salt)
        packed_dev = flat_match_packed(
            *matcher.device_arrays,
            jnp.asarray(pack_tokens(*tok[:4])),
            max_levels=flat.max_levels,
        )
        packed_np = np.asarray(packed_dev)
        P = flat.pat_depth.shape[0]
        n_it = max(3, min(12, iters))
        t0 = time.perf_counter()
        for _ in range(n_it):
            acc.resolve_batch(
                packed_np, batch, P, flat.subs.snaps, flat.window, _Subscribers
            )
        resolve_rate = round(n_it * batch / (time.perf_counter() - t0))

        # per-hit materialization / consume cost (ISSUE 13): over the
        # SAME already-fetched device result, time (a) the lazy path —
        # build views + consume their (client, sub) plans exactly like
        # _fan_out — against (b) the eager dict expansion. The lazy
        # number is the acceptance bar (< 300 ns/hit); both land in the
        # artifact so the A/B is re-checkable every round.
        if hasattr(acc, "resolve_batch_views"):
            total_hits = int(packed_np[:, 2 * P].sum())
            ovf_rows = int((packed_np[:, 2 * P + 1] != 0).sum())
            n_it2 = max(3, min(12, iters))
            t0 = time.perf_counter()
            for _ in range(n_it2):
                views, _o = acc.resolve_batch_views(
                    packed_np, batch, P, flat.subs.snaps, flat.window,
                    _Subscribers,
                )
                for v in views:
                    if v is not None:
                        v.targets()
            dt_lazy = time.perf_counter() - t0
            t0 = time.perf_counter()
            for _ in range(n_it2):
                acc.resolve_batch(
                    packed_np, batch, P, flat.subs.snaps, flat.window,
                    _Subscribers,
                )
            dt_eager = time.perf_counter() - t0
            denom = max(1, n_it2 * total_hits)
            denom_t = max(1, n_it2 * batch)
            materialization_cost = {
                "total_hits": total_hits,
                "overflow_rows": ovf_rows,
                # per-HIT is the acceptance number at dense workloads
                # (~11 hits/topic at 1M subs); per-TOPIC disambiguates
                # sparse runs where per-row view overhead dominates
                "lazy_consume_ns_per_hit": round(dt_lazy * 1e9 / denom, 1),
                "lazy_consume_ns_per_topic": round(
                    dt_lazy * 1e9 / denom_t, 1
                ),
                "eager_materialize_ns_per_hit": round(
                    dt_eager * 1e9 / denom, 1
                ),
                "lazy_speedup": round(dt_eager / max(1e-9, dt_lazy), 2),
                "lazy_consume_topics_per_sec": round(
                    n_it2 * batch / max(1e-9, dt_lazy)
                ),
            }
        else:
            materialization_cost = None

    # device-compute only: resident pre-uploaded inputs, async dispatch
    # with one final sync — the kernel's sustained rate, transfers excluded.
    # Completion is forced by a dependent scalar reduce + D2H: on this
    # platform block_until_ready can return before execution completes
    # (PROFILE.md §2 — the source of the bogus r01 27.4M reading).
    kernel_rate = None
    kernel_best = None
    if hasattr(matcher, "match_tokens"):
        red = jax.jit(lambda o: o.sum())
        salt = matcher.csr.salt
        # the kernel is gather-bound (PROFILE.md §3): per-batch cost is
        # ~P*B row-gathers plus a fixed per-dispatch overhead that is
        # ms-scale and volatile on the tunnel. Measure the sustained rate
        # at a batch large enough to amortize the dispatch floor, like any
        # throughput kernel is measured at its operating point; the e2e
        # and latency numbers above keep the staging batch.
        fast = os.environ.get("BENCH_FAST") == "1"
        kb = max(
            batch,
            int(os.environ.get("BENCH_KERNEL_BATCH", batch if fast else 65536)),
        )
        kbatches = [[topic_gen() for _ in range(kb)] for _ in range(2)]
        resident = [
            tuple(
                jnp.asarray(a)
                for a in tokenize_topics(bt, matcher.max_levels, salt)[:4]
            )
            for bt in kbatches
        ]
        jax.block_until_ready(resident)  # H2D outside the timed loop
        np.asarray(red(matcher.match_tokens(*resident[0])[0]))
        # median of several timed windows: the tunneled device's effective
        # gather rate varies ~1.5x over minutes (PROFILE.md §2), so one
        # window can land in a throttled patch
        kiters = max(4, (max(iters, 50) * batch) // (4 * kb))
        rates = []
        for _w in range(5):
            t0 = time.perf_counter()
            outs = [
                matcher.match_tokens(*resident[i % len(resident)])[0]
                for i in range(kiters)
            ]
            np.asarray(red(outs[-1]))  # dependent scalar D2H = true completion
            rates.append((kiters * kb) / (time.perf_counter() - t0))
        kernel_rate = sorted(rates)[len(rates) // 2]
        kernel_best = max(rates)

    tel_block = telemetry_block(
        lat,
        "device_batch",
        fallbacks={
            "host_fallbacks": fallbacks,
            "overflows": overflows,
            "host_fast": matcher.stats.host_fast,
        },
        fill={"p50": 1.0, "note": "fixed-size bench batches"},
    )
    if profiler.compact_d2h_hist.count:
        # the compaction d2h leg as its own stage row so stage_gate
        # diffs it round over round (a new name passes through its
        # new_stage_names notice on the first post-compaction round)
        h = profiler.compact_d2h_hist
        tel_block["stages"]["compact_d2h"] = {
            "count": h.count,
            "p50_ms": round(h.percentile(0.5) * 1e3, 3),
            "p99_ms": round(h.percentile(0.99) * 1e3, 3),
        }
    return {
        "e2e_matches_per_sec": round((iters * batch) / e2e_dt),
        # recompiles observed during the steady-state pipelined loop
        # (must be 0: fixed-size batches after warmup; nonzero means the
        # PR 11 capacity-churn bug is back — attribution names the
        # kernel/shape so the regression is diagnosable from the artifact)
        "steady_state_recompiles": steady_recompiles,
        "recompile_attribution": (
            LEDGER.attribution(ledger_t0) if steady_recompiles else None
        ),
        # kernel duty cycle / transfer-compute overlap / idle gaps over
        # the pipelined e2e loop (mqtt_tpu.tracing.DeviceProfiler) — the
        # ROADMAP item 1 gap, measured per round; carries the compaction
        # transfer ledger (d2h bytes actual vs padded, reduction ratios)
        "device_pipeline": device_pipeline,
        "telemetry": tel_block,
        "device_kernel_matches_per_sec": round(kernel_rate) if kernel_rate else None,
        # best of the timed windows: the tunnel's per-dispatch overhead is
        # volatile (PROFILE.md §2); median is the headline, best shows the
        # kernel when a window misses the throttled patches
        "device_kernel_best_window": round(kernel_best) if kernel_best else None,
        "p99_batch_ms": round(pctl(lat, 0.99) * 1e3, 3),
        "p99_bounded": p99_bounded,
        "batch": batch,
        "avg_hits_per_topic": round(hits / batch, 2),
        "host_fallback_ratio": round(fallbacks / max(1, n_topics), 5),
        "overflow_ratio": round(overflows / max(1, n_topics), 5),
        "host_fast_topics": matcher.stats.host_fast,
        # the host materialization rate with transfers excluded: the e2e
        # ceiling on a directly-attached device (link-normalized)
        "link_normalized_resolve_per_sec": resolve_rate,
        # per-hit consume cost A/B over the same device result (ISSUE
        # 13): lazy targets() vs eager dict expansion; None sans C
        "materialization_cost": materialization_cost,
    }


# -- configs ----------------------------------------------------------------


def run_cfg1(rng, fast, batch):
    from mqtt_tpu.ops import TpuMatcher

    index, topic_gen = build_cfg1(rng)
    host_rate = time_host(index, topic_gen, 2000 if fast else 20000)
    matcher = TpuMatcher(index, max_levels=4, frontier=8, out_slots=32, transfer_slots=8, compact=bench_compact(), lazy=bench_lazy())
    matcher.rebuild()
    parity_check(matcher, index, topic_gen)
    # same batch as the other configs: the tunnel's per-dispatch overhead
    # (ms-scale, volatile — PROFILE.md §2) swamps sub-4K batches
    m = time_matcher(matcher, index, topic_gen, batch, 10 if fast else 30)
    m["host_matches_per_sec"] = round(host_rate)
    m["device_speedup_vs_host"] = round(m["e2e_matches_per_sec"] / host_rate, 2)
    return m


def run_cfg2(n_subs, batch, iters, rng):
    from mqtt_tpu.ops import TpuMatcher

    index, topic_gen = build_cfg2(n_subs, rng)
    matcher = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16, compact=bench_compact(), lazy=bench_lazy())
    t0 = time.perf_counter()
    matcher.rebuild()
    log(f"cfg2 index build {time.perf_counter()-t0:.1f}s nodes={matcher.csr.num_nodes}")
    parity_check(matcher, index, topic_gen)
    m = time_matcher(matcher, index, topic_gen, batch, iters)
    # the device-observability plane's sampled-path cost (ISSUE 18
    # acceptance: <= 2%), measured on the same warmed matcher by the
    # PR 7/14 interleaved-A/B method
    m["devicestats_overhead"] = _devicestats_overhead_block(
        matcher, topic_gen, batch
    )
    return m


def _devicestats_overhead_block(matcher, topic_gen, batch) -> dict:
    """ISSUE 18 acceptance leg: what the compile watch + per-device
    profiler windows cost on the hot dispatch path. Interleaved best-of-3
    rounds (the PR 7/14 method — sequential arm-then-arm would measure
    tunnel drift, not the plane) of the same pipelined loop with the
    plane fully ON (KernelWatch signatures + per-device fold) vs OFF,
    plus the deterministic micro-number: one signature probe per jitted
    dispatch, the exact added steady-state work."""
    from mqtt_tpu.ops import devicestats
    from mqtt_tpu.tracing import DeviceProfiler

    batches = [[topic_gen() for _ in range(batch)] for _ in range(2)]
    matcher.match_topics(batches[0])  # warm both executables

    def one_round(enabled: bool) -> float:
        devicestats.set_watch_enabled(enabled)
        if hasattr(matcher, "profiler"):
            matcher.profiler = DeviceProfiler() if enabled else None
        n_it = 6
        t0 = time.perf_counter()
        pend = matcher.match_topics_async(batches[0])
        for i in range(1, n_it + 1):
            nxt = (
                matcher.match_topics_async(batches[i % 2])
                if i < n_it
                else None
            )
            pend()
            pend = nxt
        dt = time.perf_counter() - t0
        if hasattr(matcher, "profiler"):
            matcher.profiler = None
        return n_it * batch / dt

    on_rate = off_rate = 0.0
    try:
        for _rep in range(3):
            on_rate = max(on_rate, one_round(True))
            off_rate = max(off_rate, one_round(False))
    finally:
        devicestats.set_watch_enabled(True)

    # deterministic micro: the signature probe a watched kernel pays per
    # DISPATCH (not per message) in steady state — harness-noise-free,
    # the number the <=2% bar is judged against on noisy links
    import jax.numpy as jnp

    probe_args = (
        jnp.zeros((batch, 8), jnp.int32),
        jnp.zeros((64,), jnp.int32),
    )
    n = 20_000
    t0 = time.perf_counter()
    for _ in range(n):
        devicestats._sig_of(probe_args, {})
    per_probe_ns = (time.perf_counter() - t0) / n * 1e9
    # ... plus the per-device window fold one profiled batch pays
    # (tracing._DevWindow): dispatch+resolve notes over a stamped record
    from mqtt_tpu.tracing import BatchProfile

    fold_prof = DeviceProfiler()
    nf = 20_000
    t0 = time.perf_counter()
    tb = time.perf_counter()
    for i in range(nf):
        rec = BatchProfile()
        rec.devices = (0,)
        rec.d2h_bytes = 4096
        fold_prof.note_dispatch(rec, tb, tb + 1e-4)
        fold_prof.note_resolve(rec, tb + 2e-4, tb + 3e-4)
    per_fold_ns = (time.perf_counter() - t0) / nf * 1e9
    per_batch_ns = per_probe_ns + per_fold_ns
    out = {
        "enabled_matches_per_sec": round(on_rate),
        "disabled_matches_per_sec": round(off_rate),
        "overhead_pct": round(
            (off_rate - on_rate) / max(1.0, off_rate) * 100, 2
        ),
        "sig_probe_ns_per_dispatch": round(per_probe_ns, 1),
        "device_fold_ns_per_batch": round(per_fold_ns, 1),
    }
    if off_rate > 0:
        # the plane's exact added work as a fraction of one batch's wall
        # budget — harness-noise-free, the <=2% acceptance figure (the
        # macro pct above inherits the loopback/tunnel jitter)
        out["amortized_overhead_pct"] = round(
            per_batch_ns / (1e9 * batch / off_rate) * 100, 4
        )
    return out


def run_cfg3(n_subs, batch, iters, rng):
    from mqtt_tpu.ops import TpuMatcher

    index, topic_gen = build_cfg3(n_subs, rng)
    # deep fan-in: a topic can gather hundreds of '#' subs — bigger output
    # window keeps the device path useful instead of 100% host fallback
    matcher = TpuMatcher(index, max_levels=8, frontier=8, out_slots=256, transfer_slots=32, compact=bench_compact(), lazy=bench_lazy())
    t0 = time.perf_counter()
    matcher.rebuild()
    log(f"cfg3 index build {time.perf_counter()-t0:.1f}s nodes={matcher.csr.num_nodes}")
    parity_check(matcher, index, topic_gen)
    return time_matcher(matcher, index, topic_gen, batch, iters)


def run_cfg4(n_groups, members, batch, iters, rng):
    from mqtt_tpu.ops import TpuMatcher

    index, topic_gen = build_cfg4(n_groups, members, rng)
    matcher = TpuMatcher(index, max_levels=4, frontier=8, out_slots=128, transfer_slots=48, compact=bench_compact(), lazy=bench_lazy())
    t0 = time.perf_counter()
    matcher.rebuild()
    log(f"cfg4 index build {time.perf_counter()-t0:.1f}s nodes={matcher.csr.num_nodes}")
    parity_check(matcher, index, topic_gen)
    return time_matcher(matcher, index, topic_gen, batch, iters, select_shared=True)


def run_cfg5(n_subs, batch, iters, rng):
    """Sub-identifiers + retained scan under live churn via DeltaMatcher."""
    from mqtt_tpu.ops.delta import DeltaMatcher
    from mqtt_tpu.packets import PUBLISH, FixedHeader, Packet, Subscription
    from mqtt_tpu.topics import TopicsIndex

    v0 = [f"region{i}" for i in range(60)]
    v1 = [f"device{i}" for i in range(60)]
    v2 = [f"metric{i}" for i in range(60)]
    index = TopicsIndex()
    for i in range(n_subs):
        parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
        if rng.random() < 0.10:
            parts[rng.randrange(3)] = "+"
        index.subscribe(
            f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3, identifier=i % 200 + 1)
        )
    for i in range(5000):  # retained corpus for the scan
        topic = f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"
        index.retain_message(
            Packet(
                fixed_header=FixedHeader(type=PUBLISH, retain=True),
                topic_name=topic,
                payload=b"r",
            )
        )

    def topic_gen():
        return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

    m = DeltaMatcher(index, max_levels=4, out_slots=64, transfer_slots=16,
                     rebuild_after=256, rebuild_interval=0.2, background=True,
                     compact=bench_compact(), lazy=bench_lazy())

    # same GC posture as the other configs (time_matcher does this): the
    # built index must not be young-gen-scanned every 700 allocations
    # while churn + rebuilds allocate heavily
    from mqtt_tpu.utils.gctune import freeze_index, tune_for_throughput

    tune_for_throughput()
    freeze_index()

    stop = threading.Event()
    mutations = [0]

    def churn():
        r = random.Random(9)
        i = n_subs
        while not stop.is_set():
            parts = [r.choice(v0), r.choice(v1), r.choice(v2)]
            if r.random() < 0.5:
                index.subscribe(f"m{i}", Subscription(filter="/".join(parts), qos=1))
                i += 1
            else:
                index.unsubscribe("/".join(parts), f"m{r.randint(n_subs, max(n_subs + 1, i))}")
            mutations[0] += 1
            time.sleep(0.0005)  # ~2k mutations/s

    th = threading.Thread(target=churn, daemon=True)
    th.start()
    try:
        batches = [[topic_gen() for _ in range(batch)] for _ in range(4)]
        m.match_topics(batches[0])  # warmup
        s0_fall = m.stats.host_fallbacks
        s0_topics = m.stats.topics
        lat, scans = [], 0
        t0 = time.perf_counter()
        pending = m.match_topics_async(batches[0])
        for i in range(1, iters + 1):
            t1 = time.perf_counter()
            nxt = m.match_topics_async(batches[i % len(batches)]) if i < iters else None
            pending()
            # retained-message wildcard scan rides along (processSubscribe path)
            index.messages(f"{rng.choice(v0)}/+/{rng.choice(v2)}")
            scans += 1
            lat.append(time.perf_counter() - t1)
            pending = nxt
        dt = time.perf_counter() - t0
        fallbacks = m.stats.host_fallbacks - s0_fall
        n_topics = m.stats.topics - s0_topics
        out = {
            "e2e_matches_per_sec": round((iters * batch) / dt),
            "telemetry": telemetry_block(
                lat,
                "device_batch",
                fallbacks={"host_fallbacks": fallbacks},
            ),
            "p99_batch_ms": round(pctl(lat, 0.99) * 1e3, 3),
            "batch": batch,
            "mutations_during_run": mutations[0],
            "retained_scans": scans,
            "host_fallback_ratio": round(fallbacks / max(1, n_topics), 5),
            "pending_deltas_at_end": m.pending_deltas,
            "snapshot_rebuilds": m.stats.rebuilds,
            "snapshot_folds": m.stats.folds,
        }
    finally:
        stop.set()
        th.join(timeout=5)
        m.close()
    # final parity after churn stopped
    for t in [topic_gen() for _ in range(16)]:
        assert canon(m.subscribers(t)) == canon(index.subscribers(t))
    return out


def run_cfg9(fast: bool, rng) -> dict:
    """Predicate-selectivity sweep (ISSUE 8 / ROADMAP item 4): device
    rule-table evaluation vs the host interpreter across pass rates.

    One DISTINCT rule per predicated subscription (thresholds uniform in
    [0,1], so a payload value v passes ~v of the population — the pass
    rate IS the payload), evaluated through the same
    ``PredicateEngine.eval_batch_async`` path the staging loop uses, so
    the measured rate is the staged-batch rate (one fused dispatch, one
    packed-bit D2H — no extra round trip). Every rate's batch is fully
    cross-checked against the host interpreter; the artifact carries the
    mismatch count, which must be zero."""
    from mqtt_tpu.predicates import PredicateEngine, eval_rule_host

    n = int(os.environ.get("BENCH_PRED_SUBS", 10_000 if fast else 100_000))
    batch = int(os.environ.get("BENCH_PRED_BATCH", 64))
    iters = 3 if fast else 10
    eng = PredicateEngine(oracle_sample=0)
    suffixes = []
    t0 = time.perf_counter()
    for i in range(n):
        s = "$GT{v:%.9f}" % rng.random()
        eng.register(s)
        suffixes.append(s)
    build_s = time.perf_counter() - t0
    out = {
        "n_rules": eng.rule_count,
        "batch": batch,
        "register_seconds": round(build_s, 3),
        "sweep": {},
        "oracle_mismatches": 0,
    }
    for rate in (0.01, 0.1, 0.5, 0.9):
        payload = json.dumps({"v": rate}).encode()
        feats = [eng.features_for(payload) for _ in range(batch)]
        resolved = eng.eval_batch_async(feats)
        if resolved is None:
            out["sweep"][str(rate)] = {"skipped": "device eval unavailable"}
            continue
        resolved()  # warmup: jit compile + first transfer
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            issued = eng.eval_batch_async(feats)
            last = issued() if issued is not None else None
        dt = time.perf_counter() - t0
        if last is None:
            # the resolver degrades to None on a device fault (it never
            # raises): record the rate as degraded instead of crashing
            out["sweep"][str(rate)] = {"skipped": "device eval degraded"}
            continue
        rows, _eligible, _gen = last
        # host-interpreter comparison rate (bounded sample: the point is
        # the order-of-magnitude gap, not a long host soak)
        host_n = min(n, 2000 if fast else 20000)
        t0 = time.perf_counter()
        for s in suffixes[:host_n]:
            eval_rule_host(eng._rules[s].spec, payload)
        host_dt = time.perf_counter() - t0
        # full differential oracle over every rule for this payload
        row = rows[0]
        mismatches = 0
        passed = 0
        for s in suffixes:
            rule = eng._rules[s]
            bit = bool((row[rule.idx >> 5] >> np.uint32(rule.idx & 31)) & 1)
            passed += bit
            if bit != eval_rule_host(rule.spec, payload):
                mismatches += 1
        out["oracle_mismatches"] += mismatches
        out["sweep"][str(rate)] = {
            "device_evals_per_sec": round(iters * batch * n / dt),
            "host_evals_per_sec": round(host_n / host_dt) if host_dt else 0,
            "observed_pass_ratio": round(passed / n, 4),
            "transfer_bytes_per_batch": int(rows.nbytes),
            "mismatches": mismatches,
        }
    if out["oracle_mismatches"]:
        log(f"cfg9 ORACLE MISMATCHES: {out['oracle_mismatches']}")
    return out


def _keystream_device_rate(fast: bool):
    """The PR 12 residual (ISSUE 18 satellite): the device keystream's
    raw sustained byte rate — resident inputs, pipelined dispatches, one
    dependent sync — on a REAL accelerator. On CPU-jax the 'device' path
    is the same host silicon the vectorized-host path uses, so the
    number would be a fiction: the zero-headline rule applies and the
    cell records an honest skip instead."""
    try:
        import jax
        import jax.numpy as jnp
    except ImportError:
        return {"skipped": True, "skip_reason": "jax not importable"}
    platform = getattr(jax.devices()[0], "platform", "cpu")
    if platform == "cpu":
        return {
            "skipped": True,
            "skip_reason": "CPU-jax backend: device keystream bytes/s "
            "is only meaningful on a real accelerator",
        }
    from mqtt_tpu.ops.recrypt import BLOCK, ctr_counters, keystream
    from mqtt_tpu.tenancy import KeyRegistry

    reg = KeyRegistry()
    for k in range(64):
        reg.set_key("bt0", f"c{k}", bytes([k % 256]) * 16)
    table = reg.table()
    n_blocks = 1 << (12 if fast else 16)  # 64 KiB / 1 MiB of keystream
    kidx = np.arange(n_blocks, dtype=np.int32) % 64
    counters = ctr_counters(b"bnks" * 3, n_blocks)
    args = (jnp.asarray(table), jnp.asarray(kidx), jnp.asarray(counters))
    jax.block_until_ready(args)
    np.asarray(keystream(*args))  # warm the executable
    red = jax.jit(lambda o: o.sum())
    iters = 8 if fast else 32
    rates = []
    for _w in range(3):
        t0 = time.perf_counter()
        outs = [keystream(*args) for _ in range(iters)]
        np.asarray(red(outs[-1]))  # dependent D2H = true completion
        rates.append(iters * n_blocks * BLOCK / (time.perf_counter() - t0))
    return round(sorted(rates)[len(rates) // 2])


def run_cfg10(fast: bool, rng) -> dict:
    """Tenants x keys x fan-out re-encryption matrix (ISSUE 12 /
    ROADMAP item 6): the MQT-TZ stage measured at the engine seam —
    decrypt-once + ONE batched per-subscriber keystream dispatch per
    fan-out tick — against the plaintext fan-out baseline: the
    per-subscriber Packet copy + encode the unencrypted per-subscriber
    delivery path pays (re-encrypted fan-out can never share frames,
    so THAT is the path it displaces). Each cell A/Bs the device
    keystream against the vectorized-host path (the breaker's
    degradation target — on a CPU-jax box the host path is usually the
    deployable config; on a real accelerator the device path wins) and
    the acceptance ratio takes the better deployable path. Sampled
    device dispatches are differentially checked (mismatches must be
    zero)."""
    from mqtt_tpu.packets import ENCODERS, PUBLISH, FixedHeader, Packet
    from mqtt_tpu.tenancy import KeyRegistry, RecryptEngine, TenantPlane

    n_tenants = 2 if fast else 4
    keys_per_tenant = int(
        os.environ.get("BENCH_RECRYPT_KEYS", 16 if fast else 128)
    )
    fanouts = (10, 100)
    payload_sizes = (256, 4096)
    iters = 20 if fast else 100
    reg = KeyRegistry()
    plane = TenantPlane()
    tenants = []
    t0 = time.perf_counter()
    for t in range(n_tenants):
        name = f"bt{t}"
        tenant = plane.register(name, encrypted=("e/",))
        tenants.append(tenant)
        for k in range(keys_per_tenant):
            reg.set_key(name, f"c{k}", bytes([t, k % 256]) * 8)
    build_s = time.perf_counter() - t0
    eng = RecryptEngine(reg, oracle_sample=16, device_min_blocks=1)
    eng.reseed_nonce(b"bnch")
    out: dict = {
        "tenants": n_tenants,
        "keys_per_tenant": keys_per_tenant,
        "key_setup_seconds": round(build_s, 3),
        "matrix": {},
        "oracle_mismatches": 0,
    }
    worst_ratio_at_100 = 0.0
    for size in payload_sizes:
        plaintext = (bytes(range(256)) * (size // 256 + 1))[:size]
        for fanout in fanouts:
            tenant = tenants[0]
            targets = [
                (f"c{i % keys_per_tenant}", (f"c{i % keys_per_tenant}",))
                for i in range(fanout)
            ]
            wire = eng.seal_with_key(bytes([0, 0]) * 8, plaintext)
            # plaintext baseline: per-subscriber Packet copy + encode
            # (what the per-subscriber plaintext delivery path pays; the
            # recrypt path pays the same copies PLUS the crypto)
            pk = Packet(
                fixed_header=FixedHeader(type=PUBLISH),
                topic_name="e/bench/topic",
                payload=plaintext,
            )
            t0 = time.perf_counter()
            for _ in range(iters):
                for _t in targets:
                    o = pk.copy(False)
                    buf = bytearray()
                    ENCODERS[PUBLISH](o, buf)
            base_dt = time.perf_counter() - t0

            def recrypt_leg(engine) -> float:
                # warmup (jit compile / first-touch of the shapes)
                job = engine.decrypt_job(tenant, ("c0",), wire)
                pt = engine.open_publish(tenant, ("c0",), wire, job)
                assert pt == plaintext
                engine.seal_fanout(tenant, pt, targets)
                t0 = time.perf_counter()
                for _ in range(iters):
                    job = engine.decrypt_job(tenant, ("c0",), wire)
                    pt = engine.open_publish(tenant, ("c0",), wire, job)
                    sealed = engine.seal_fanout(tenant, pt, targets)
                    for _t in targets:
                        o = pk.copy(False)
                        o.payload = sealed.get(_t[0], b"")
                        buf = bytearray()
                        ENCODERS[PUBLISH](o, buf)
                return time.perf_counter() - t0

            dev_dt = recrypt_leg(eng)
            # A/B: the vectorized-host keystream path (the breaker's
            # degradation target; usually the deployable config on a
            # CPU-jax box)
            host_eng = RecryptEngine(
                reg, oracle_sample=0, device_min_blocks=1 << 30
            )
            host_eng.reseed_nonce(b"bnhh")
            host_dt = recrypt_leg(host_eng)
            rec_dt, path = min((dev_dt, "device"), (host_dt, "host"))
            base_rate = iters * fanout / base_dt if base_dt else 0.0
            ratio = rec_dt / base_dt if base_dt else float("inf")
            if fanout == 100:
                worst_ratio_at_100 = max(worst_ratio_at_100, ratio)
            out["matrix"][f"payload{size}_fanout{fanout}"] = {
                "plaintext_deliveries_per_sec": round(base_rate),
                "recrypt_deliveries_per_sec": round(
                    iters * fanout / rec_dt
                )
                if rec_dt
                else 0,
                "recrypt_vs_plaintext_ratio": round(ratio, 3),
                "best_path": path,
                "device_path_ratio": round(dev_dt / base_dt, 3)
                if base_dt
                else None,
                "host_path_ratio": round(host_dt / base_dt, 3)
                if base_dt
                else None,
            }
    out["device_batches"] = eng.device_batches
    out["oracle_mismatches"] = eng.oracle_mismatches
    out["kernel_worst_ratio_at_fanout100"] = round(worst_ratio_at_100, 3)
    # real-accelerator keystream byte rate as a TOP-LEVEL scalar so the
    # bench-history ledger keeps it and exp/bench_trend.py gates its
    # trajectory (ISSUE 18 satellite; honest skip dict on CPU-jax)
    try:
        out["keystream_device_bytes_per_sec"] = _keystream_device_rate(fast)
    except Exception as e:  # a dead link must not sink the whole matrix
        out["keystream_device_bytes_per_sec"] = {
            "skipped": True,
            "skip_reason": f"error: {e}",
        }
    # the acceptance leg: a REAL broker A/B at 100-subscriber fan-out.
    # QoS1 deliveries (the at-least-once class trust-sensitive
    # workloads run on) pay the per-subscriber copy+encode path either
    # way, so the measured ratio is what re-encryption actually costs a
    # deployment: plaintext namespace vs encrypted namespace, same
    # broker, same subscribers.
    try:
        out["broker"] = _recrypt_broker_ab(fast)
        ratio = out["broker"]["recrypt_vs_plaintext_ratio"]
        out["within_2x_at_fanout100"] = ratio <= 2.0
    except Exception as e:
        out["broker"] = {"skipped": f"error: {e}"}
        out["within_2x_at_fanout100"] = None
    if eng.oracle_mismatches:
        log(f"cfg10 ORACLE MISMATCHES: {eng.oracle_mismatches}")
    return out


def _recrypt_broker_ab(fast: bool) -> dict:
    """The cfg 10 acceptance leg: one in-process broker, 100 QoS1
    subscribers over real TCP, a publisher driving the SAME payloads
    through a plaintext topic and an encrypted-namespace topic; the
    ratio of wall-clock fan-out rates is the re-encryption overhead a
    deployment actually pays."""
    import asyncio

    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes

    port = 18845
    fanout = 100
    msgs = 30 if fast else 120
    payload_size = 256
    pub_key = bytes(range(16))
    sub_key_of = lambda i: bytes([i % 256]) * 16  # noqa: E731

    async def read_publishes(reader, counter, done_evt, want):
        """Count PUBLISH frames off one subscriber connection."""
        try:
            while counter[0] < want:
                first = await reader.readexactly(1)
                rl = 0
                mult = 1
                while True:
                    b = (await reader.readexactly(1))[0]
                    rl += (b & 0x7F) * mult
                    mult *= 128
                    if not (b & 0x80):
                        break
                body = await reader.readexactly(rl) if rl else b""
                if first[0] >> 4 == 3:  # PUBLISH
                    counter[0] += 1
                del body
            done_evt.set()
        except (asyncio.IncompleteReadError, ConnectionError):
            done_evt.set()

    async def main() -> dict:
        tenants = {
            "bt": {
                "encrypted": ["e/"],
                "keys": {"pub": pub_key.hex()},
            }
        }
        users = {"pub": "bt"}
        for i in range(fanout):
            tenants["bt"]["keys"][f"s{i}"] = sub_key_of(i).hex()
            users[f"s{i}"] = "bt"
        opts = Options(
            tenancy=True,
            tenants=tenants,
            tenant_users=users,
            telemetry=False,
            profile=False,
            # the CPU-jax box serves keystreams faster from the
            # vectorized host path (BENCH_RECRYPT_DEVICE=1 forces the
            # device kernel — the right config on a real accelerator)
            recrypt_device_min_blocks=(
                4 if os.environ.get("BENCH_RECRYPT_DEVICE") == "1" else 1 << 30
            ),
        )
        srv = Server(opts)
        srv.add_hook(AllowHook())
        srv.add_listener(
            TCP(LConfig(type="tcp", id="recrypt", address=f"127.0.0.1:{port}"))
        )
        await srv.serve()
        eng = srv._recrypt
        try:
            subs = []
            for i in range(fanout):
                r, w = await asyncio.open_connection("127.0.0.1", port)
                w.write(_connect_bytes(f"s{i}", version=4))
                await w.drain()
                await r.readexactly(4)
                w.write(_subscribe_bytes(1, "p/#", qos=1))
                await w.drain()
                await r.readexactly(5)
                w.write(_subscribe_bytes(2, "e/#", qos=1))
                await w.drain()
                await r.readexactly(5)
                subs.append((r, w))
            pr, pw = await asyncio.open_connection("127.0.0.1", port)
            pw.write(_connect_bytes("pub", version=4))
            await pw.drain()
            await pr.readexactly(4)

            plaintext = (bytes(range(256)) * 2)[:payload_size]

            async def leg(topic, payloads) -> float:
                counters = []
                dones = []
                for r, _w in subs:
                    counter = [0]
                    done = asyncio.Event()
                    counters.append(counter)
                    dones.append(done)
                    asyncio.get_running_loop().create_task(
                        read_publishes(r, counter, done, len(payloads))
                    )
                t0 = time.perf_counter()
                tb = topic.encode()
                for i, body in enumerate(payloads):
                    var = (
                        len(tb).to_bytes(2, "big")
                        + tb
                        + (i % 65534 + 1).to_bytes(2, "big")
                        + body
                    )
                    # QoS1 PUBLISH frame
                    hdr = bytearray([0x32])
                    rl = len(var)
                    while True:
                        e = rl % 128
                        rl //= 128
                        hdr.append(e | (0x80 if rl else 0))
                        if not rl:
                            break
                    pw.write(bytes(hdr) + var)
                await pw.drain()
                await asyncio.wait_for(
                    asyncio.gather(*[d.wait() for d in dones]), timeout=120
                )
                return time.perf_counter() - t0

            plain_wall = await leg("p/bench", [plaintext] * msgs)
            enc_wall = await leg(
                "e/bench",
                [eng.seal_with_key(pub_key, plaintext) for _ in range(msgs)],
            )
            total = fanout * msgs
            return {
                "fanout": fanout,
                "msgs": msgs,
                "payload_bytes": payload_size,
                "qos": 1,
                "plaintext_deliveries_per_sec": round(total / plain_wall),
                "recrypt_deliveries_per_sec": round(total / enc_wall),
                "recrypt_vs_plaintext_ratio": round(
                    enc_wall / plain_wall, 3
                ),
                "recrypt_fanouts": eng.fanouts,
                "oracle_mismatches": eng.oracle_mismatches,
                "no_key_drops": eng.no_key_drops,
            }
        finally:
            await srv.close()

    return asyncio.run(main())


def run_cfg11(fast: bool, rng) -> dict:
    """Config 11 (ISSUE 16): the durable session plane. Two legs:

    1. recovery-time vs key count over the log-structured store, A/B
       between pure log replay and snapshot+tail (the checkpoint is the
       whole point: replay cost must scale with the tail, not history);
    2. retained wildcard-scan throughput, device kernel
       (ops/retained.RetainedMatchEngine) vs the host trie walk
       (TopicsIndex.messages), with a full parity check first.
    """
    import shutil
    import tempfile

    from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore
    from mqtt_tpu.ops.retained import RetainedMatchEngine
    from mqtt_tpu.packets import PUBLISH, FixedHeader, Packet
    from mqtt_tpu.topics import TopicsIndex

    # -- leg 1: recovery-time sweep --------------------------------------
    scales = [
        int(s)
        for s in os.environ.get(
            "BENCH_DURABLE_KEYS",
            "2000,10000" if fast else "10000,100000,1000000",
        ).split(",")
        if s.strip()
    ]
    tail_every = 20  # after the checkpoint, 5% of keys get a tail update
    recovery = []
    for n in scales:
        row: dict = {"keys": n}
        for label, snap in (("log", False), ("snapshot", True)):
            d = tempfile.mkdtemp(prefix="bench-logkv-")
            try:
                s = LogKVStore()
                s.init(LogKVOptions(path=d, gc_interval=0.0))
                # session-plane shaped records (the restart workload is
                # dominated by SUB_ rows: one per persisted subscription)
                for i in range(n):
                    s._set(f"SUB_cl{i}:bench/c{i}/#", b'{"qos":1}')
                if snap:
                    s.snapshot()
                    for i in range(0, n, tail_every):
                        s._set(f"SUB_cl{i}:bench/c{i}/#", b'{"qos":2}')
                s.stop()
                t0 = time.perf_counter()
                s2 = LogKVStore()
                s2.init(LogKVOptions(path=d, gc_interval=0.0))
                dt = time.perf_counter() - t0
                st = s2.durable_stats()
                s2.stop()
                if st["keys"] != n:
                    raise AssertionError(
                        f"cfg11 recovery lost keys: {st['keys']} != {n}"
                    )
                row[f"recovery_s_{label}"] = round(dt, 4)
                row[f"replayed_keys_{label}"] = st["replayed_keys"]
            finally:
                shutil.rmtree(d, ignore_errors=True)
        row["snapshot_speedup"] = round(
            row["recovery_s_log"] / max(row["recovery_s_snapshot"], 1e-9), 2
        )
        recovery.append(row)
        log(f"cfg11 recovery {row}")
    top = recovery[-1]

    # -- leg 2: retained matching, device kernel vs host walk ------------
    n_ret = 2_000 if fast else 50_000
    idx = TopicsIndex()
    for i in range(n_ret):
        idx.retain_message(
            Packet(
                fixed_header=FixedHeader(type=PUBLISH, retain=True),
                topic_name=(
                    f"region{i % 40}/device{(i // 40) % 50}"
                    f"/metric{i // 2000}"
                ),
                payload=b"r",
            )
        )
    # wildcard shapes only: the engine declines exact filters by design
    # (a host dict hit beats any kernel), so they would bench the
    # fallback path, not the kernel
    filters = []
    for k in range(64):
        filters.append(
            [
                f"region{k % 40}/device{k % 50}/+",
                f"region{k % 40}/+/metric{k % 25}",
                f"region{k % 40}/#",
                f"+/device{k % 50}/metric{k % 25}",
            ][k % 4]
        )
    eng = RetainedMatchEngine(idx, max_levels=8, oracle_sample=0)
    eng.reseed()
    mismatched = 0
    for f in filters:  # parity first: the speed of a wrong scan is noise
        dev = eng.match(f)
        host = {pk.topic_name for pk in idx.messages(f)}
        if dev is None or set(dev) != host:
            mismatched += 1
    rounds = 4 if fast else 20

    t0 = time.perf_counter()
    for _ in range(rounds):
        for f in filters:
            eng.match(f)
    dev_dt = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(rounds):
        for f in filters:
            idx.messages(f)
    host_dt = time.perf_counter() - t0
    scans = rounds * len(filters)

    out = {
        # top-level scalars are what the history ledger keeps (and what
        # exp/bench_trend.py gates): replay throughput at the largest
        # scale + the device scan rate, both higher-is-better
        "recovery_keys_per_sec": round(
            top["keys"] / max(top["recovery_s_snapshot"], 1e-9)
        ),
        "recovery_s_log": top["recovery_s_log"],
        "recovery_s_snapshot": top["recovery_s_snapshot"],
        "snapshot_speedup": top["snapshot_speedup"],
        "max_keys": top["keys"],
        "retained_corpus": n_ret,
        "retained_device_scans_per_sec": round(scans / max(dev_dt, 1e-9)),
        "retained_host_scans_per_sec": round(scans / max(host_dt, 1e-9)),
        "retained_device_vs_host": round(host_dt / max(dev_dt, 1e-9), 3),
        "retained_parity_mismatches": mismatched,
        "recovery": recovery,
    }
    if mismatched:
        log(f"cfg11 RETAINED PARITY MISMATCHES: {mismatched}")
    return out


def run_cfg12(fast: bool, rng) -> dict:
    """Config 12 (ISSUE 17): the mesh predicate push-down gate in
    isolation — no sockets, no jax. One tree-mode Cluster gets a
    hand-installed edge summary whose subtree holds ONLY a predicated
    subscriber (``pp/#$GT{v:50}``): the exact shape where push-down
    earns its keep, because the plain bloom misses and every forward
    hinges on evaluating the interned rule against the payload. Three
    legs over ``_route_edges``:

    1. failing payloads — the edge must be SKIPPED every time (the
       filtered ratio is asserted at 1.0: a silent degradation to
       pass-through is a correctness bug, not a slow round);
    2. passing payloads — the edge must forward every time;
    3. a bloom-miss topic — the PR 9 topic gate, for scale.
    """
    import shutil
    import tempfile

    from mqtt_tpu.cluster import Cluster, _EdgeSummary
    from mqtt_tpu.mesh_topology import BloomBits, CountedBloom
    from mqtt_tpu.predicates import predicate_digest
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.topics import summary_base

    d = tempfile.mkdtemp(prefix="bench-mesh-pushdown-")
    try:
        srv = Server(
            Options(telemetry=False, profile=False, cluster_topology="tree")
        )
        cl = Cluster(srv, 0, 2, d)
        ep = cl.topo.epoch
        sfx = "$GT{v:50}"
        interest = CountedBloom()
        interest.add(summary_base("pp/#" + sfx))
        cl._edge_summaries[1] = _EdgeSummary(
            interest.bits(),
            1,
            (ep.num, ep.boot, ep.proposer),
            plain=BloomBits.empty(),
            digests=((predicate_digest(sfx), sfx),),
        )

        n = 20_000 if fast else 200_000
        # a pool of distinct payloads so the JSON parse inside the gate
        # is paid on every call, like live traffic — not one hot string
        fails = [
            json.dumps({"v": rng.randint(0, 50), "seq": i}).encode()
            for i in range(256)
        ]
        passes = [
            json.dumps({"v": rng.randint(51, 500), "seq": i}).encode()
            for i in range(256)
        ]
        route = cl._route_edges

        base_filtered = cl.summary_predicate_filtered_forwards
        t0 = time.perf_counter()
        for i in range(n):
            route("pp/x", None, payload=fails[i & 255])
        fail_dt = time.perf_counter() - t0
        filtered = cl.summary_predicate_filtered_forwards - base_filtered

        forwarded = 0
        t0 = time.perf_counter()
        for i in range(n):
            forwarded += len(route("pp/x", None, payload=passes[i & 255]))
        pass_dt = time.perf_counter() - t0

        base_bloom = cl.summary_filtered_forwards
        t0 = time.perf_counter()
        for i in range(n):
            route("zz/x", None, payload=passes[i & 255])
        bloom_dt = time.perf_counter() - t0
        bloom_filtered = cl.summary_filtered_forwards - base_bloom

        ratio = filtered / max(n, 1)
        if ratio != 1.0 or forwarded != n or bloom_filtered != n:
            # a gate that stops filtering (or worse, stops forwarding)
            # must fail the round loudly, not post a smaller number
            raise AssertionError(
                f"cfg12 gate broke: filtered={filtered}/{n} "
                f"forwarded={forwarded}/{n} bloom={bloom_filtered}/{n}"
            )
        out = {
            "pushdown_filter_evals_per_sec": round(n / max(fail_dt, 1e-9)),
            "pushdown_forward_evals_per_sec": round(n / max(pass_dt, 1e-9)),
            "bloom_gate_evals_per_sec": round(n / max(bloom_dt, 1e-9)),
            "pushdown_filtered_ratio": ratio,
            "evals": n,
        }
        log(f"cfg12 pushdown {out}")
        return out
    finally:
        shutil.rmtree(d, ignore_errors=True)


def run_materializer_bench(fast: bool) -> dict:
    """Config 7: the host result materializer in isolation — NO device, no
    jax. Synthetic snapshot tables and packed range rows shaped like cfg2's
    (window 16, P=4, ~11 hits/topic at 1M-sub scale) feed the C extension
    (native/accelmod.c) and the pure-Python oracle. This is the round-5
    north-star bottleneck component (PROFILE.md §4/§8), measured in a form
    the driver can capture even when the device tunnel is down."""
    import random as _r

    from mqtt_tpu.ops.flat import _LazySubTable
    from mqtt_tpu.ops.matcher import _accel, expand_sids
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import Subscribers
    from mqtt_tpu.utils.gctune import tune_for_throughput

    tune_for_throughput()
    rng = _r.Random(7)
    window, P = 16, 4
    n_entries = 5_000 if fast else 80_000
    batch = 1024 if fast else 16384
    snaps = []
    for e in range(n_entries):
        n_cli = rng.randint(1, 7)  # E[hits/topic] = 0.7*4*4 ~ 11, matching cfg2
        snaps.append(
            (
                tuple(
                    (
                        f"cl{e}_{i}",
                        Subscription(
                            filter=f"f/{e}", qos=rng.randint(0, 2),
                            identifier=rng.choice([0, 0, 0, e % 200 + 1]),
                        ),
                    )
                    for i in range(n_cli)
                ),
                (),
                (),
            )
        )
    totals = [len(s[0]) for s in snaps]
    packed = np.zeros((batch, 2 * P + 2), dtype=np.int32)
    for i in range(batch):
        for p in range(P):
            if rng.random() < 0.7:
                e = rng.randrange(n_entries)
                packed[i, p] = e * window
                packed[i, P + p] = totals[e]
    hits = int(packed[:, P : 2 * P].sum())
    out = {"batch": batch, "avg_hits_per_topic": round(hits / batch, 2)}
    iters = 3 if fast else 10
    acc = _accel()
    if acc is not None:
        acc.resolve_batch(packed, batch, P, snaps, window, Subscribers)  # warm
        c_lat = []
        t0 = time.perf_counter()
        for _ in range(iters):
            t1 = time.perf_counter()
            acc.resolve_batch(packed, batch, P, snaps, window, Subscribers)
            c_lat.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        out["c_materializer_topics_per_sec"] = round(iters * batch / dt)
        out["c_materializer_subs_per_sec"] = round(iters * hits / dt)
        out["telemetry"] = telemetry_block(c_lat, "materialize")
    # the pure-Python oracle (the pre-round-5 ceiling), on a slice to keep
    # the config cheap
    table = _LazySubTable(window, list(snaps), n_entries * window)
    rows = packed[: max(256, batch // 8)].tolist()
    py_lat = []
    t0 = time.perf_counter()
    for row in rows:
        t1 = time.perf_counter()
        sids = []
        for p in range(P):
            c = row[P + p]
            if c:
                sids.extend(range(row[p], row[p] + c))
        expand_sids(table, sids, Subscribers())
        py_lat.append(time.perf_counter() - t1)
    dt = time.perf_counter() - t0
    out["python_oracle_topics_per_sec"] = round(len(rows) / dt)
    if "telemetry" not in out:  # no C module: the oracle is the stage
        out["telemetry"] = telemetry_block(py_lat, "materialize_oracle")
    if "c_materializer_topics_per_sec" in out:
        out["c_speedup_vs_python"] = round(
            out["c_materializer_topics_per_sec"] / out["python_oracle_topics_per_sec"], 2
        )
    return out


def run_broker_bench(fast: bool) -> dict:
    """The mqtt-stresser analog over real TCP against a broker subprocess
    (reference README.md:474-508): N clients x M QoS0 msgs on own topics,
    per-client publish/receive medians + aggregate. The broker runs in its
    own process (no jax); the load generator runs here. CPU count is
    reported because both timeshare this machine's cores."""
    import subprocess

    from mqtt_tpu.stress import run_stress

    port = 18831
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # multi-core data plane (mqtt_tpu.cluster): one SO_REUSEPORT worker
    # per core when the host has them — the scale-out the reference gets
    # from goroutine-per-connection; a 1-core host stays single-process
    # (workers would only timeshare the core and pay mesh overhead)
    workers = max(1, int(os.environ.get("BENCH_BROKER_WORKERS", os.cpu_count() or 1)))
    cmd = [sys.executable, "-m", "mqtt_tpu.stress", "--serve", "--broker",
           f"127.0.0.1:{port}"]
    if workers > 1:
        cmd += ["--workers", str(workers)]
    proc = subprocess.Popen(
        cmd,
        stdin=subprocess.PIPE,
        stdout=subprocess.PIPE,
        cwd=repo,
        env=env,
    )
    out = {"cpus": os.cpu_count(), "broker_workers": workers}
    try:
        assert proc.stdout.readline().strip() == b"READY"
        # the reference table's exact mqtt-stresser scenarios: 2/10/100
        # clients x 10000 messages each (README.md:482-506)
        scenarios = (
            [(2, 1000), (10, 500)]
            if fast
            else [(2, 10000), (10, 10000), (100, 10000)]
        )
        for n, m in scenarios:
            import asyncio

            r = asyncio.run(run_stress("127.0.0.1", port, n, m))
            out[f"{n}_clients"] = r
            log(f"broker {n}x{m}: {r}")
        # the reference table's 100-client receive median (mochi v2.2.10,
        # M2, 8 cores): 7,274 msg/s (README.md:500-503). Receive is the
        # honest end-to-end rate; QoS0 publish rates on both sides mostly
        # measure socket-buffer writes, so no publish ratio is reported.
        hundred = out.get("100_clients")
        if hundred:
            out["vs_mochi_100c_receive"] = round(
                hundred["receive_median_per_sec"] / 7274, 4
            )
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()
    return out


def run_conn_rate_qos_matrix(fast: bool) -> dict:
    """Config 8's connections × rate × QoS comparative matrix (the
    PAPERS.md 2603.21600 reporting frame; ISSUE 13): a subprocess
    broker (one SO_REUSEPORT worker per core, the run_broker_bench
    posture) driven through every (clients, msgs/client, QoS) cell.
    Every cell carries its OWN publish/receive medians so rounds diff
    cell by cell; BENCH_LAZY=0 re-runs the whole matrix on the legacy
    eager/per-subscriber path (the serve-side broker honors the knob)."""
    import asyncio
    import subprocess

    from mqtt_tpu.stress import run_stress

    port = 18852
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    workers = max(
        1, int(os.environ.get("BENCH_BROKER_WORKERS", os.cpu_count() or 1))
    )
    cmd = [
        sys.executable, "-m", "mqtt_tpu.stress", "--serve", "--broker",
        f"127.0.0.1:{port}",
    ]
    if workers > 1:
        cmd += ["--workers", str(workers)]
    proc = subprocess.Popen(
        cmd, stdin=subprocess.PIPE, stdout=subprocess.PIPE, cwd=repo, env=env
    )
    cells = (
        [(2, 300, 0), (2, 300, 1), (6, 150, 0), (6, 150, 1)]
        if fast
        else [
            (10, 2000, 0), (10, 2000, 1),
            (100, 600, 0), (100, 600, 1),
            (100, 2000, 0), (100, 2000, 1),
        ]
    )
    matrix = []
    try:
        assert proc.stdout.readline().strip() == b"READY"
        for n, m, q in cells:
            r = asyncio.run(run_stress("127.0.0.1", port, n, m, qos=q))
            matrix.append(r)
            log(
                f"matrix {n}c x {m}m qos{q}: "
                f"{r['aggregate_msgs_per_sec']}/s "
                f"recv_median {r['receive_median_per_sec']}/s"
            )
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()
    return {
        "lazy": bench_lazy(),
        "broker_workers": workers,
        "cells": matrix,
    }


def run_idle_conn_matrix(fast: bool) -> dict:
    """Config 8's connection-scale axis (ISSUE 15): a subprocess broker
    running the event-loop shard fabric holds a MOSTLY-IDLE device
    population (the 2603.21600 connection axis — 1k/10k attached
    connections that never publish) while a small active set measures
    per-cell receive medians. Each cell's ``receive_flatness_ratio`` is
    its active-receive-median against the 0-idle baseline cell — a flat
    front-end holds ~1.0 as the idle population grows.

    ``BENCH_SHARDS=1`` re-runs the whole matrix on the single-loop
    front-end (the serve-side broker honors the knob); the shard count
    itself comes from ``BENCH_SHARD_COUNT`` (default ``max(2, cpus)``).
    The idle ramp adapts to the bench process's fd budget (2 fds per
    connection in this harness) — dropped cells are recorded, never
    silently skipped."""
    import asyncio
    import resource
    import subprocess

    from mqtt_tpu.stress import ramp_idle, run_stress

    port = 18862
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    shards = 1
    if os.environ.get("BENCH_SHARDS") != "1":
        shards = int(
            os.environ.get("BENCH_SHARD_COUNT", max(2, os.cpu_count() or 1))
        )
        env["MQTT_TPU_LOOP_SHARDS"] = str(shards)
    levels_env = os.environ.get("BENCH_IDLE_LEVELS")
    if levels_env:
        # operator override, e.g. BENCH_IDLE_LEVELS=0,1000,10000 — a
        # fast-mode run can still measure the full connection axis
        idle_levels = [int(x) for x in levels_env.split(",") if x.strip()]
    else:
        idle_levels = [0, 200] if fast else [0, 1000, 10000]
    active, msgs = (4, 150) if fast else (10, 500)

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    # the broker runs in a SUBPROCESS with its own fd table: this
    # process pays one fd per idle connection (the client side)
    budget = max(0, soft - 1024)

    proc = subprocess.Popen(
        [
            sys.executable, "-m", "mqtt_tpu.stress", "--serve", "--broker",
            f"127.0.0.1:{port}",
        ],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, cwd=repo, env=env,
    )
    cells = []
    dropped = []
    idle_writers: list = []

    async def drive() -> None:
        attached = 0
        baseline = None
        for level in idle_levels:
            if level > budget:
                dropped.append(level)
                log(f"idle-conn cell {level} dropped (fd budget {budget})")
                continue
            if level > attached:
                t0 = time.perf_counter()
                idle_writers.extend(
                    await ramp_idle(
                        "127.0.0.1", port, level - attached,
                        client_prefix=f"bench-idle-{attached}",
                    )
                )
                ramp_s = time.perf_counter() - t0
                attached = level
            else:
                ramp_s = 0.0
            r = await run_stress("127.0.0.1", port, active, msgs)
            cell = {
                "idle_connections": level,
                "clients": active,
                "msgs_per_client": msgs,
                "ramp_seconds": round(ramp_s, 2),
                "publish_median_per_sec": r["publish_median_per_sec"],
                "receive_median_per_sec": r["receive_median_per_sec"],
                "receive_min_per_sec": r["receive_min_per_sec"],
                "aggregate_msgs_per_sec": r["aggregate_msgs_per_sec"],
            }
            if baseline is None:
                baseline = max(1e-9, r["receive_median_per_sec"])
            cell["receive_flatness_ratio"] = round(
                r["receive_median_per_sec"] / baseline, 4
            )
            cells.append(cell)
            log(
                f"idle-conn cell {level}: recv_median "
                f"{r['receive_median_per_sec']}/s flatness "
                f"{cell['receive_flatness_ratio']}"
            )
        for w in idle_writers:
            try:
                w.close()
            except Exception:
                pass

    try:
        assert proc.stdout.readline().strip() == b"READY"
        asyncio.run(drive())
    finally:
        try:
            proc.stdin.close()
            proc.wait(timeout=10)
        except Exception:
            proc.kill()
    return {
        "loop_shards": shards,
        "idle_levels": idle_levels,
        "dropped_levels": dropped,
        "cells": cells,
    }


async def _flatness_profile_block(fast: bool) -> dict:
    """Config 8's host-observatory leg (mqtt_tpu.profiling): the
    per-client receive-rate flatness ratio (10 vs 100 clients — ROADMAP
    item 3's success criterion), the host-profile artifact at the
    100-client point (top contended locks + fan-out amplification), and
    an A/B overhead probe — the same 100-client workload with the
    profiler+lock plane enabled vs disabled (the acceptance bar is
    <=2% aggregate msgs/s; both numbers land in the artifact so the
    claim is re-checkable every round). Device matcher off: the
    collapse under study is the pure broker write path."""
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import run_flatness, run_stress

    small, large = (4, 20) if fast else (10, 100)
    m_small, m_large = (300, 120) if fast else (2000, 600)

    from mqtt_tpu.utils.locked import DEFAULT_PLANE

    async def one_round(port: int, profile_on: bool) -> tuple[dict, dict, int]:
        # the lock plane aggregates process-wide by name: reset so this
        # round's top-contended list reflects THIS workload, not the
        # storm phase that ran earlier in the same process
        DEFAULT_PLANE.reset()
        srv = Server(
            Options(
                device_matcher=False,
                profile=profile_on,
                profile_locks=profile_on,
                # broker and load generator share one process+loop here:
                # the generator's starved reads look like slow consumers
                # and the governor would evict the probe itself — this
                # leg measures the write path, not overload control
                overload_control=False,
            )
        )
        srv.add_hook(AllowHook())
        srv.add_listener(
            TCP(LConfig(type="tcp", id="flat", address=f"127.0.0.1:{port}"))
        )
        await srv.serve()
        try:
            # a short warmup so neither arm pays first-connection costs
            await run_stress("127.0.0.1", port, 2, 100)
            flat = await run_flatness(
                "127.0.0.1", port,
                clients_small=small, clients_large=large,
                msgs_small=m_small, msgs_large=m_large,
            )
            # best-of-2 on the large leg for the overhead A/B: a single
            # sub-second round is scheduler noise, not a measurement
            rerun = await run_stress("127.0.0.1", port, large, m_large)
            best = max(
                flat["large"]["aggregate_msgs_per_sec"],
                rerun["aggregate_msgs_per_sec"],
            )
            return flat, srv.host_profile_block(), best
        finally:
            await srv.close()

    flat_on, profile, on_rate = await one_round(18843, True)
    flat_off, _, off_rate = await one_round(18844, False)
    return {
        "clients": flat_on["clients"],
        "receive_flatness_ratio": flat_on["receive_flatness_ratio"],
        # per-cell medians (diffable cell-by-cell across rounds)
        "cells": flat_on.get("cells"),
        "small": flat_on["small"],
        "large": flat_on["large"],
        "host_profile": profile,
        "profiler_overhead": {
            "enabled_msgs_per_sec": on_rate,
            "disabled_msgs_per_sec": off_rate,
            "overhead_pct": round((off_rate - on_rate) / max(1, off_rate) * 100, 2),
        },
    }


async def _slo_overhead_block(fast: bool) -> dict:
    """Config 8's SLO-plane A/B (ISSUE 14 acceptance: SLI-stamping
    overhead <= 2%): the same stress workload against two fresh brokers
    — the SLO observatory fully ON (delivery SLIs + a live burn-rate
    objective evaluating every housekeeping tick) vs ``Options.slo``
    OFF — best-of-2 each so a sub-second scheduler hiccup cannot decide
    the verdict. Production sampling rates (the default 1-in-64): the
    claim under test is the plane's cost as shipped, not under
    sample-everything instrumentation."""
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import run_stress

    clients, msgs = (10, 500) if fast else (40, 1500)
    reps = 3 if fast else 4

    async def one_round(port: int, slo_on: bool) -> float:
        srv = Server(
            Options(
                device_matcher=False,
                overload_control=False,  # measure the SLI path, not sheds
                slo=slo_on,
                slo_objectives=(
                    ["p99 delivery < 50ms over 5m", "shed ratio < 0.1%"]
                    if slo_on
                    else None
                ),
            )
        )
        srv.add_hook(AllowHook())
        srv.add_listener(
            TCP(LConfig(type="tcp", id="slo", address=f"127.0.0.1:{port}"))
        )
        await srv.serve()
        try:
            await run_stress("127.0.0.1", port, 2, 100)  # warmup
            res = await run_stress("127.0.0.1", port, clients, msgs)
            if slo_on and srv.slo is not None:
                # prove the engine actually evaluated live objectives
                # during the measured window (a dead engine would make
                # the A/B vacuous)
                srv.slo.evaluate()
            return res["aggregate_msgs_per_sec"]
        finally:
            await srv.close()

    # INTERLEAVED best-of-N: the in-process loopback workload is noisy
    # (±20% between back-to-back identical rounds on a shared box), so
    # sequential arm-then-arm would measure scheduler drift, not the
    # plane; alternating rounds and taking each arm's best bounds the
    # bias to within-pair jitter
    on_rate = off_rate = 0.0
    for rep in range(reps):
        on_rate = max(on_rate, await one_round(18845 + 2 * rep, True))
        off_rate = max(off_rate, await one_round(18846 + 2 * rep, False))
    out = {
        "enabled_msgs_per_sec": on_rate,
        "disabled_msgs_per_sec": off_rate,
        "reps": reps,
        "overhead_pct": round(
            (off_rate - on_rate) / max(1, off_rate) * 100, 2
        ),
    }
    # deterministic micro-measurement of the EXACT added work: one
    # sampled-path observe_delivery call (dict probe + histogram
    # observe), amortized over the 1-in-telemetry_sample publishes that
    # pay it. The macro A/B above inherits the loopback harness's
    # scheduler noise; this number is the stamping cost itself, and the
    # amortized-per-publish figure is what the <=2% acceptance bar is
    # judged against on noisy boxes.
    from mqtt_tpu.telemetry import Telemetry

    tele = Telemetry(sample=64)
    n = 200_000
    t0 = time.perf_counter()
    for i in range(n):
        tele.observe_delivery(1e-4, "", 0, "local")
    per_call_ns = (time.perf_counter() - t0) / n * 1e9
    out["sampled_observe_ns"] = round(per_call_ns, 1)
    out["amortized_ns_per_publish"] = round(per_call_ns / 64, 2)
    if off_rate > 0:
        # the stamping cost as a fraction of the measured per-publish
        # wall budget (1/rate): the harness-noise-free overhead claim
        out["amortized_overhead_pct"] = round(
            (per_call_ns / 64) / (1e9 / off_rate) * 100, 4
        )
    return out


async def _loopwitness_overhead_block(fast: bool) -> dict:
    """Config 8's loop-affinity witness A/B (ISSUE 19 acceptance:
    armed-recording overhead <= 2% amortized): the same stress workload
    against fresh brokers with ``DEFAULT_LOOP_PLANE`` armed (a recording
    LoopWitness noting every OutboundQueue put/get and stage resolve
    seam) vs disarmed — the shipped default outside the test suite.
    Interleaved best-of-N, same rationale as ``_slo_overhead_block``:
    alternating rounds bound scheduler drift to within-pair jitter. The
    disarmed hot path must stay at the LockWitness bar: one plane.active
    attribute read + branch per touch point, no allocation, no lock."""
    import asyncio

    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import run_stress
    from mqtt_tpu.utils.loopwitness import DEFAULT_LOOP_PLANE

    clients, msgs = (10, 500) if fast else (40, 1500)
    reps = 3 if fast else 4
    witness_edges = 0

    async def one_round(port: int, armed: bool) -> float:
        nonlocal witness_edges
        if armed:
            DEFAULT_LOOP_PLANE.arm_witness()  # recording, non-raising
        srv = Server(Options(device_matcher=False, overload_control=False))
        srv.add_hook(AllowHook())
        srv.add_listener(
            TCP(LConfig(type="tcp", id="loopwit", address=f"127.0.0.1:{port}"))
        )
        await srv.serve()
        try:
            await run_stress("127.0.0.1", port, 2, 100)  # warmup
            res = await run_stress("127.0.0.1", port, clients, msgs)
            if armed and DEFAULT_LOOP_PLANE.witness is not None:
                # the armed arm must actually produce evidence — a dead
                # witness would make the A/B vacuous
                witness_edges = max(
                    witness_edges, len(DEFAULT_LOOP_PLANE.witness.edges)
                )
            return res["aggregate_msgs_per_sec"]
        finally:
            await srv.close()
            DEFAULT_LOOP_PLANE.disarm_witness()

    on_rate = off_rate = 0.0
    try:
        for rep in range(reps):
            on_rate = max(on_rate, await one_round(18870 + 2 * rep, True))
            off_rate = max(off_rate, await one_round(18871 + 2 * rep, False))
    finally:
        DEFAULT_LOOP_PLANE.disarm_witness()
    out = {
        "armed_msgs_per_sec": on_rate,
        "disarmed_msgs_per_sec": off_rate,
        "reps": reps,
        "witness_edges_observed": witness_edges,
        "overhead_pct": round((off_rate - on_rate) / max(1, off_rate) * 100, 2),
    }
    # deterministic micro-measurement of the EXACT added work, free of
    # the loopback harness's scheduler noise. Three legs: a bare bool
    # attribute read (the LockWitness bar), the disarmed guard as the
    # instrumented code writes it (plane.active read + branch), and the
    # armed note_crossing (seam pick + known-edge dict probe). The
    # acceptance bars are judged on these: disarmed_guard_ns must sit at
    # flag_read_ns (no hidden work when off), and the armed per-touch
    # cost amortized over the measured per-publish wall budget must stay
    # under 2%.
    from mqtt_tpu.utils.loopwitness import LoopPlane

    plane = LoopPlane()
    n = 200_000
    flag = plane.active  # noqa: F841 — prime the attribute
    t0 = time.perf_counter()
    for _ in range(n):
        flag = plane.active
    flag_read_ns = (time.perf_counter() - t0) / n * 1e9
    t0 = time.perf_counter()
    for _ in range(n):
        if plane.active:
            w = plane.witness
            if w is not None:
                w.note_crossing("outbound_queue", "put_local", "put_cross", None)
    disarmed_guard_ns = (time.perf_counter() - t0) / n * 1e9
    w = plane.arm_witness()
    # steady state as the broker pays it: the queue HAS a stamped owner
    # and the touch happens ON that loop, so the seam pick runs the
    # loop-identity probe every call (this block is async — the running
    # loop is real)
    own = asyncio.get_running_loop()
    w.note_crossing("outbound_queue", "put_local", "put_cross", own)  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        w.note_crossing("outbound_queue", "put_local", "put_cross", own)
    armed_note_ns = (time.perf_counter() - t0) / n * 1e9
    out["flag_read_ns"] = round(flag_read_ns, 1)
    out["disarmed_guard_ns"] = round(disarmed_guard_ns, 1)
    out["armed_note_ns"] = round(armed_note_ns, 1)
    if off_rate > 0:
        # each delivered publish crosses the witnessed queue seam twice
        # (put + get). The ACCEPTANCE bar (ISSUE 19) is on the DISARMED
        # path — the shipped default: its guard cost amortized over the
        # measured per-publish wall budget must stay under 2%, and the
        # guard itself at the LockWitness bar (one flag test, see
        # flag_read_ns vs disarmed_guard_ns above). The armed figure is
        # recorded telemetry for test-suite/fuzzer budgeting.
        budget_ns = 1e9 / off_rate
        out["amortized_overhead_pct"] = round(
            (2 * disarmed_guard_ns) / budget_ns * 100, 4
        )
        out["armed_amortized_pct"] = round(
            (2 * armed_note_ns) / budget_ns * 100, 4
        )
    return out


def run_storm_bench(fast: bool) -> dict:
    """Config 8: the publish-storm overload drill. An in-process broker
    (tight overload caps, a deliberately slow consumer, the staging loop
    active when jax is importable) takes an offered load far above what
    its consumers drain; the artifact records how it DEGRADES: shed rate
    (0x97-acked QoS1 + dropped QoS0), slow-consumer evictions, the peak
    staging pending depth (must stay at/below its cap), and the
    admitted-traffic delivery p99 — brokers must fail by clean errors,
    not OOM/latency collapse (PAPERS: IoT-edge broker benchmarking)."""
    import asyncio

    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes, run_storm

    try:  # the stage (and its pending-depth signal) needs a matcher
        import jax  # noqa: F401

        device = True
    except ImportError:
        device = False

    port = 18841
    publishers = 4 if fast else 12
    msgs_each = 1500 if fast else 6000

    async def main() -> dict:
        opts = Options(
            device_matcher=device,
            matcher_opts={"max_levels": 4, "background": False} if device else None,
            # tight caps so the storm visibly crosses the bands: the
            # governor is judged on degrading predictably, not on how
            # much a big box can absorb
            overload_stage_max_pending=1024,
            overload_max_outbound_backlog=8192,
            overload_throttle_enter=0.20,
            overload_throttle_exit=0.05,
            overload_shed_enter=0.40,
            overload_shed_exit=0.05,
            overload_eval_interval_ms=50.0,
            overload_min_dwell_ms=300.0,
            overload_publish_quota=500,
            overload_shed_quota=50,
            overload_eviction_grace_ms=300.0,
            overload_client_buffer_limit_bytes=65536,
        )
        srv = Server(opts)
        srv.add_hook(AllowHook())
        srv.add_listener(TCP(LConfig(type="tcp", id="storm", address=f"127.0.0.1:{port}")))
        await srv.serve()
        try:
            # the slow consumer: subscribes to every storm topic, never
            # reads — its bounded queue must fill and, past the grace
            # window, cost it a DISCONNECT 0x97 eviction (not broker RAM)
            slow_r, slow_w = await asyncio.open_connection("127.0.0.1", port)
            slow_w.write(_connect_bytes("storm-slow", version=4))
            await slow_w.drain()
            await slow_r.readexactly(4)  # CONNACK
            # shrink both kernel buffers so the victim's unread backlog
            # surfaces in the broker's transport buffer (where the
            # eviction watermark looks) instead of hiding in TCP buffers
            import socket as _sock

            cs = slow_w.get_extra_info("socket")
            if cs is not None:
                cs.setsockopt(_sock.SOL_SOCKET, _sock.SO_RCVBUF, 4096)
            scl = srv.clients.get("storm-slow")
            if scl is not None and scl.net.writer is not None:
                ss = scl.net.writer.get_extra_info("socket")
                if ss is not None:
                    ss.setsockopt(_sock.SOL_SOCKET, _sock.SO_SNDBUF, 4096)
            slow_w.write(_subscribe_bytes(1, "storm/#"))
            await slow_w.drain()
            await slow_r.readexactly(5)  # SUBACK
            slow_w.transport.pause_reading()  # a truly stalled reader

            storm = await run_storm(
                "127.0.0.1", port,
                publishers=publishers, msgs_each=msgs_each,
                qos1_fraction=0.5, seed=7,
            )
            srv.sweep_overload()  # deterministic final eviction pass
            if srv.overload.gauges()["evictions"] == 0:
                # the backlog may need one more grace-spaced observation
                await asyncio.sleep(0.4)
                srv.sweep_overload()
            gauges = srv.overload.gauges()
            out = dict(storm)
            delivered_rate = storm["delivered"] / max(1e-9, storm["storm_wall_s"])
            out["offered_to_delivered_ratio"] = round(
                storm["offered_rate_per_sec"] / max(1.0, delivered_rate), 2
            )
            out["governor_sheds"] = gauges["sheds"]
            # the TOTAL shed rate (0x97-acked QoS1 AND silently-dropped
            # QoS0, counted broker-side) over the offered load
            out["governor_shed_rate"] = round(
                gauges["sheds"] / max(1, storm["offered"]["total"]), 4
            )
            out["governor_evictions"] = gauges["evictions"]
            out["governor_throttled"] = gauges["throttled"]
            out["governor_transitions"] = gauges["transitions"]
            out["peak_pressure"] = max(
                (v for k, v in gauges.items() if k.startswith("peak/")),
                default=0.0,
            )
            if srv._stage is not None:
                out["peak_pending_depth"] = srv._stage.peak_pending
                out["pending_cap"] = srv._stage.max_pending
                out["stage_admission_fallbacks"] = srv._stage.admission_fallbacks
            if srv.telemetry is not None:
                # the live telemetry plane's per-stage view of the storm:
                # sampled stage p50/p99, batch occupancy, fallback classes
                srv.telemetry.recorder.join_writer()  # dump IO off-thread
                out["telemetry"] = srv.telemetry.bench_block()
                out["flight_dumps"] = srv.telemetry.recorder.dumps
            if srv.profiler is not None:
                # the live broker's device duty-cycle / overlap / idle-gap
                # numbers under storm load (mqtt_tpu.tracing) — ROADMAP
                # item 1's per-round baseline of the staging gap
                out["device_pipeline"] = srv.profiler.bench_block()
            # the storm broker's own host-profile block (stacks, locks,
            # amplification under STORM load, mqtt_tpu.profiling)
            out["host_profile_storm"] = srv.host_profile_block()
            try:
                slow_w.close()
            except Exception:
                pass
            return out
        finally:
            await srv.close()

    out = asyncio.run(main())
    # the flatness + amplification + overhead leg runs on fresh
    # default-cap brokers AFTER the storm broker is fully closed: its
    # deliberately tiny quotas would shed the probe itself, and its
    # still-armed lock plane would contaminate the disabled A/B arm
    out["receive_flatness"] = asyncio.run(_flatness_profile_block(fast))
    # hoisted as a TOP-LEVEL scalar so the bench-history ledger keeps it
    # (_history_config_block) and exp/bench_trend.py can gate the
    # flatness trajectory beside the headline (ISSUE 15)
    out["receive_flatness_ratio"] = out["receive_flatness"][
        "receive_flatness_ratio"
    ]
    # the connection-scale axis (ISSUE 15): 1k/10k mostly-idle clients
    # against the shard-fabric subprocess broker, BENCH_SHARDS=1 A/B
    out["idle_conn_matrix"] = run_idle_conn_matrix(fast)
    # the SLO-plane on/off A/B (ISSUE 14 acceptance: <=2% SLI overhead);
    # BENCH_SLO=0 skips the arm for broker-only sweeps
    if os.environ.get("BENCH_SLO") != "0":
        out["slo_overhead"] = asyncio.run(_slo_overhead_block(fast))
    # the loop-affinity witness on/off A/B (ISSUE 19 acceptance: armed
    # recording <=2% amortized; disarmed cost = one flag test)
    out["loopwitness_overhead"] = asyncio.run(_loopwitness_overhead_block(fast))
    # the connections × rate × QoS comparative matrix runs last, on a
    # subprocess broker (per-core workers) — the 2603.21600 reporting
    # frame for the encode-once write path (ISSUE 13)
    out["conn_rate_qos_matrix"] = run_conn_rate_qos_matrix(fast)
    return out


def main() -> None:
    if os.environ.get("JAX_PLATFORMS"):
        # honor the caller's platform even when a site hook imported jax
        # before this process saw the env var (the config route still
        # applies because the backend initializes lazily). Broker-only
        # runs must keep working on hosts without jax at all.
        try:
            import jax

            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except ImportError:
            pass
    fast = os.environ.get("BENCH_FAST") == "1"
    n_subs = int(os.environ.get("BENCH_SUBS", 50_000 if fast else 1_000_000))
    batch = int(os.environ.get("BENCH_BATCH", 1024 if fast else 16384))
    iters = int(os.environ.get("BENCH_ITERS", 5 if fast else 20))
    which = {
        int(c)
        for c in os.environ.get(
            "BENCH_CONFIGS", "1,2,3,4,5,6,7,8,9,10,11,12"
        ).split(",")
        if c.strip()
    }
    rng = random.Random(7)

    link = None
    device_ok = True
    probe_err = ""

    # the probe is tracked by the same breaker machinery the broker's
    # degradation manager uses (mqtt_tpu.resilience): the artifact then
    # carries breaker-style stats — probe attempts, failure kinds,
    # backoff state — instead of a bare device_unreachable flag
    from mqtt_tpu.resilience import CLOSED, Backoff, CircuitBreaker

    probe_breaker = CircuitBreaker(
        failure_threshold=1,
        probe_successes=1,
        backoff=Backoff(
            initial=float(os.environ.get("BENCH_PROBE_WAIT", "60")),
            maximum=240.0,
            jitter=0.1,
            seed=7,  # deterministic artifact-to-artifact schedule
        ),
    )

    def probe_device(retries: int, wait_s: int = int(os.environ.get("BENCH_PROBE_WAIT", "60"))):
        """Device liveness probe in a SUBPROCESS: a dead tunnel hangs jax
        backend init indefinitely (no timeout in the client), which would
        otherwise wedge the whole bench run and produce nothing."""
        import subprocess

        # a hung backend init is killed by the child's own watchdog first,
        # the parent timeout second; both scale from one knob so tests can
        # exercise the hang path without 90s per probe
        probe_timeout = int(os.environ.get("BENCH_PROBE_TIMEOUT", "150"))
        watchdog = max(5, int(probe_timeout * 0.6))
        probe = None
        for attempt in range(max(1, retries)):
            if attempt:
                log(f"device probe retry {attempt} in {wait_s}s (tunnel may be restarting)")
                time.sleep(wait_s)
            try:
                probe = subprocess.run(
                    [
                        sys.executable,
                        "-c",
                        f"import faulthandler; faulthandler.dump_traceback_later({watchdog}, exit=True)\n"
                        "import jax, numpy, jax.numpy as jnp\n"
                        "print(jax.devices()); print(int(numpy.asarray((jnp.ones((8,))*2).sum())))",
                    ],
                    timeout=probe_timeout,
                    capture_output=True,
                )
            except subprocess.TimeoutExpired as e:
                # a probe wedged past its own watchdog counts as one failed
                # attempt — the graceful broker-only path must still run
                probe = subprocess.CompletedProcess(
                    e.cmd, returncode=-1, stdout=b"", stderr=b"probe timeout"
                )
            if probe.returncode == 0:
                if probe_breaker.state != CLOSED:
                    # a successful retry IS the verified half-open probe:
                    # the artifact must end state=closed (trips still
                    # record the transient), not report a dark link for
                    # a run that benchmarked the device
                    probe_breaker.acquire_probe(force=True)
                    probe_breaker.record_probe_success()
                else:
                    probe_breaker.record_success()
                return True, ""
            probe_breaker.record_failure(
                "hang" if probe.returncode == -1 else "error"
            )
        return False, probe.stderr.decode(errors="replace")[-300:].replace("\n", " | ")

    device_wanted = bool(which & {1, 2, 3, 4, 5})
    if os.environ.get("BENCH_ASSUME_DEVICE") == "1":
        pass  # validation runs on a pinned backend: skip the probe
    elif device_wanted:
        device_ok, probe_err = probe_device(
            int(os.environ.get("BENCH_PROBE_RETRIES", "3"))
        )
        if not device_ok:
            log(
                "DEVICE UNREACHABLE (backend init hung or failed); deferring "
                "device configs — broker bench runs first, then one re-probe. "
                "probe stderr tail: " + probe_err
            )

    configs = {}
    t_all = time.perf_counter()

    def run_device_configs() -> None:
        nonlocal link
        if link is None:
            import jax

            link = probe_link()
            log(
                f"device={jax.devices()[0].platform} fast={fast} subs={n_subs} "
                f"batch={batch} link={link}"
            )
        if 1 in which:
            t0 = time.perf_counter()
            configs["1_exact_10k"] = run_cfg1(rng, fast, batch)
            log(f"cfg1 {configs['1_exact_10k']} ({time.perf_counter()-t0:.0f}s)")
        if 2 in which:
            t0 = time.perf_counter()
            configs["2_1m_plus"] = run_cfg2(n_subs, batch, iters, rng)
            log(f"cfg2 {configs['2_1m_plus']} ({time.perf_counter()-t0:.0f}s)")
        if 3 in which:
            t0 = time.perf_counter()
            # full 1M for the deep/# config (round-3 VERDICT item 7); the
            # flat build walks terminals once, so deep tries need no cap
            n3 = min(n_subs, int(os.environ.get("BENCH_SUBS3", n_subs)))
            configs["3_deep_hash"] = run_cfg3(n3, batch, iters, rng)
            configs["3_deep_hash"]["n_subs"] = n3
            log(f"cfg3 {configs['3_deep_hash']} ({time.perf_counter()-t0:.0f}s)")
        if 4 in which:
            t0 = time.perf_counter()
            n_groups = int(os.environ.get("BENCH_GROUPS", 5_000 if fast else 100_000))
            configs["4_shared_groups"] = run_cfg4(n_groups, 16, batch, iters, rng)
            log(f"cfg4 {configs['4_shared_groups']} ({time.perf_counter()-t0:.0f}s)")
        if 5 in which:
            t0 = time.perf_counter()
            n5 = min(n_subs, 20_000 if fast else 200_000)
            configs["5_churn_ids_retained"] = run_cfg5(n5, batch, iters, rng)
            log(f"cfg5 {configs['5_churn_ids_retained']} ({time.perf_counter()-t0:.0f}s)")

    # device configs FIRST while the tunnel is known-up (VERDICT r4 item 2:
    # the round-4 artifact zeroed because the tunnel died between the
    # broker configs and the device configs)
    if device_ok and device_wanted:
        run_device_configs()
    if 6 in which:
        t0 = time.perf_counter()
        configs["broker"] = run_broker_bench(fast)
        log(f"broker bench done ({time.perf_counter()-t0:.0f}s)")
    if 7 in which:
        t0 = time.perf_counter()
        configs["7_materializer_host"] = run_materializer_bench(fast)
        log(f"cfg7 {configs['7_materializer_host']} ({time.perf_counter()-t0:.0f}s)")
    if 8 in which:
        t0 = time.perf_counter()
        configs["8_publish_storm"] = run_storm_bench(fast)
        log(f"cfg8 {configs['8_publish_storm']} ({time.perf_counter()-t0:.0f}s)")
    if 9 in which:
        # predicate-selectivity sweep: runs on any jax backend (the rule
        # kernel is shape-tiny); skipped gracefully on jax-less hosts
        t0 = time.perf_counter()
        try:
            configs["9_predicate_sweep"] = run_cfg9(fast, rng)
        except ImportError as e:
            configs["9_predicate_sweep"] = {"skipped": f"no jax: {e}"}
        log(f"cfg9 {configs['9_predicate_sweep']} ({time.perf_counter()-t0:.0f}s)")
    if 10 in which:
        # tenants x keys x fan-out re-encryption matrix: runs on any
        # jax backend (keystream shapes are tiny); the engine degrades
        # to the vectorized host path on jax-less hosts by itself
        t0 = time.perf_counter()
        try:
            configs["10_recrypt_matrix"] = run_cfg10(fast, rng)
        except Exception as e:  # never take the whole artifact down
            configs["10_recrypt_matrix"] = {"skipped": f"error: {e}"}
        log(f"cfg10 {configs['10_recrypt_matrix']} ({time.perf_counter()-t0:.0f}s)")
    if 11 in which:
        # durable recovery sweep + retained device-vs-host scan rates:
        # the store leg is pure host I/O; the retained kernel runs on
        # any jax backend and the config is skipped without one
        t0 = time.perf_counter()
        try:
            configs["11_durable_recovery"] = run_cfg11(fast, rng)
        except Exception as e:  # never take the whole artifact down
            configs["11_durable_recovery"] = {"skipped": f"error: {e}"}
        log(f"cfg11 {configs['11_durable_recovery']} ({time.perf_counter()-t0:.0f}s)")
    if 12 in which:
        # mesh predicate push-down gate (ISSUE 17): pure host, no
        # sockets — the per-edge filter/forward decision rate and the
        # asserted filtered ratio
        t0 = time.perf_counter()
        try:
            configs["12_mesh_pushdown"] = run_cfg12(fast, rng)
        except Exception as e:  # never take the whole artifact down
            configs["12_mesh_pushdown"] = {"skipped": f"error: {e}"}
        log(f"cfg12 {configs['12_mesh_pushdown']} ({time.perf_counter()-t0:.0f}s)")
    if not device_ok and device_wanted:
        # the broker bench bought the tunnel a few minutes: one more chance
        device_ok, probe_err = probe_device(2)
        if device_ok:
            log("device recovered after broker bench; running device configs")
            run_device_configs()
    log(f"total bench wall time {time.perf_counter()-t_all:.0f}s")

    headline = configs.get("2_1m_plus") or next(
        (c for c in configs.values() if "e2e_matches_per_sec" in c), None
    )
    # headline stays the full-path e2e rate (BASELINE.md's definition and
    # comparable with prior BENCH_rNN.json); the transfer-free kernel rate
    # — the on-chip capability this harness's tunneled link (RTT/bandwidth
    # in "link") cannot express e2e — is surfaced alongside.
    value = (headline or {}).get("e2e_matches_per_sec")
    kernel = (headline or {}).get("device_kernel_matches_per_sec") or 0
    if value is not None:
        out = {
            "metric": f"publish_topic_matches_per_sec@{n_subs}_wildcard_subs_e2e",
            "value": value,
            "unit": "matches/s",
            "vs_baseline": round(value / TARGET_MATCHES_PER_SEC, 4),
            "device_kernel_matches_per_sec": kernel,
            "kernel_vs_baseline": round(kernel / TARGET_MATCHES_PER_SEC, 4),
            "link": link,
            "configs": configs,
        }
    else:
        # NO e2e-producing config ran (dead device tunnel, or a
        # broker-only BENCH_CONFIGS selection): the run is SKIPPED for
        # headline purposes — value/vs_baseline are null, never a silent
        # 0 that poisons trend lines (the r05 artifact recorded
        # vs_baseline=0.0 for a run that never touched the device)
        if device_wanted and not device_ok:
            reason = (
                "device unreachable after probe retries: " + probe_err
            )
        else:
            reason = "no e2e-producing config selected by BENCH_CONFIGS"
        out = {
            "metric": f"publish_topic_matches_per_sec@{n_subs}_wildcard_subs_e2e",
            "value": None,
            "unit": "matches/s",
            "vs_baseline": None,
            "device_kernel_matches_per_sec": None,
            "kernel_vs_baseline": None,
            "skipped": True,
            "skip_reason": reason,
            "link": link,
            "configs": configs,
        }
    if device_wanted:
        # breaker-style probe stats in every device-wanting artifact:
        # attempts, failure kinds (hang vs error), trips — so a degraded
        # run documents HOW the link failed, not just that it did
        out["probe_breaker"] = probe_breaker.as_dict()
    if device_wanted and not device_ok:
        # an explicit flag beside the skipped headline: the device was
        # unreachable for this run, the recorded configs cover only what
        # actually ran (VERDICT r4 item 2)
        out["device_unreachable"] = True
        out["device_probe_error"] = probe_err
    print(json.dumps(out))
    append_history(out)


def _history_config_block(cfg) -> dict:
    """The compact per-config slice a history entry keeps: top-level
    scalars only (rates, ratios, counts) — enough for trend lines
    without duplicating whole artifacts into the ledger."""
    if not isinstance(cfg, dict):
        return {}
    return {
        k: v for k, v in cfg.items() if isinstance(v, (int, float, bool))
    }


def history_entry(doc: dict, round_tag: str = "", time_unix: int = 0) -> dict:
    """The CANONICAL bench-history ledger entry for one bench document
    — the single schema both the live append below and
    exp/bench_trend.py's backfill write, so the two can never drift."""
    return {
        "round": round_tag,
        "time_unix": time_unix,
        "metric": doc.get("metric"),
        "value": doc.get("value"),
        "vs_baseline": doc.get("vs_baseline"),
        "device_kernel_matches_per_sec": doc.get(
            "device_kernel_matches_per_sec"
        ),
        "configs": {
            name: _history_config_block(cfg)
            for name, cfg in (doc.get("configs") or {}).items()
        },
    }


def append_history(out: dict) -> None:
    """Append this round's headline + per-config scalar blocks to the
    bench-history ledger (ISSUE 14 satellite: ``BENCH_HISTORY.jsonl``,
    gated by exp/bench_trend.py in CI). SKIPPED rounds never append —
    a null headline must not enter the trend window (the r05 lesson) —
    and ``BENCH_HISTORY=0`` disables the ledger outright (subprocess
    test runs). ``BENCH_HISTORY_PATH`` overrides the destination."""
    if os.environ.get("BENCH_HISTORY") == "0" or out.get("skipped"):
        return
    path = os.environ.get("BENCH_HISTORY_PATH") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.jsonl"
    )
    entry = history_entry(
        out,
        round_tag=os.environ.get("BENCH_ROUND", ""),
        time_unix=int(time.time()),  # ledger stamps are operator-correlatable wall clock
    )
    try:
        with open(path, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except OSError as e:
        log(f"bench-history append failed ({e}); continuing")
    else:
        log(f"bench-history entry appended to {path}")


if __name__ == "__main__":
    main()
