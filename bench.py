#!/usr/bin/env python
"""Benchmark: batched publish-topic matching against a large wildcard
subscription index on the real device.

Implements BASELINE.json config #2 — N subscriptions over 3-level topics
with ~10% single-level ``+`` wildcards — and measures sustained
publish-topic matches/sec through the device matcher (host tokenization +
device NFA match + result transfer). North-star target: >= 10M matches/sec
@ 1M subscriptions on one v5e-1 (BASELINE.md).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Environment overrides: BENCH_SUBS, BENCH_BATCH, BENCH_ITERS, BENCH_LEVELS.
"""

import json
import os
import random
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TARGET_MATCHES_PER_SEC = 10_000_000  # the BASELINE.json north star


def build_index(n_subs: int, rng: random.Random):
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.topics import TopicsIndex

    v0 = [f"region{i}" for i in range(100)]
    v1 = [f"device{i}" for i in range(100)]
    v2 = [f"metric{i}" for i in range(100)]
    index = TopicsIndex()
    for i in range(n_subs):
        parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
        if rng.random() < 0.10:  # 10% single-level wildcards
            parts[rng.randrange(3)] = "+"
        index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
    return index, (v0, v1, v2)


def main() -> None:
    n_subs = int(os.environ.get("BENCH_SUBS", 1_000_000))
    batch = int(os.environ.get("BENCH_BATCH", 4096))
    iters = int(os.environ.get("BENCH_ITERS", 30))
    max_levels = int(os.environ.get("BENCH_LEVELS", 4))
    rng = random.Random(7)

    t0 = time.time()
    index, (v0, v1, v2) = build_index(n_subs, rng)
    t_build = time.time() - t0
    print(f"# built {n_subs} subs in {t_build:.1f}s", file=sys.stderr)

    import jax
    import jax.numpy as jnp

    from mqtt_tpu.ops import TpuMatcher
    from mqtt_tpu.ops.hashing import tokenize_topics

    matcher = TpuMatcher(index, max_levels=max_levels, frontier=8, out_slots=64)
    t0 = time.time()
    matcher.rebuild()
    print(
        f"# CSR compile {time.time() - t0:.1f}s: nodes={matcher.csr.num_nodes} "
        f"subs={matcher.csr.num_subs} device={jax.devices()[0].platform}",
        file=sys.stderr,
    )

    # pre-generate a topic pool and tokenize per batch on the host
    pool = [
        f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"
        for _ in range(batch * 4)
    ]
    batches = []
    for i in range(4):
        topics = pool[i * batch : (i + 1) * batch]
        tok1, tok2, lengths, is_dollar, _ = tokenize_topics(
            topics, max_levels, matcher.csr.salt
        )
        batches.append(tuple(jnp.asarray(a) for a in (tok1, tok2, lengths, is_dollar)))

    def run_one(i):
        out, totals, overflow = matcher.match_tokens(*batches[i % len(batches)])
        return out

    # warmup / compile
    run_one(0).block_until_ready()
    t0 = time.time()
    run_one(1).block_until_ready()
    print(f"# steady-state single batch {(time.time()-t0)*1e3:.2f}ms", file=sys.stderr)

    lat = []
    t_start = time.time()
    for i in range(iters):
        t1 = time.time()
        run_one(i).block_until_ready()
        lat.append(time.time() - t1)
    elapsed = time.time() - t_start

    matches_per_sec = (iters * batch) / elapsed
    p99 = sorted(lat)[max(0, int(len(lat) * 0.99) - 1)] * 1e3
    print(
        f"# {iters} x {batch} topics in {elapsed:.3f}s; p99 batch latency {p99:.2f}ms",
        file=sys.stderr,
    )

    print(
        json.dumps(
            {
                "metric": f"publish_topic_matches_per_sec@{n_subs}_wildcard_subs",
                "value": round(matches_per_sec),
                "unit": "matches/s",
                "vs_baseline": round(matches_per_sec / TARGET_MATCHES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
