#!/usr/bin/env python
"""CI device-observatory scrape gate (ISSUE 18): boot a broker with the
device-stats plane over an 8-way forced host mesh, drive a publish
burst plus an 8-way mesh-sharded matcher, fetch ``GET /devices`` and
``GET /metrics`` from the stats listener, validate the labeled
``mqtt_tpu_device_*`` families with the pure-Python exposition checker
(mqtt_tpu.telemetry.check_exposition), and write the /devices snapshot
to disk — the workflow uploads it as the per-run device baseline
artifact.

Usage: python exp/scrape_devices.py [--out devices-snapshot.json]
Exits non-zero when the scrape fails to parse, any of the 8 per-device
families is missing, or the compile ledger / skew gauge stayed inert.
"""

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the gate's whole point is an 8-device mesh: force the host platform
# to present 8 devices BEFORE jax initialises (import-order-sensitive,
# same trick as tests/conftest.py)
_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") + " " + _FLAG
    ).strip()


async def main(out_path: str) -> int:
    try:
        import jax
    except ImportError:
        # a dev box without jax must not brick `make scrape-devices`;
        # CI always installs jax so the gate never silently skips there
        print("SKIP: jax not installed; device scrape needs a backend",
              file=sys.stderr)
        return 0

    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.packets import Subscription
    from mqtt_tpu.parallel.sharded import ShardedTpuMatcher, make_mesh
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes
    from mqtt_tpu.telemetry import check_exposition
    from mqtt_tpu.topics import TopicsIndex

    if len(jax.devices()) < 8:
        print(
            f"FAIL: expected >=8 forced host devices, "
            f"got {len(jax.devices())}",
            file=sys.stderr,
        )
        return 1

    opts = Options(
        device_matcher=True,
        matcher_opts={"max_levels": 4, "background": False},
        telemetry_sample=1,  # sample everything: a 2s burst must register
        device_stats=True,
    )
    srv = Server(opts)
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
        )
    )
    await srv.serve()
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)

        # one subscriber + a small publish burst: exercises the staged
        # matcher so the compile ledger records the flat-kernel entries
        sr, sw = await asyncio.open_connection(host, int(port))
        sw.write(_connect_bytes("scrape-sub", version=4))
        await sw.drain()
        await sr.readexactly(4)
        sw.write(_subscribe_bytes(1, "bench/#"))
        await sw.drain()
        await sr.readexactly(5)
        if srv.matcher is not None:
            srv.matcher.flush()

        pr, pw = await asyncio.open_connection(host, int(port))
        pw.write(_connect_bytes("scrape-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        for i in range(200):
            topic = f"bench/{i % 10}".encode()
            body = len(topic).to_bytes(2, "big") + topic + b"x" * 16
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()
        deadline = asyncio.get_event_loop().time() + 20
        got = 0
        while got < 200 and asyncio.get_event_loop().time() < deadline:
            try:
                # generous first-read budget: the burst's first staged
                # batch pays the match kernel jit compile
                data = await asyncio.wait_for(sr.read(65536), 5.0)
            except asyncio.TimeoutError:
                break
            if not data:
                break
            got += data.count(b"bench/")
        print(f"# delivered ~{got}/200 publishes", file=sys.stderr)

        # mesh-sharded leg: attach an 8-way sharded matcher to the
        # plane (the server's staged matcher is single-device) so the
        # tile/skew families and all 8 per-device duty windows populate
        index = TopicsIndex()
        for i in range(64):
            index.subscribe(f"c{i}", Subscription(filter=f"mesh/{i % 8}/+"))
        sharded = ShardedTpuMatcher(
            index, mesh=make_mesh(jax.devices()[:8]), max_levels=4
        )
        if srv.profiler is not None:
            sharded.profiler = srv.profiler
        assert srv.device_stats is not None
        srv.device_stats.attach_matcher(sharded)
        for _ in range(3):
            sharded.match_topics([f"mesh/{i % 8}/x" for i in range(64)])

        srv.publish_sys_topics()
        from scrapelib import http_get

        addr = srv.listeners.get("s").address()
        head, body = await http_get(addr, "/devices")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        doc = json.loads(body)
        if doc.get("n_devices") != 8 or len(doc.get("devices", [])) != 8:
            print(f"FAIL: /devices n_devices={doc.get('n_devices')} != 8",
                  file=sys.stderr)
            return 1
        if sorted(d["id"] for d in doc["devices"]) != list(range(8)):
            print("FAIL: /devices ids are not 0..7", file=sys.stderr)
            return 1
        if doc["compiles"]["total"] < 1:
            print("FAIL: compile ledger recorded no events", file=sys.stderr)
            return 1
        if doc["skew"]["ratio"] <= 0.0:
            print("FAIL: skew gauge inert after sharded burst",
                  file=sys.stderr)
            return 1

        head, mbody = await http_get(addr, "/metrics")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        text = mbody.decode()
        samples = check_exposition(text)
        required = [
            "mqtt_tpu_device_skew_ratio",
            'mqtt_tpu_device_tile_hits_total{tile="0"}',
            "mqtt_tpu_device_tile_fill_ratio_bucket",
            "mqtt_tpu_matcher_recompiles_total",
            "mqtt_tpu_matcher_compile_seconds_count",
        ]
        for did in range(8):
            required.append(f'mqtt_tpu_device_hbm_ratio{{device="{did}"}}')
            required.append(
                f'mqtt_tpu_device_duty_cycle_ratio{{device="{did}"}}'
            )
        missing = [m for m in required if m not in text]
        if missing:
            print(f"FAIL: metrics missing {missing}", file=sys.stderr)
            return 1

        with open(out_path, "w") as f:
            json.dump({"devices": doc, "metrics_samples": samples}, f,
                      indent=2)
        print(
            f"OK: 8 devices exported, {samples} samples parsed, "
            f"{doc['compiles']['total']} compile event(s); "
            f"snapshot -> {out_path}",
            file=sys.stderr,
        )
        return 0
    finally:
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="devices-snapshot.json")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
