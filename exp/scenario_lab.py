#!/usr/bin/env python
"""Scenario lab runner (ISSUE 20 tentpole CLI).

Executes the declarative workload/fault scenarios in
``mqtt_tpu/scenarios.py`` — each one a seeded fleet + traffic mix +
fault script judged by a delivery oracle AND the SLO engine's
burn-rate objectives — and writes the machine-readable verdicts the
rest of the repo's gating already consumes:

- a JSON artifact (``--out``, default ``exp/artifacts/scenario_lab.json``)
  with the full per-scenario result docs (oracle counts, SLO objective
  states, driver metrics, wall time, seed) for CI upload;
- a ``BENCH_HISTORY.jsonl`` entry (via ``bench.append_history`` — the
  ONE ledger schema) whose headline is the matrix's aggregate delivery
  rate under its own metric name, so ``exp/bench_trend.py`` trends
  scenario rounds against scenario rounds and bench rounds against
  bench rounds without cross-contamination. Per-scenario scalar blocks
  land under ``configs["scenario_<name>"]`` where the trend gate's
  CONFIG_SCALARS rows watch them.

History appends only for the canonical selections (``--smoke`` /
``--all``): an ad-hoc named run or a ``--seed`` override is not a
comparable round and must not enter the trend window.

Usage:
    python exp/scenario_lab.py --smoke            # CI verify-job gate
    python exp/scenario_lab.py --all              # nightly full matrix
    python exp/scenario_lab.py tenant_rekey       # one scenario, ad hoc
    python exp/scenario_lab.py --all --seed 7     # reseeded (no ledger)
Exit code is non-zero when any selected scenario fails its oracle or
breaches an SLO objective.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from mqtt_tpu.scenarios import SCENARIOS, run_matrix, scenario_names  # noqa: E402


def _config_block(res: dict) -> dict:
    """The per-scenario scalar slice kept in the history ledger: oracle
    counts, pass bit, wall time, throughput, plus every numeric the
    driver reported (bench.py's ``_history_config_block`` drops
    non-scalars on append, so richer values are safe to include)."""
    oracle = res.get("oracle") or {}
    wall = res.get("wall_s") or 0.0
    delivered = oracle.get("delivered", 0)
    block: dict = {
        "passed": bool(res.get("passed")),
        "expected": oracle.get("expected", 0),
        "delivered": delivered,
        "gaps": oracle.get("gaps", 0),
        "duplicates": oracle.get("duplicates", 0),
        "faults": oracle.get("faults", 0),
        "wall_s": wall,
        "deliveries_per_sec": (delivered / wall) if wall > 0 else 0,
        "seed": res.get("seed"),
    }
    for k, v in (res.get("metrics") or {}).items():
        if isinstance(v, (int, float, bool)) and k not in block:
            block[k] = v
    return block


def _history_doc(results: list[dict], selection: str) -> dict:
    """A bench-document-shaped dict for ``bench.append_history``: the
    headline is the matrix aggregate delivery rate, named per selection
    (smoke vs full matrices are different workloads — bench_trend's
    same-metric rule keeps their trend lines separate)."""
    delivered = sum((r.get("oracle") or {}).get("delivered", 0) for r in results)
    wall = sum(r.get("wall_s") or 0.0 for r in results)
    return {
        "metric": f"scenario_deliveries_per_sec@{selection}",
        "value": round(delivered / wall, 1) if wall > 0 else None,
        "configs": {
            f"scenario_{r['scenario']}": _config_block(r) for r in results
        },
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "names",
        nargs="*",
        help=f"scenario names to run (known: {', '.join(SCENARIOS)})",
    )
    ap.add_argument(
        "--smoke",
        action="store_true",
        help="run only the smoke-tier scenarios (CI verify job)",
    )
    ap.add_argument(
        "--all", action="store_true", help="run the full scenario matrix"
    )
    ap.add_argument(
        "--seed",
        type=int,
        default=None,
        help="override every spec's seed (disables the history append)",
    )
    ap.add_argument(
        "--out",
        default=os.path.join(_REPO, "exp", "artifacts", "scenario_lab.json"),
        help="artifact path for the full result docs",
    )
    ap.add_argument(
        "--no-history",
        action="store_true",
        help="skip the BENCH_HISTORY.jsonl append even for canonical runs",
    )
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args()

    if args.list:
        for name, spec in SCENARIOS.items():
            tier = "smoke" if spec.smoke else "full "
            print(f"{name:20s} [{tier}] seed={spec.seed}  {spec.title}")
        return 0

    if args.names:
        unknown = [n for n in args.names if n not in SCENARIOS]
        if unknown:
            ap.error(f"unknown scenario(s): {', '.join(unknown)}")
        names, selection = list(args.names), "custom"
    elif args.all:
        names, selection = scenario_names(), "full"
    elif args.smoke:
        names, selection = scenario_names(smoke_only=True), "smoke"
    else:
        ap.error("pick scenarios by name, or pass --smoke / --all")
        return 2  # unreachable; keeps type-checkers honest

    print(f"scenario-lab: running {len(names)} scenario(s): {', '.join(names)}")
    results = run_matrix(names, seed=args.seed)

    failed = [r["scenario"] for r in results if not r.get("passed")]
    for r in results:
        oracle = r.get("oracle") or {}
        mark = "PASS" if r.get("passed") else "FAIL"
        print(
            f"scenario-lab: [{mark}] {r['scenario']:18s} "
            f"delivered {oracle.get('delivered', 0)}/{oracle.get('expected', 0)} "
            f"gaps={oracle.get('gaps', 0)} dups={oracle.get('duplicates', 0)} "
            f"wall={r.get('wall_s', 0):.2f}s"
        )
        for msg in r.get("failures") or []:
            print(f"scenario-lab:        - {msg}")

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    artifact = {
        "selection": selection,
        "seed_override": args.seed,
        "passed": not failed,
        "results": results,
    }
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(artifact, f, indent=2, default=str)
    print(f"scenario-lab: artifact written to {args.out}")

    # failed rounds never enter the ledger: a red matrix's delivery
    # rate is not a comparable baseline, and CI already fails on rc=1
    canonical = (
        selection in ("smoke", "full") and args.seed is None and not failed
    )
    if canonical and not args.no_history:
        from bench import append_history

        append_history(_history_doc(results, selection))

    if failed:
        print(f"scenario-lab: FAILED: {', '.join(failed)}")
        return 1
    print(f"scenario-lab: all {len(results)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
