"""E2: does the 0.1ms/batch result survive (a) forced completion via scalar
D2H, (b) 1M subs, (c) distinct batch buffers per call — the bench's exact
kernel-measurement shape?"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from mqtt_tpu.ops import TpuMatcher
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
N = int(os.environ.get("NSUBS", "1000000"))
for i in range(N):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))

matcher = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16)
t0 = time.perf_counter(); matcher.rebuild(); print(f"rebuild {time.perf_counter()-t0:.1f}s nodes={matcher.csr.num_nodes}", flush=True)
salt = matcher.csr.salt

def topic():
    return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

B = 16384
batches = [[topic() for _ in range(B)] for _ in range(4)]
resident = [tuple(jnp.asarray(a) for a in tokenize_topics(bt, 4, salt)[:4]) for bt in batches]
jax.block_until_ready(resident)

red = jax.jit(lambda o: o.sum())
# warmup/compile
out = matcher.match_tokens(*resident[0])[0]
s = red(out); print("warm sum:", int(np.asarray(s)), flush=True)

for iters in (8, 20):
    t0 = time.perf_counter()
    outs = [matcher.match_tokens(*resident[i % 4])[0] for i in range(iters)]
    val = int(np.asarray(red(outs[-1])))  # scalar D2H forces full completion of last
    dt = time.perf_counter() - t0
    print(f"iters={iters} distinct-batches: {dt:.3f}s, {dt/iters*1e3:.1f}ms/batch, {B*iters/dt:,.0f} topics/s (sum={val})", flush=True)

# force completion of EVERY batch via scalar chain
t0 = time.perf_counter()
acc = None
outs = []
for i in range(20):
    o = matcher.match_tokens(*resident[i % 4])[0]
    outs.append(red(o))
vals = [int(np.asarray(x)) for x in outs]
dt = time.perf_counter() - t0
print(f"per-batch scalar D2H x20: {dt:.3f}s, {dt/20*1e3:.1f}ms/batch, {B*20/dt:,.0f} topics/s", flush=True)
