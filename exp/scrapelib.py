"""Shared plumbing for the CI scrape gates (scrape_metrics /
scrape_traces / scrape_profile): the raw HTTP/1.1 fetch against the
broker's stats listener. One implementation so a fetch-path fix (the
read-to-EOF rule, timeouts) lands in every gate at once."""

import asyncio


async def http_get(addr: str, path: str, timeout: float = 5.0):
    """``(status_head, body)`` for one GET against ``host:port``. The
    listener sends ``Connection: close``, so the body is read to EOF —
    a large exposition split across TCP segments never truncates."""
    host, port = addr.rsplit(":", 1)
    reader, writer = await asyncio.open_connection(host, int(port))
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = b""
    while True:
        chunk = await asyncio.wait_for(reader.read(65536), timeout)
        if not chunk:
            break
        raw += chunk
    writer.close()
    head, body = raw.split(b"\r\n\r\n", 1)
    return head, body
