import sys
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from mqtt_tpu.ops import TpuMatcher
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.ops.matcher import match_batch
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
for i in range(200_000):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
m = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16)
m.rebuild()
salt = m.csr.salt
topics = [f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}" for _ in range(16384)]
res = tuple(jnp.asarray(a) for a in tokenize_topics(topics, 4, salt)[:4])
lowered = match_batch.lower(*m.device_arrays, *res, frontier=8, out_slots=64, search_iters=8)
comp = lowered.compile()
txt = comp.as_text()
open("/root/repo/exp/match.hlo.txt", "w").write(txt)
print("bytes:", len(txt))
