"""E3: sync-method / table-size / buffer-identity matrix for kernel timing."""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from mqtt_tpu.ops import TpuMatcher
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

red = jax.jit(lambda o: o.sum())

def build(N):
    rng = random.Random(7)
    v0 = [f"region{i}" for i in range(100)]
    v1 = [f"device{i}" for i in range(100)]
    v2 = [f"metric{i}" for i in range(100)]
    index = TopicsIndex()
    for i in range(N):
        parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
        if rng.random() < 0.10:
            parts[rng.randrange(3)] = "+"
        index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
    def topic():
        return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"
    return index, topic

B = 16384
for N in (200_000, 1_000_000):
    index, topic = build(N)
    m = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16)
    m.rebuild()
    print(f"N={N} nodes={m.csr.num_nodes} iters_search={m.search_iters}", flush=True)
    salt = m.csr.salt
    batches = [[topic() for _ in range(B)] for _ in range(4)]
    resident = [tuple(jnp.asarray(a) for a in tokenize_topics(bt, 4, salt)[:4]) for bt in batches]
    jax.block_until_ready(resident)
    int(np.asarray(red(m.match_tokens(*resident[0])[0])))  # compile+warm

    iters = 12
    # A: same buffer, block_until_ready
    t0 = time.perf_counter()
    outs = [m.match_tokens(*resident[0])[0] for _ in range(iters)]
    outs[-1].block_until_ready()
    print(f"  same+bur:      {(time.perf_counter()-t0)/iters*1e3:8.1f} ms/batch", flush=True)
    # B: same buffer, scalar D2H on last
    t0 = time.perf_counter()
    outs = [m.match_tokens(*resident[0])[0] for _ in range(iters)]
    int(np.asarray(red(outs[-1])))
    print(f"  same+d2h:      {(time.perf_counter()-t0)/iters*1e3:8.1f} ms/batch", flush=True)
    # C: distinct buffers, block_until_ready
    t0 = time.perf_counter()
    outs = [m.match_tokens(*resident[i % 4])[0] for i in range(iters)]
    outs[-1].block_until_ready()
    print(f"  distinct+bur:  {(time.perf_counter()-t0)/iters*1e3:8.1f} ms/batch", flush=True)
    # D: distinct buffers, scalar D2H on last
    t0 = time.perf_counter()
    outs = [m.match_tokens(*resident[i % 4])[0] for i in range(iters)]
    int(np.asarray(red(outs[-1])))
    print(f"  distinct+d2h:  {(time.perf_counter()-t0)/iters*1e3:8.1f} ms/batch", flush=True)
