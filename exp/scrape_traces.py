#!/usr/bin/env python
"""CI trace-scrape gate: boot a broker with the trace plane on (sample
every publish), drive a short publish burst over real TCP, fetch ``GET
/traces`` from the stats listener, validate it with the pure-Python
trace-event checker (mqtt_tpu.tracing.check_trace_events), assert the
publish span trees actually recorded, and write the snapshot to disk —
the workflow uploads it as an artifact, so every CI run carries a
Perfetto-loadable trace of its own publish burst.

Usage: python exp/scrape_traces.py [--out traces-snapshot.json]
Exits non-zero when the export fails to parse or the expected spans are
missing.
"""

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main(out_path: str) -> int:
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes
    from mqtt_tpu.tracing import check_trace_events

    try:  # the device sub-stage spans need the device matcher; CPU jax works
        import jax  # noqa: F401

        device = True
    except ImportError:
        device = False

    opts = Options(
        device_matcher=device,
        matcher_opts={"max_levels": 4, "background": False} if device else None,
        telemetry_sample=1,
        trace_sample=1,  # trace everything: a 2s burst must register
    )
    srv = Server(opts)
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
        )
    )
    await srv.serve()
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)

        sr, sw = await asyncio.open_connection(host, int(port))
        sw.write(_connect_bytes("trace-sub", version=4))
        await sw.drain()
        await sr.readexactly(4)
        sw.write(_subscribe_bytes(1, "bench/#"))
        await sw.drain()
        await sr.readexactly(5)
        if srv.matcher is not None:
            srv.matcher.flush()

        pr, pw = await asyncio.open_connection(host, int(port))
        pw.write(_connect_bytes("trace-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        for i in range(200):
            topic = f"bench/{i % 10}".encode()
            payload = b"x" * 16
            body = len(topic).to_bytes(2, "big") + topic + payload
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()
        # a cold first batch pays the JIT compile (seconds on a fresh
        # XLA cache): keep waiting to the deadline instead of bailing on
        # the first quiet read — the span tree only exists once fan-out
        # completed, so leaving early reads an empty ring
        deadline = asyncio.get_event_loop().time() + 60
        got = 0
        while got < 200 and asyncio.get_event_loop().time() < deadline:
            try:
                data = await asyncio.wait_for(sr.read(65536), 1.0)
            except asyncio.TimeoutError:
                if got >= 200:
                    break
                continue
            if not data:
                break
            got += data.count(b"bench/")
        print(f"# delivered ~{got}/200 publishes", file=sys.stderr)

        from scrapelib import http_get

        head, body = await http_get(srv.listeners.get("s").address(), "/traces")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        doc = json.loads(body.decode())

        events = check_trace_events(doc)
        names = {e["name"] for e in doc["traceEvents"]}
        required = {"publish", "decode", "admission", "fanout"}
        if device:
            required |= {"staging_wait"}
        missing = sorted(required - names)
        if missing:
            print(f"FAIL: trace export missing spans {missing}", file=sys.stderr)
            return 1
        roots = sum(1 for e in doc["traceEvents"] if e["name"] == "publish")
        with open(out_path, "w") as f:
            json.dump(doc, f)
        print(
            f"OK: {events} trace events ({roots} publish roots) parsed; "
            f"snapshot -> {out_path}",
            file=sys.stderr,
        )
        return 0
    finally:
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="traces-snapshot.json")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
