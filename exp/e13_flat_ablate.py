"""E13: ablate the flat kernel's stages on the real device.

e11 measured the full flat kernel at ~197K topics/s while e9's raw row
gathers run at ~60M rows/s — a ~40x gap. Variants isolate which stage
eats it: bucket gather (2-D vs flattened indices), window slice-gather,
hash-mix loop, one-hot compaction.
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from functools import partial

from mqtt_tpu.ops.flat import (
    BUCKET_ENTRIES, ENTRY_INTS, KIND_HASH, PLUS1, PLUS2, _M1, _M2,
    build_flat_index,
)
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

N = int(os.environ.get("NSUBS", "200000"))
B = int(os.environ.get("B", "16384"))
rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
for i in range(N):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
flat = build_flat_index(index, max_levels=4)
print(f"built: entries={flat.n_entries} S={flat.table.shape[0]} P={flat.num_patterns}", flush=True)

table = jnp.asarray(flat.table)
pat_kind = jnp.asarray(flat.pat_kind)
pat_depth = jnp.asarray(flat.pat_depth)
pat_mask = jnp.asarray(flat.pat_mask)
topics = [f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}" for _ in range(B)]
tok1, tok2, lengths, is_dollar, _ = tokenize_topics(topics, 4, flat.salt)
tok1 = jnp.asarray(tok1); tok2 = jnp.asarray(tok2)
lengths = jnp.asarray(lengths); is_dollar = jnp.asarray(is_dollar)
jax.block_until_ready((table, tok1, tok2))
W = flat.window
L = 4
P = int(pat_depth.shape[0])
S = int(flat.table.shape[0])


def hashes(tok1, tok2, lengths):
    m1 = jnp.uint32(_M1); m2 = jnp.uint32(_M2)
    kd = pat_depth.astype(jnp.uint32)
    h1 = jnp.broadcast_to((kd * m2 ^ pat_kind)[None, :], (B, P))
    h2 = jnp.broadcast_to((kd * m1 ^ pat_kind)[None, :], (B, P))
    def rotl13(x):
        return (x << jnp.uint32(13)) | (x >> jnp.uint32(19))
    for d in range(L):
        use = (d < pat_depth)[None, :]
        plus = ((pat_mask >> np.uint32(d)) & 1)[None, :] == 1
        t1 = jnp.where(plus, jnp.uint32(PLUS1), tok1[:, d][:, None])
        t2 = jnp.where(plus, jnp.uint32(PLUS2), tok2[:, d][:, None])
        h1 = jnp.where(use, rotl13(h1 ^ t1) * m1, h1)
        h2 = jnp.where(use, rotl13(h2 ^ t2) * m1, h2)
    n = lengths[:, None]
    hash_pat = (pat_kind == jnp.uint32(KIND_HASH))[None, :]
    active = jnp.where(hash_pat, pat_depth[None, :] <= n, pat_depth[None, :] == n)
    return h1, h2, active


@jax.jit
def v_hash_only(tok1, tok2, lengths, is_dollar):
    h1, h2, active = hashes(tok1, tok2, lengths)
    return h1.sum() + h2.sum()


@jax.jit
def v_bucket_2d(tok1, tok2, lengths, is_dollar):
    h1, h2, active = hashes(tok1, tok2, lengths)
    slot = jnp.where(active, (h1 & jnp.uint32(S - 1)).astype(jnp.int32), 0)
    rows = table[slot]  # [B, P, 16]
    return rows.sum()


@jax.jit
def v_bucket_1d(tok1, tok2, lengths, is_dollar):
    h1, h2, active = hashes(tok1, tok2, lengths)
    slot = jnp.where(active, (h1 & jnp.uint32(S - 1)).astype(jnp.int32), 0)
    rows = table[slot.reshape(-1)].reshape(B, P, ENTRY_INTS * BUCKET_ENTRIES)
    return rows.sum()


@jax.jit
def v_full_no_compact(tok1, tok2, lengths, is_dollar):
    from mqtt_tpu.ops.flat import flat_match_core
    out, totals, ovf = flat_match_core(
        table, pat_kind, pat_depth, pat_mask,
        tok1, tok2, lengths, is_dollar,
        max_levels=L, out_slots=64,
    )
    return totals.sum()  # compaction may be DCE'd; see v_full


def v_full(tok1, tok2, lengths, is_dollar):
    from mqtt_tpu.ops.flat import flat_match
    out, totals, ovf = flat_match(
        table, pat_kind, pat_depth, pat_mask,
        tok1, tok2, lengths, is_dollar,
        max_levels=L, out_slots=64,
    )
    return out


def bench(name, f, iters=8):
    red = jax.jit(lambda o: o.sum() if hasattr(o, 'ndim') and o.ndim else o)
    r = f(tok1, tok2, lengths, is_dollar)
    np.asarray(red(r))  # compile + complete
    t0 = time.perf_counter()
    outs = [f(tok1, tok2, lengths, is_dollar) for _ in range(iters)]
    np.asarray(red(outs[-1]))
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:22s} {dt*1e3:8.2f} ms/batch -> {B/dt:>12,.0f} topics/s", flush=True)


bench("hash only", v_hash_only)
bench("+bucket gather 2d", v_bucket_2d)
bench("+bucket gather 1d", v_bucket_1d)
bench("full kernel", v_full)

# profile the full kernel
os.makedirs("/root/repo/exp/trace3", exist_ok=True)
with jax.profiler.trace("/root/repo/exp/trace3"):
    out = v_full(tok1, tok2, lengths, is_dollar)
    np.asarray(out[:1, :1].sum())
print("trace3 written", flush=True)
