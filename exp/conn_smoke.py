#!/usr/bin/env python
"""CI connection-scale gate for the event-loop shard fabric (ISSUE 15):
boot a broker with ``loop_shards > 1``, ramp thousands of mostly-idle
connections through the shard router, push a publish burst, and assert

- ``GET /healthz`` answers 200 with the whole population attached,
- ZERO delivery mismatches vs the host-trie oracle (cross-shard fan-out
  must be delivery-identical to the single-loop walk),
- the per-shard live-connection spread stays within 2x (the
  least-loaded router actually balanced the ramp), and
- every connection landed on a fabric shard (none fell back to the
  main loop).

The connection count adapts to the process fd budget (each idle
connection costs two fds in this single-process harness); the gate
FAILS only below a 512-connection floor. The spread/ramp/burst block is
written to ``--out`` and uploaded as a CI artifact.

Usage: python exp/conn_smoke.py [--conns 5000] [--shards 4] [--out conn-smoke.json]
"""

import argparse
import asyncio
import collections
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SUB_FILTERS = {
    "wild-hash": "conns/#",
    "wild-plus": "conns/+/x",
    "exact": "conns/d3/x",
}
N_PUBLISHES = 500
MIN_CONNS = 512


def fd_budget(target: int) -> int:
    """Raise RLIMIT_NOFILE toward the hard limit and clamp the ramp to
    what the budget allows (2 fds per connection + 512 slack)."""
    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    return max(MIN_CONNS, min(target, (soft - 512) // 2))


async def _drain_topics(reader, counts, stop):
    buf = b""
    while not stop.is_set():
        try:
            data = await asyncio.wait_for(reader.read(65536), 0.5)
        except asyncio.TimeoutError:
            continue
        if not data:
            return
        buf += data
        while len(buf) >= 2:
            if buf[0] >> 4 != 3:
                buf = buf[1:]
                continue
            rl = buf[1]
            if rl & 0x80 or len(buf) < 2 + rl:
                break
            frame = buf[2 : 2 + rl]
            tlen = int.from_bytes(frame[:2], "big")
            counts[frame[2 : 2 + tlen].decode()] += 1
            buf = buf[2 + rl :]


async def main(conns: int, shards: int, out_path: str) -> int:
    from exp.scrapelib import http_get
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes, ramp_idle

    conns = fd_budget(conns)
    srv = Server(Options(loop_shards=shards))
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
            health=srv.health_report,
        )
    )
    await srv.serve()
    stop = asyncio.Event()
    drains = []
    idle_writers = []
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)
        http_addr = srv.listeners.get("s").address()

        # -- ramp: mostly-idle device population (stress.ramp_idle:
        # keepalive 0, so a slow CI box can never reap the population
        # mid-gate) ------------------------------------------------------
        t0 = time.monotonic()
        idle_writers.extend(await ramp_idle(host, int(port), conns))
        ramp_s = time.monotonic() - t0
        attached = srv.info.clients_connected
        print(
            f"# ramped {conns} idle connections in {ramp_s:.1f}s "
            f"(attached={attached})",
            file=sys.stderr,
        )

        # -- oracle subscribers + publish burst --------------------------
        counts: dict = {}
        for name, flt in SUB_FILTERS.items():
            r, w = await asyncio.open_connection(host, int(port))
            w.write(_connect_bytes(f"smoke-{name}", version=4))
            await w.drain()
            await r.readexactly(4)
            w.write(_subscribe_bytes(1, flt))
            await w.drain()
            await r.readexactly(5)
            counts[name] = collections.Counter()
            drains.append(
                asyncio.get_event_loop().create_task(
                    _drain_topics(r, counts[name], stop)
                )
            )

        topics = [
            f"conns/d{i % 10}/{'x' if i % 3 else 'y'}"
            for i in range(N_PUBLISHES)
        ]
        expected = {name: collections.Counter() for name in SUB_FILTERS}
        for t in topics:
            subs = srv.topics.subscribers(t)
            for cid in subs.subscriptions:
                name = cid.removeprefix("smoke-")
                if name in expected:
                    expected[name][t] += 1

        pr, pw = await asyncio.open_connection(host, int(port))
        pw.write(_connect_bytes("smoke-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        for t in topics:
            tb = t.encode()
            body = len(tb).to_bytes(2, "big") + tb + b"p"
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()

        want_total = sum(sum(c.values()) for c in expected.values())
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if sum(sum(c.values()) for c in counts.values()) >= want_total:
                break
            await asyncio.sleep(0.2)
        stop.set()
        await asyncio.gather(*drains, return_exceptions=True)

        mismatches = 0
        for name in SUB_FILTERS:
            if counts[name] != expected[name]:
                mismatches += 1
                missing = expected[name] - counts[name]
                surplus = counts[name] - expected[name]
                print(
                    f"FAIL: {name} diverged from the host-trie oracle "
                    f"(missing={dict(list(missing.items())[:5])} "
                    f"surplus={dict(list(surplus.items())[:5])})",
                    file=sys.stderr,
                )

        # -- gates --------------------------------------------------------
        head, body = await http_get(http_addr, "/healthz", timeout=15.0)
        healthz_ok = b"200" in head.split(b"\r\n", 1)[0]
        spread = srv._fabric.spread()
        unowned = 0
        for cl in srv.clients.get_all().values():
            if cl.closed or cl.net.inline:
                continue
            if not srv._fabric.owns(cl.net.loop):
                unowned += 1
        block = {
            "conns": conns,
            "shards": shards,
            "ramp_seconds": round(ramp_s, 2),
            "conns_per_second": round(conns / max(ramp_s, 1e-9)),
            "attached": attached,
            "spread": {str(k): v for k, v in spread.items()},
            "unowned_connections": unowned,
            "healthz_ok": healthz_ok,
            "publishes": N_PUBLISHES,
            "oracle_checked_deliveries": want_total,
            "oracle_mismatched_subscribers": mismatches,
        }
        with open(out_path, "w") as f:
            json.dump(block, f, indent=2)
        print(f"# conn block -> {out_path}: {json.dumps(block)}",
              file=sys.stderr)

        if not healthz_ok:
            print(f"FAIL: /healthz -> {head!r}", file=sys.stderr)
            return 1
        if mismatches:
            return 1
        if attached < conns:
            print(
                f"FAIL: only {attached}/{conns} connections attached",
                file=sys.stderr,
            )
            return 1
        if unowned:
            print(
                f"FAIL: {unowned} connections not owned by any shard",
                file=sys.stderr,
            )
            return 1
        lo, hi = min(spread.values()), max(spread.values())
        if lo <= 0 or hi > 2 * lo:
            print(
                f"FAIL: per-shard spread {spread} outside the 2x bound",
                file=sys.stderr,
            )
            return 1
        print(
            f"OK: {conns} connections over {shards} shards "
            f"(spread {spread}), healthz 200, {want_total} oracle-checked "
            "deliveries, zero mismatches",
            file=sys.stderr,
        )
        return 0
    finally:
        stop.set()
        for w in idle_writers:
            try:
                w.close()
            except Exception:
                pass
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--conns", type=int, default=5000)
    ap.add_argument("--shards", type=int, default=4)
    ap.add_argument("--out", default="conn-smoke.json")
    args = ap.parse_args()
    sys.exit(asyncio.run(main(args.conns, args.shards, args.out)))
