#!/usr/bin/env python
"""Bench-history trend gate (ISSUE 14 satellite, wired into CI beside
exp/stage_gate.py).

``bench.py`` appends each non-skipped round's headline + per-config
scalar blocks to ``BENCH_HISTORY.jsonl`` (one JSON object per line).
This gate reads the last K usable rounds and FAILS when the newest
headline regressed more than ``--threshold`` (default 25%) below the
MEDIAN of the preceding rounds — median, not max, so one lucky round on
a quiet box cannot turn every successor red, and not newest-vs-previous
alone, so a two-round noise dip does not slip through as the new
baseline.

Robustness rules (the stage-gate posture: a gate that cries wolf gets
deleted):
- entries with a null/zero headline never enter the window (bench.py
  already refuses to append skipped rounds; this end double-checks);
- fewer than 2 usable rounds passes with a notice — absence of history
  is not a regression;
- ``--backfill`` seeds the ledger from the repo's canonical
  ``BENCH_rNN.json`` artifacts (skipped rounds excluded), deduped by
  round tag, so the trajectory starts from the rounds that already
  exist instead of an empty file.

Usage:
    python exp/bench_trend.py                    # gate the ledger
    python exp/bench_trend.py --backfill         # seed from BENCH_rNN.json
    python exp/bench_trend.py --last 8 --threshold 0.3
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import statistics
import sys

_CANONICAL_RE = re.compile(r"^BENCH_r(\d+)\.json$")


def load_history(path: str) -> list[dict]:
    """Ledger entries in file order; malformed lines are skipped with a
    notice (a half-written line from a crashed bench run must not brick
    the gate)."""
    if not os.path.exists(path):
        return []
    out: list[dict] = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                entry = json.loads(line)
            except ValueError:
                print(f"bench-trend: skipping malformed line {i} in {path}")
                continue
            if isinstance(entry, dict):
                out.append(entry)
    return out


def usable_rounds(entries: list[dict]) -> list[dict]:
    """Entries that carry a real headline (positive numeric value)."""
    out = []
    for e in entries:
        v = e.get("value")
        if isinstance(v, (int, float)) and v > 0:
            out.append(e)
    return out


def check_trend(
    entries: list[dict], last: int = 5, threshold: float = 0.25
) -> tuple[bool, str]:
    """(ok, message) over the last ``last`` usable rounds: the newest
    must hold >= (1 - threshold) x median(previous rounds). Only rounds
    measuring the SAME metric as the newest participate — a headline
    redefinition (r01's kernel-rate metric vs the e2e metric) starts a
    fresh trend line instead of comparing apples to oranges."""
    rounds = usable_rounds(entries)
    if rounds:
        metric = rounds[-1].get("metric")
        rounds = [e for e in rounds if e.get("metric") == metric]
    rounds = rounds[-last:]
    if len(rounds) < 2:
        return True, (
            f"bench-trend: {len(rounds)} usable round(s) in the window; "
            "nothing to gate"
        )
    newest = rounds[-1]
    prev = [float(e["value"]) for e in rounds[:-1]]
    baseline = statistics.median(prev)
    value = float(newest["value"])
    floor = baseline * (1.0 - threshold)
    tag = newest.get("round") or f"t={newest.get('time_unix')}"
    if value < floor:
        return False, (
            f"bench-trend REGRESSION: newest headline ({tag}) "
            f"{value:.1f} fell below {floor:.1f} "
            f"(median of {len(prev)} prior round(s) {baseline:.1f}, "
            f"threshold -{100 * threshold:.0f}%)"
        )
    return True, (
        f"bench-trend: newest headline ({tag}) {value:.1f} vs prior-median "
        f"{baseline:.1f} across {len(rounds)} round(s); within "
        f"-{100 * threshold:.0f}%"
    )


def check_config_scalar(
    entries: list[dict],
    config: str,
    key: str,
    last: int = 5,
    threshold: float = 0.25,
) -> tuple[bool, str]:
    """(ok, message) for one per-config scalar's trajectory — the same
    median-window rule as the headline, over ``configs[config][key]``
    (e.g. cfg 8's ``receive_flatness_ratio``, ISSUE 15). Entries that
    never measured the scalar are skipped; fewer than 2 usable rounds
    passes with a notice, and the NEWEST round not carrying it passes
    too (a partial-config run must not be judged on a cell it skipped)."""
    rounds = []
    for e in entries:
        v = ((e.get("configs") or {}).get(config) or {}).get(key)
        if isinstance(v, (int, float)) and v > 0:
            rounds.append((e, float(v)))
    rounds = rounds[-last:]
    if len(rounds) < 2:
        return True, (
            f"bench-trend[{config}.{key}]: {len(rounds)} usable round(s); "
            "nothing to gate"
        )
    newest_entry, value = rounds[-1]
    if entries and entries[-1] is not newest_entry:
        return True, (
            f"bench-trend[{config}.{key}]: newest round did not measure "
            "it; nothing to gate"
        )
    prev = [v for _e, v in rounds[:-1]]
    baseline = statistics.median(prev)
    floor = baseline * (1.0 - threshold)
    tag = newest_entry.get("round") or f"t={newest_entry.get('time_unix')}"
    if value < floor:
        return False, (
            f"bench-trend[{config}.{key}] REGRESSION: newest ({tag}) "
            f"{value:.4f} fell below {floor:.4f} "
            f"(median of {len(prev)} prior round(s) {baseline:.4f}, "
            f"threshold -{100 * threshold:.0f}%)"
        )
    return True, (
        f"bench-trend[{config}.{key}]: newest ({tag}) {value:.4f} vs "
        f"prior-median {baseline:.4f} across {len(rounds)} round(s); "
        f"within -{100 * threshold:.0f}%"
    )


# per-config scalars gated beside the headline: (config, key)
CONFIG_SCALARS = (
    ("8_publish_storm", "receive_flatness_ratio"),
    # durable session plane (ISSUE 16): snapshot+tail replay throughput
    # at the largest swept scale, and the device retained scan rate
    ("11_durable_recovery", "recovery_keys_per_sec"),
    ("11_durable_recovery", "retained_device_scans_per_sec"),
    # mesh predicate push-down (ISSUE 17): the per-edge filter decision
    # rate — the filtered RATIO is asserted inside cfg12 itself (a
    # silent pass-through degradation errors the config, which this
    # gate's >0 usability rule would otherwise skip)
    ("12_mesh_pushdown", "pushdown_filter_evals_per_sec"),
    # device observability plane (ISSUE 18): real-accelerator keystream
    # byte rate (a skip dict on CPU-jax rounds is ignored by the gate),
    # and the steady-state recompile guard rides cfg 2's block — the
    # scalar is asserted == 0 by tier-1 tests; the ledger keeps it for
    # post-hoc attribution when a regression lands anyway
    ("10_recrypt_matrix", "keystream_device_bytes_per_sec"),
    # scenario lab (ISSUE 20): exp/scenario_lab.py appends matrix
    # rounds under its own headline metric; these per-scenario rates
    # catch a slow scenario (throughput cliff) even while its oracle
    # still passes. Pass/fail itself is enforced by the lab's exit
    # code, not here — "passed" is a bit, not a trendable scalar.
    ("scenario_payload_sweep", "deliveries_per_sec"),
    ("scenario_qos2_fanout", "deliveries_per_sec"),
    ("scenario_tenant_rekey", "deliveries_per_sec"),
)


def backfill(repo: str, history_path: str) -> int:
    """Seed the ledger from the canonical BENCH_rNN.json artifacts in
    round order, skipping rounds already present (by tag) and rounds
    with no usable headline. Returns the number appended. Entries come
    from bench.history_entry — the ONE ledger schema, shared with the
    live append in bench.append_history."""
    sys.path.insert(0, repo)
    from bench import history_entry
    have = {
        e.get("round")
        for e in load_history(history_path)
        if e.get("round")
    }
    files = []
    for f in glob.glob(os.path.join(repo, "BENCH_*.json")):
        m = _CANONICAL_RE.match(os.path.basename(f))
        if m is not None:
            files.append((int(m.group(1)), f))
    appended = 0
    with open(history_path, "a", encoding="utf-8") as out:
        for _num, f in sorted(files):
            tag = os.path.splitext(os.path.basename(f))[0]
            if tag in have:
                continue
            try:
                with open(f, encoding="utf-8") as fh:
                    doc = json.load(fh)
            except (OSError, ValueError) as e:
                print(f"bench-trend: skipping unreadable {f}: {e}")
                continue
            if isinstance(doc.get("parsed"), dict):
                # driver-wrapped artifact: {"n","cmd","rc","tail","parsed"}
                doc = doc["parsed"]
            value = doc.get("value")
            if not isinstance(value, (int, float)) or value <= 0:
                print(f"bench-trend: {tag} has no usable headline; skipped")
                continue
            entry = history_entry(
                doc, round_tag=tag, time_unix=int(os.path.getmtime(f))
            )
            out.write(json.dumps(entry) + "\n")
            appended += 1
            print(f"bench-trend: backfilled {tag} (headline {value:.1f})")
    return appended


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--history", default=os.path.join(repo, "BENCH_HISTORY.jsonl")
    )
    ap.add_argument("--repo", default=repo)
    ap.add_argument("--last", type=int, default=5)
    ap.add_argument("--threshold", type=float, default=0.25)
    ap.add_argument(
        "--backfill",
        action="store_true",
        help="seed the ledger from BENCH_rNN.json artifacts, then gate",
    )
    args = ap.parse_args()

    if args.backfill:
        n = backfill(args.repo, args.history)
        print(f"bench-trend: backfill appended {n} round(s)")

    entries = load_history(args.history)
    if not entries:
        print(
            f"bench-trend: no history at {args.history}; run bench.py (or "
            "--backfill) to start the ledger"
        )
        return 0
    ok, msg = check_trend(entries, last=args.last, threshold=args.threshold)
    print(msg)
    rc = 0 if ok else 1
    for config, key in CONFIG_SCALARS:
        sok, smsg = check_config_scalar(
            entries, config, key, last=args.last, threshold=args.threshold
        )
        print(smsg)
        if not sok:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
