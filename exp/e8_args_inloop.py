"""E8: (a) dispatch cost vs #args / arg bytes; (b) in-dispatch primitive
rates via fori_loop chaining (no per-op dispatch)."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from functools import partial

def bench_call(name, f, args, iters=20):
    red = jax.jit(lambda o: jnp.asarray(o).ravel()[:1].sum() if hasattr(o, 'ravel') else o)
    out = f(*args)
    first = out[0] if isinstance(out, (tuple, list)) else out
    first.block_until_ready()
    np.asarray(first.ravel()[0] if first.ndim else first)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
        first = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(first.ravel()[0] if first.ndim else first)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:44s} {dt*1e3:8.2f} ms/call", flush=True)

# (a) dispatch floor vs arg count / size
tiny = [jnp.zeros((8,), jnp.int32) for _ in range(16)]
jax.block_until_ready(tiny)
f1 = jax.jit(lambda *a: a[0] + 1)
bench_call("1 tiny arg", f1, tiny[:1])
f16 = jax.jit(lambda *a: sum(a) + 1)
bench_call("16 tiny args", f16, tiny)
big1 = [jnp.zeros((1 << 20,), jnp.int32)]  # 4MB
big4 = [jnp.zeros((1 << 20,), jnp.int32) for _ in range(4)]  # 4x4MB
jax.block_until_ready(big1 + big4)
bench_call("1 x 4MB arg", jax.jit(lambda *a: a[0][:8] + 1), big1)
bench_call("4 x 4MB args", jax.jit(lambda *a: a[0][:8] + a[1][:8] + a[2][:8] + a[3][:8]), big4)
big32 = [jnp.zeros((1 << 23,), jnp.int32)]  # 32MB
jax.block_until_ready(big32)
bench_call("1 x 32MB arg", jax.jit(lambda *a: a[0][:8] + 1), big32)

# (b) in-dispatch rates: chain K dependent ops inside one jit
B = 131072
key = jax.random.PRNGKey(0)
K = 50
for N in (1 << 14, 1 << 20, 1 << 22):
    table = jnp.arange(N, dtype=jnp.int32)
    idx0 = jax.random.randint(key, (B,), 0, N, dtype=jnp.int32)
    jax.block_until_ready((table, idx0))
    @jax.jit
    def chain_gather(T, I):
        def body(k, I):
            return (T[I] + k) % N   # dependent gather chain
        return jax.lax.fori_loop(0, K, body, I)
    bench_call(f"{K}x chained gather[{B}] N={N}", chain_gather, (table, idx0), iters=3)

# elementwise chain for reference
x0 = jnp.zeros((B,), jnp.float32)
jax.block_until_ready(x0)
@jax.jit
def chain_ew(X):
    return jax.lax.fori_loop(0, K, lambda k, X: X * 1.000001 + k, X)
bench_call(f"{K}x chained elementwise[{B}]", chain_ew, (x0,), iters=3)
