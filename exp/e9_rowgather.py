"""E9: per-index cost of ROW gathers vs row width; one-hot matmul compaction cost."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

def bench1(name, f, args, iters=4):
    out = f(*args)
    first = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(first.ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
        first = out[0] if isinstance(out, (tuple, list)) else out
    np.asarray(first.ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    return dt

key = jax.random.PRNGKey(0)
S = 1 << 20  # 1M buckets
BI = 131072  # number of row indices (B*P)
K = 20  # chained reps inside one dispatch

for W in (1, 8, 16, 32, 64, 128):
    table = jnp.arange(S * W, dtype=jnp.int32).reshape(S, W) % 65536
    idx0 = jax.random.randint(key, (BI,), 0, S, dtype=jnp.int32)
    jax.block_until_ready((table, idx0))
    @jax.jit
    def chain(T, I):
        def body(k, I):
            rows = T[I]            # [BI, W] row gather
            return (I + rows[:, 0] + k) % S  # dependency
        return jax.lax.fori_loop(0, K, body, I)
    dt = bench1(f"W={W}", chain, (table, idx0))
    per = dt / K
    print(f"row width {W:4d} ints: {per*1e3:7.2f} ms per {BI} row-gathers"
          f" -> {BI/per/1e6:7.1f} M rows/s, {BI*W*4/per/1e9:7.1f} GB/s", flush=True)

# one-hot matmul compaction: [B, J] -> [B, Kc] with positions
B, J, Kc = 16384, 104, 64
ids = jax.random.randint(key, (B, J), 0, 65536, dtype=jnp.int32)
valid = jax.random.bernoulli(key, 0.2, (B, J))
jax.block_until_ready((ids, valid))
@jax.jit
def compact(ids, valid):
    pos = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1
    oh = (valid[:, :, None] & (pos[:, :, None] == jnp.arange(Kc)[None, None, :])).astype(jnp.float32)
    out = jnp.einsum("bj,bjk->bk", ids.astype(jnp.float32), oh)
    return out.astype(jnp.int32)
@jax.jit
def chain_compact(ids, valid):
    def body(k, acc):
        return acc + compact(ids, valid)
    return jax.lax.fori_loop(0, 10, body, jnp.zeros((B, Kc), jnp.int32))
dt = bench1("compact", chain_compact, (ids, valid)) / 10
print(f"one-hot compaction [16384,104]->[.,64]: {dt*1e3:.2f} ms per call", flush=True)
