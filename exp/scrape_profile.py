#!/usr/bin/env python
"""CI profile-scrape gate: boot a broker with the host observatory on,
drive a 100-client stress burst over real TCP (the client count ROADMAP
item 3's collapse is measured at), fetch ``GET /profile`` from the
stats listener, validate the collapsed export with the pure-Python
checker (mqtt_tpu.profiling.check_collapsed) and the ``?format=trace``
export with the trace-event checker, assert the lock plane and the
fan-out amplification counters actually populated on /metrics, and
write the collapsed snapshot to disk — the workflow uploads it as an
artifact, so every CI run carries a flamegraph of its own burst.

Usage: python exp/scrape_profile.py [--out profile-snapshot.txt]
Exits non-zero when an export fails to parse or the expected signals
are missing.
"""

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from scrapelib import http_get as _http_get  # noqa: E402


async def main(out_path: str) -> int:
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.profiling import check_collapsed
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import run_stress
    from mqtt_tpu.tracing import check_trace_events

    opts = Options(
        device_matcher=False,  # the HOST path is what this gate profiles
        telemetry_sample=1,
        profile_hz=97.0,  # a short burst must still land plenty of sweeps
        # broker and load generator share one process+loop here, so the
        # generator's own starved reads would trip the governor; this
        # gate validates the profile plane, not overload control
        overload_control=False,
    )
    srv = Server(opts)
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
        )
    )
    await srv.serve()
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)
        burst = await run_stress("127.0.0.1", int(port), 100, 60)
        print(f"# burst: {burst['aggregate_msgs_per_sec']} msgs/s", file=sys.stderr)
        stats_addr = srv.listeners.get("s").address()

        head, body = await _http_get(stats_addr, "/profile")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        collapsed = body.decode()
        stacks = check_collapsed(collapsed)

        head, body = await _http_get(stats_addr, "/profile?format=trace")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        events = check_trace_events(json.loads(body.decode()))

        head, body = await _http_get(stats_addr, "/metrics")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        text = body.decode()
        missing = [
            m
            for m in (
                "mqtt_tpu_profile_samples_total",
                "mqtt_tpu_lock_acquisitions_total",
                "mqtt_tpu_publish_encodes_total",
                "mqtt_tpu_fanout_amplification_ratio",
            )
            if m not in text
        ]
        if missing:
            print(f"FAIL: /metrics missing {missing}", file=sys.stderr)
            return 1
        # the 100-client burst MUST have exercised the instrumented
        # locks — a silent lock-plane regression would otherwise pass
        clients_acq = 0
        for line in text.splitlines():
            if line.startswith('mqtt_tpu_lock_acquisitions_total{lock="clients"}'):
                clients_acq = int(float(line.rsplit(" ", 1)[1]))
        if clients_acq <= 0:
            print("FAIL: clients lock saw no acquisitions", file=sys.stderr)
            return 1

        block = srv.host_profile_block()
        amp = block.get("fanout", {}).get("delivery_amplification")
        with open(out_path, "w") as f:
            f.write(collapsed)
        print(
            f"OK: {stacks} collapsed stacks, {events} trace events, "
            f"clients-lock acquisitions={clients_acq}, "
            f"delivery amplification={amp}; snapshot -> {out_path}",
            file=sys.stderr,
        )
        return 0
    finally:
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="profile-snapshot.txt")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
