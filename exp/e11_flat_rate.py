"""E11: flat matcher kernel rate at cfg2 scale (1M subs)."""
import sys, time, os
sys.path.insert(0, "/root/repo")
sys.path.insert(0, "/root/repo/exp")
import numpy as np, random
import jax, jax.numpy as jnp
from e10_flat_proto import build_flat, flat_match, subscribers_flat, canon
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
N = int(os.environ.get("NSUBS", "1000000"))
for i in range(N):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
def topic():
    return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

t0 = time.perf_counter()
built = build_flat(index, max_levels=4, window=16)
print(f"total build {time.perf_counter()-t0:.1f}s", flush=True)
built["dev"] = tuple(jnp.asarray(a) for a in
                     (built["table"], built["all_ids"], built["pat_kind"], built["pat_depth"], built["pat_mask"]))
jax.block_until_ready(built["dev"])

# parity spot-check
topics = [topic() for _ in range(64)]
got = subscribers_flat(built, topics, index)
bad = sum(1 for t, g in zip(topics, got) if canon(g) != canon(index.subscribers(t)))
print(f"parity: {64-bad}/64", flush=True)

salt = built["salt"]
for B in (16384, 65536, 131072):
    batches = [[topic() for _ in range(B)] for _ in range(4)]
    resident = [tuple(jnp.asarray(a) for a in tokenize_topics(bt, 4, salt)[:4]) for bt in batches]
    jax.block_until_ready(resident)
    args = built["dev"]
    def run(i):
        return flat_match(*args, *resident[i % 4], window=16, max_levels=4, out_slots=64)
    np.asarray(run(0)[0].ravel()[0])  # compile+complete
    iters = 10
    t0 = time.perf_counter()
    outs = [run(i) for i in range(iters)]
    np.asarray(outs[-1][0].ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    print(f"B={B}: {dt*1e3:7.2f} ms/batch -> {B/dt:,.0f} topics/s", flush=True)

# profile one batch
os.makedirs("/root/repo/exp/trace2", exist_ok=True)
B = 16384
batch = [[topic() for _ in range(B)]]
res = tuple(jnp.asarray(a) for a in tokenize_topics(batch[0], 4, salt)[:4])
jax.block_until_ready(res)
args = built["dev"]
np.asarray(flat_match(*args, *res, window=16, max_levels=4, out_slots=64)[0].ravel()[0])
with jax.profiler.trace("/root/repo/exp/trace2"):
    out = flat_match(*args, *res, window=16, max_levels=4, out_slots=64)
    np.asarray(out[0].ravel()[0])
print("trace2 written", flush=True)
