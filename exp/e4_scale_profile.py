"""E4: B-scaling at fixed table + jax.profiler attempt + ablations."""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from mqtt_tpu.ops import TpuMatcher
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

red = jax.jit(lambda o: o.sum())
rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
for i in range(200_000):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
def topic():
    return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

m = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16)
m.rebuild()
salt = m.csr.salt
print("nodes", m.csr.num_nodes, flush=True)

def timeit(B, iters=6):
    topics = [topic() for _ in range(B)]
    res = tuple(jnp.asarray(a) for a in tokenize_topics(topics, 4, salt)[:4])
    jax.block_until_ready(res)
    int(np.asarray(red(m.match_tokens(*res)[0])))  # compile+complete
    t0 = time.perf_counter()
    outs = [m.match_tokens(*res)[0] for _ in range(iters)]
    int(np.asarray(red(outs[-1])))
    dt = (time.perf_counter() - t0) / iters
    print(f"B={B}: {dt*1e3:.1f} ms/batch -> {B/dt:,.0f} topics/s", flush=True)
    return res

for B in (512, 2048, 8192, 16384):
    res = timeit(B)

# profiler attempt
try:
    os.makedirs("/root/repo/exp/trace", exist_ok=True)
    with jax.profiler.trace("/root/repo/exp/trace"):
        out = m.match_tokens(*res)[0]
        int(np.asarray(red(out)))
    print("profiler trace written", flush=True)
except Exception as e:
    print("profiler failed:", repr(e), flush=True)
