#!/usr/bin/env python
"""CI crash-recovery gate for the durable session plane (ISSUE 16):
seed a broker subprocess with persistent sessions + retained state
backed by the log-structured store, ``kill -9`` it, restart a broker
on the same store directory, and assert

- the restore finishes inside the recovery budget (wall-clock),
- ``/healthz`` reports ``recovering`` (not ready) mid-restore and
  answers 200 once the maps are served,
- every seeded subscription and retained message survives the kill
  (``durable/restored_*`` match the seed exactly),
- the delivery oracle holds: reconnecting persisted clients resume
  their session (CONNACK session-present), live publishes route
  through the restored trie, and fresh subscribers receive the
  pre-crash retained payloads bit-identically, and
- the device-resident retained matcher served those retained scans
  with ZERO differential-oracle mismatches and zero error fallbacks.

The seed leg runs in a child process so the SIGKILL is real: nothing
gets a chance to flush, and recovery starts from whatever the store's
fsync discipline put on disk (the child seeds with ``sync=True`` so
the expected post-crash state is exact). The block is written to
``--out`` and uploaded as a CI artifact.

Usage: python exp/recovery_smoke.py [--sessions 400] [--retained 200]
           [--budget 10.0] [--out recovery-smoke.json]
"""

import argparse
import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ORACLE_SAMPLE = 20  # persisted sessions re-driven end-to-end after restart


def _connect(cid: str, clean: bool) -> bytes:
    from mqtt_tpu.packets import CONNECT, ConnectParams, FixedHeader, Packet
    from mqtt_tpu.packets import encode_packet

    return encode_packet(
        Packet(
            fixed_header=FixedHeader(type=CONNECT),
            protocol_version=4,
            connect=ConnectParams(
                protocol_name=b"MQTT",
                clean=clean,
                keepalive=0,
                client_identifier=cid,
            ),
        )
    )


def _subscribe(pid: int, flt: str) -> bytes:
    from mqtt_tpu.packets import SUBSCRIBE, FixedHeader, Packet, Subscription
    from mqtt_tpu.packets import encode_packet

    return encode_packet(
        Packet(
            fixed_header=FixedHeader(type=SUBSCRIBE, qos=1),
            protocol_version=4,
            packet_id=pid,
            filters=[Subscription(filter=flt)],
        )
    )


def _publish(topic: str, payload: bytes, retain: bool = False) -> bytes:
    from mqtt_tpu.packets import PUBLISH, FixedHeader, Packet, encode_packet

    return encode_packet(
        Packet(
            fixed_header=FixedHeader(type=PUBLISH, retain=retain),
            protocol_version=4,
            topic_name=topic,
            payload=payload,
        )
    )


async def _read_frame(reader, timeout: float = 10.0):
    """One MQTT frame -> (packet type, body bytes)."""

    async def _inner():
        b1 = await reader.readexactly(1)
        mul, rl = 1, 0
        while True:
            b = (await reader.readexactly(1))[0]
            rl += (b & 0x7F) * mul
            if not b & 0x80:
                break
            mul *= 128
        body = await reader.readexactly(rl) if rl else b""
        return b1[0] >> 4, body

    return await asyncio.wait_for(_inner(), timeout)


async def _pub_frame(reader, timeout: float = 10.0):
    """Skip non-PUBLISH frames (SUBACK ordering is unspecified) and
    return (topic, payload) of the first QoS0 PUBLISH."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ptype, body = await _read_frame(reader, deadline - time.monotonic())
        if ptype != 3:
            continue
        tlen = int.from_bytes(body[:2], "big")
        return body[2 : 2 + tlen].decode(), body[2 + tlen :]
    raise asyncio.TimeoutError


async def child(store: str, sessions: int, retained: int) -> int:
    """Seed leg: boot a broker over the store, create the persistent
    population through the real wire path, then wait to be SIGKILLed."""
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server

    srv = Server(Options())
    srv.add_hook(AllowHook())
    # sync=True: every append is fsynced, so the kill -9 must lose
    # NOTHING -- the restart leg can assert exact counts
    srv.add_hook(LogKVStore(), LogKVOptions(path=store, sync=True))
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    await srv.serve()
    host, port_s = srv.listeners.get("t").address().rsplit(":", 1)
    port = int(port_s)

    # persistent sessions: v4 clean=False CONNECT + one wildcard
    # SUBSCRIBE each, then an abrupt socket close -- the session and
    # its subscription must survive in the log
    for i in range(sessions):
        r, w = await asyncio.open_connection(host, port)
        w.write(_connect(f"rec-{i}", clean=False))
        await w.drain()
        await r.readexactly(4)
        w.write(_subscribe(1, f"rec/c{i}/#"))
        await w.drain()
        await r.readexactly(5)
        w.close()

    # retained state: one transient publisher, one retained QoS0
    # message per session topic
    r, w = await asyncio.open_connection(host, port)
    w.write(_connect("rec-seed-pub", clean=True))
    await w.drain()
    await r.readexactly(4)
    for i in range(retained):
        w.write(_publish(f"rec/c{i}/state", f"v{i}".encode(), retain=True))
    await w.drain()

    # QoS0 publishes race the broker's async read loop: wait until the
    # broker itself holds (and has therefore persisted) every one.
    # Count only the seeded namespace -- the broker's own $SYS retained
    # rows live in the same store and would satisfy the bound early.
    def _seeded() -> int:
        return sum(
            1 for t in srv.topics.retained.get_all() if t.startswith("rec/")
        )

    deadline = time.monotonic() + 60
    while _seeded() < retained:
        if time.monotonic() > deadline:
            print(f"CHILD-FAIL retained={_seeded()}", flush=True)
            return 1
        await asyncio.sleep(0.05)

    print(f"SEEDED {sessions} {_seeded()}", flush=True)
    await asyncio.sleep(3600)  # the parent kill -9s us here
    return 0


def _seed_and_kill(store: str, sessions: int, retained: int) -> None:
    """Run the seed leg in a subprocess and SIGKILL it once seeded."""
    proc = subprocess.Popen(
        [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            "--store",
            store,
            "--sessions",
            str(sessions),
            "--retained",
            str(retained),
        ],
        stdout=subprocess.PIPE,
        stderr=sys.stderr,
        text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        assert proc.stdout is not None
        line = ""
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line and proc.poll() is not None:
                raise RuntimeError("seed child exited before SEEDED")
            if line.startswith("SEEDED") or line.startswith("CHILD-FAIL"):
                break
        if not line.startswith("SEEDED"):
            raise RuntimeError(f"seed child never seeded: {line!r}")
        print(f"# seed child (pid {proc.pid}): {line.strip()}", file=sys.stderr)
    finally:
        # the point of the gate: no shutdown path runs, nothing flushes
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait()
        if proc.stdout is not None:
            proc.stdout.close()


async def restart(store: str, sessions: int, retained: int,
                  budget_s: float, out_path: str) -> int:
    """Restart leg: recover the store, gate the budget/healthz flip,
    then re-drive a session sample through the delivery oracle."""
    from exp.scrapelib import http_get
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.hooks.storage.logkv import LogKVOptions, LogKVStore
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server

    opts = Options(
        retained_matcher=True,
        retained_oracle_sample=1,  # oracle-check EVERY device retained scan
        durable_restore_batch=64,
    )
    opts.hooks = [(LogKVStore(), LogKVOptions(path=store))]
    srv = Server(opts)
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
            health=srv.health_report,
        )
    )

    # sample readiness DURING the restore (read_store blocks the loop,
    # so an HTTP poll cannot race it deterministically): wrap the last
    # restore stage and snapshot health_report right before it runs
    mid: dict = {}
    orig_load_retained = srv.load_retained

    def _spy(v):
        ok, detail = srv.health_report()
        mid["ready"] = ok
        mid["not_ready"] = list(detail.get("not_ready", []))
        return orig_load_retained(v)

    srv.load_retained = _spy  # type: ignore[method-assign]

    t0 = time.monotonic()
    await srv.serve()
    serve_s = time.monotonic() - t0
    failures: list[str] = []
    try:
        host, port_s = srv.listeners.get("t").address().rsplit(":", 1)
        port = int(port_s)
        http_addr = srv.listeners.get("s").address()
        dur = srv._durable

        # -- recovery budget + restored-count gates ----------------------
        if dur["recovery_seconds"] > budget_s:
            failures.append(
                f"recovery took {dur['recovery_seconds']:.3f}s "
                f"(budget {budget_s}s)"
            )
        if dur["restored_subscriptions"] != sessions:
            failures.append(
                f"restored_subscriptions={dur['restored_subscriptions']} "
                f"!= seeded {sessions}"
            )
        if dur["restored_retained"] != retained:
            failures.append(
                f"restored_retained={dur['restored_retained']} "
                f"!= seeded {retained}"
            )
        if dur["recovering"]:
            failures.append("still recovering after serve()")

        # -- healthz: 503 mid-restore, 200 once serving ------------------
        if mid.get("ready", True) or "recovering" not in mid.get(
            "not_ready", []
        ):
            failures.append(f"mid-restore health was not 'recovering': {mid}")
        head, _body = await http_get(http_addr, "/healthz", timeout=15.0)
        healthz_ok = b"200" in head.split(b"\r\n", 1)[0]
        if not healthz_ok:
            failures.append(f"/healthz after restore -> {head!r}")

        # -- delivery oracle over a session sample -----------------------
        step = max(1, sessions // ORACLE_SAMPLE)
        sample = list(range(0, sessions, step))[:ORACLE_SAMPLE]
        session_present = live_ok = retained_ok = 0
        for i in sample:
            # resume the persisted session: CONNACK must flag it present
            r, w = await asyncio.open_connection(host, port)
            w.write(_connect(f"rec-{i}", clean=False))
            await w.drain()
            ack = await asyncio.wait_for(r.readexactly(4), 10.0)
            if ack[2] & 0x01:
                session_present += 1
            # the restored subscription must route a live publish
            pr, pw = await asyncio.open_connection(host, port)
            pw.write(_connect(f"rec-orc-pub-{i}", clean=True))
            await pw.drain()
            await asyncio.wait_for(pr.readexactly(4), 10.0)
            pw.write(_publish(f"rec/c{i}/live", b"after-crash"))
            await pw.drain()
            try:
                topic, payload = await _pub_frame(r, 10.0)
                if topic == f"rec/c{i}/live" and payload == b"after-crash":
                    live_ok += 1
            except asyncio.TimeoutError:
                pass
            # a fresh subscriber must get the pre-crash retained payload
            # (served through the device-resident retained matcher)
            sr, sw = await asyncio.open_connection(host, port)
            sw.write(_connect(f"rec-orc-sub-{i}", clean=True))
            await sw.drain()
            await asyncio.wait_for(sr.readexactly(4), 10.0)
            sw.write(_subscribe(1, f"rec/c{i}/#"))
            await sw.drain()
            # always wait out the SUBACK so the retained scan has run
            # before the socket closes (and before we read the engine
            # counters); only the first `retained` sessions seeded a
            # retained message, so a PUBLISH is due only for those
            got_suback = False
            got_pub = None
            try:
                while not got_suback or (i < retained and got_pub is None):
                    ptype, body = await _read_frame(sr, 10.0)
                    if ptype == 9:
                        got_suback = True
                    elif ptype == 3:
                        tlen = int.from_bytes(body[:2], "big")
                        got_pub = (
                            body[2 : 2 + tlen].decode(),
                            body[2 + tlen :],
                        )
            except asyncio.TimeoutError:
                pass
            if got_pub == (f"rec/c{i}/state", f"v{i}".encode()):
                retained_ok += 1
            for wr in (w, pw, sw):
                wr.close()
        if session_present != len(sample):
            failures.append(
                f"session-present on reconnect: {session_present}/{len(sample)}"
            )
        if live_ok != len(sample):
            failures.append(
                f"live deliveries through restored trie: "
                f"{live_ok}/{len(sample)}"
            )
        want_retained = sum(1 for i in sample if i < retained)
        if retained_ok != want_retained:
            failures.append(
                f"retained redeliveries: {retained_ok}/{want_retained}"
            )

        # -- device retained matcher: oracle-clean, no error fallbacks ---
        eng = srv._retained_engine
        eng_stats = eng.stats() if eng is not None else {}
        if eng is None:
            failures.append("retained matcher engine not constructed")
        else:
            if eng.oracle_mismatches:
                failures.append(
                    f"{eng.oracle_mismatches} retained oracle mismatches"
                )
            if eng.fallbacks.get("error", 0):
                failures.append(
                    f"{eng.fallbacks['error']} retained kernel error fallbacks"
                )
            if eng.device_matches < len(sample):
                failures.append(
                    f"device served only {eng.device_matches} retained "
                    f"scans for {len(sample)} subscribes"
                )

        block = {
            "sessions": sessions,
            "retained": retained,
            "budget_seconds": budget_s,
            "recovery_seconds": round(dur["recovery_seconds"], 4),
            "serve_seconds": round(serve_s, 4),
            "replayed_keys": dur["replayed_keys"],
            "restored_subscriptions": dur["restored_subscriptions"],
            "restored_retained": dur["restored_retained"],
            "restore_batches": dur["restore_batches"],
            "healthz_mid_restore": mid,
            "healthz_ready_ok": healthz_ok,
            "oracle_sample": len(sample),
            "session_present": session_present,
            "live_deliveries": live_ok,
            "retained_redeliveries": retained_ok,
            "retained_redeliveries_expected": want_retained,
            "retained_engine": eng_stats,
        }
        with open(out_path, "w") as f:
            json.dump(block, f, indent=2)
        print(f"# recovery block -> {out_path}: {json.dumps(block)}",
              file=sys.stderr)

        if failures:
            for msg in failures:
                print(f"FAIL: {msg}", file=sys.stderr)
            return 1
        print(
            f"OK: killed -9 with {sessions} sessions + {retained} retained, "
            f"recovered in {dur['recovery_seconds']:.3f}s "
            f"(budget {budget_s}s), healthz 503->200, "
            f"{len(sample)}/{len(sample)} sessions resumed with exact "
            "delivery, retained oracle clean",
            file=sys.stderr,
        )
        return 0
    finally:
        await srv.close()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sessions", type=int, default=400)
    ap.add_argument("--retained", type=int, default=200)
    ap.add_argument("--budget", type=float, default=10.0)
    ap.add_argument("--out", default="recovery-smoke.json")
    ap.add_argument("--store", default="")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.child:
        return asyncio.run(child(args.store, args.sessions, args.retained))

    store = args.store or tempfile.mkdtemp(prefix="recovery-smoke-")
    _seed_and_kill(store, args.sessions, args.retained)
    return asyncio.run(
        restart(store, args.sessions, args.retained, args.budget, args.out)
    )


if __name__ == "__main__":
    sys.exit(main())
