#!/usr/bin/env python
"""CI cluster-federation scrape gate (ISSUE 14): boot a 3-worker tree
mesh in one process, drive a cross-worker burst (QoS0 passthrough AND
QoS1 packet legs, so both forward encodings carry the origin's elapsed
stamp), wait for the metric summaries to federate up the tree, then
scrape the ROOT worker's ``GET /metrics/cluster`` and ``GET /healthz``
and validate:

- the federated exposition parses (telemetry.check_exposition), carries
  ``worker``-labeled samples from every worker AND cluster-folded rows;
- the remote-path delivery-latency SLI recorded nonzero samples on the
  subscriber's worker and is visible from the root;
- /healthz answers 200 with ok=true on a healthy mesh.

The snapshot is written to disk and uploaded as a CI artifact — every
run carries a real federated-scrape baseline.

Usage: python exp/scrape_cluster.py [--out cluster-metrics-snapshot.txt]
"""

import argparse
import asyncio
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_WORKERS = 3


async def main(out_path: str) -> int:
    from mqtt_tpu.cluster import Cluster
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes
    from mqtt_tpu.telemetry import check_exposition

    sock_dir = tempfile.mkdtemp(prefix="mqtt_tpu_scrape_cluster_")
    servers = []
    clusters = []
    for i in range(N_WORKERS):
        opts = Options(
            telemetry_sample=1,  # sample everything: a short burst must land
            cluster_topology="tree",
            cluster_tree_degree=2,
            slo_objectives=["p99 delivery < 5s over 30s/2m"],
        )
        srv = Server(opts)
        srv.add_hook(AllowHook())
        srv.add_listener(
            TCP(LConfig(type="tcp", id=f"t{i}", address="127.0.0.1:0"))
        )
        if i == 0:
            # the ROOT's scrape surface: /metrics/cluster + /healthz
            srv.add_listener(
                HTTPStats(
                    LConfig(type="sysinfo", id="s0", address="127.0.0.1:0"),
                    srv.info,
                    telemetry=srv.telemetry,
                    health=srv.health_report,
                )
            )
        servers.append(srv)
    try:
        for srv in servers:
            await srv.serve()
        for i, srv in enumerate(servers):
            c = Cluster(srv, i, N_WORKERS, sock_dir)
            c.PING_INTERVAL_S = 0.2  # fast gossip/federation cadence
            clusters.append(c)
        for c in clusters:
            await c.start()
        loop = asyncio.get_event_loop()

        async def wait_for(cond, timeout, what):
            deadline = loop.time() + timeout
            while loop.time() < deadline:
                if cond():
                    return True
                await asyncio.sleep(0.05)
            print(f"FAIL: timed out waiting for {what}", file=sys.stderr)
            return False

        if not await wait_for(
            lambda: all(
                all(p in c._writers for p in c.topo.neighbors())
                for c in clusters
            ),
            20,
            "tree links",
        ):
            return 1

        def addr(i):
            host, port = servers[i].listeners.get(f"t{i}").address().rsplit(":", 1)
            return host, int(port)

        # subscriber on worker 2, publisher on worker 0: every delivery
        # crosses the mesh and lands in worker 2's remote-path SLI
        host2, port2 = addr(2)
        sr, sw = await asyncio.open_connection(host2, port2)
        sw.write(_connect_bytes("fed-sub", version=4))
        await sw.drain()
        await sr.readexactly(4)
        sw.write(_subscribe_bytes(1, "fed/#"))
        await sw.drain()
        await sr.readexactly(5)
        # interest summaries must settle on the root's edges (tree mode
        # replaces per-filter presence with counted blooms; forwards
        # pass conservatively before this, so the wait is about making
        # the scrape deterministic, not about deliverability)
        if not await wait_for(
            lambda: all(
                p in clusters[0]._edge_summaries
                for p in clusters[0].topo.neighbors()
            ),
            20,
            "edge interest summaries",
        ):
            return 1

        host0, port0 = addr(0)
        pr, pw = await asyncio.open_connection(host0, port0)
        pw.write(_connect_bytes("fed-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        # QoS0 leg: the v4 passthrough frames ride _T_RFRAME with the
        # route json carrying the origin's elapsed stamp
        for i in range(60):
            topic = f"fed/{i % 5}".encode()
            body = len(topic).to_bytes(2, "big") + topic + b"p%d" % i
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()
        # QoS1 leg: decoded packets ride _T_PACKET with "el" in the head
        for i in range(20):
            topic = b"fed/q1"
            payload = b"q%d" % i
            body = (
                len(topic).to_bytes(2, "big")
                + topic
                + (i + 1).to_bytes(2, "big")
                + payload
            )
            pw.write(bytes([0x32, len(body)]) + body)
        await pw.drain()

        # the subscriber must actually receive the burst (frames flushed)
        got = 0
        deadline = loop.time() + 20
        while got < 70 and loop.time() < deadline:
            try:
                data = await asyncio.wait_for(sr.read(65536), 3.0)
            except asyncio.TimeoutError:
                break
            if not data:
                break
            got += data.count(b"fed/")
        print(f"# delivered ~{got}/80 cross-worker publishes", file=sys.stderr)
        if got == 0:
            print("FAIL: no cross-worker deliveries", file=sys.stderr)
            return 1

        # remote-path SLI samples recorded on the subscriber's worker
        tele2 = servers[2].telemetry
        if not await wait_for(
            lambda: any(
                p == "remote" and h.count
                for (_t, _q, p), h in tele2._delivery_cache.items()
            ),
            20,
            "remote-path delivery samples",
        ):
            return 1

        # federation: the root must hold BOTH children's summaries, and
        # worker 2's copy must already carry the delivery samples
        # recorded above (the next federation tick after the burst)
        cm0 = servers[0].telemetry.cluster_metrics

        def _w2_delivery_federated():
            ent = (cm0.entries() if cm0 is not None else {}).get("2")
            if ent is None:
                return False
            fam = ent["f"].get("mqtt_tpu_delivery_latency_seconds")
            return bool(fam and fam.get("c"))

        if not await wait_for(
            lambda: cm0 is not None
            and cm0.worker_count >= N_WORKERS - 1
            and _w2_delivery_federated(),
            30,
            "federated summaries (incl. worker 2's delivery samples)",
        ):
            return 1

        from scrapelib import http_get

        http_addr = servers[0].listeners.get("s0").address()
        head, body = await http_get(http_addr, "/metrics/cluster")
        if b"200" not in head.split(b"\r\n", 1)[0]:
            print(f"FAIL: /metrics/cluster -> {head!r}", file=sys.stderr)
            return 1
        text = body.decode()
        samples = check_exposition(text)

        # per-worker labels from every worker + the cluster fold
        for wid in range(N_WORKERS):
            if f'worker="{wid}"' not in text:
                print(f"FAIL: no samples labeled worker={wid}", file=sys.stderr)
                return 1
        remote_counts = [
            int(m.group(1))
            for m in re.finditer(
                r'^mqtt_tpu_delivery_latency_seconds_count\{[^}]*'
                r'path="remote"[^}]*\} (\d+)$',
                text,
                re.M,
            )
        ]
        if not remote_counts or max(remote_counts) == 0:
            print(
                "FAIL: no remote-path delivery-latency samples federated",
                file=sys.stderr,
            )
            return 1
        # the cluster FOLD: a delivery-latency count row with NO worker
        # label must exist beside the per-worker rows
        folded = re.search(
            r"^mqtt_tpu_delivery_latency_seconds_count\{(?![^}]*worker=)"
            r"[^}]*\} (\d+)$",
            text,
            re.M,
        )
        if folded is None:
            print("FAIL: no cluster-folded delivery rows", file=sys.stderr)
            return 1

        head_h, body_h = await http_get(http_addr, "/healthz")
        if b"200" not in head_h.split(b"\r\n", 1)[0]:
            print(f"FAIL: /healthz -> {head_h!r}", file=sys.stderr)
            return 1
        health = json.loads(body_h)
        if not health.get("ok"):
            print(f"FAIL: /healthz not ok: {health}", file=sys.stderr)
            return 1

        head_s, body_s = await http_get(http_addr, "/cluster/slo")
        if b"200" not in head_s.split(b"\r\n", 1)[0]:
            print(f"FAIL: /cluster/slo -> {head_s!r}", file=sys.stderr)
            return 1
        slo = json.loads(body_s)
        if not slo.get("local"):
            print(f"FAIL: /cluster/slo has no local objectives", file=sys.stderr)
            return 1

        with open(out_path, "w") as f:
            f.write(text)
        print(
            f"OK: {samples} federated samples; remote delivery counts "
            f"{remote_counts}; {cm0.worker_count + 1} workers visible; "
            f"snapshot -> {out_path}",
            file=sys.stderr,
        )
        return 0
    finally:
        for c in clusters:
            try:
                await c.stop()
            except Exception:
                pass
        for srv in servers:
            try:
                await srv.close()
            except Exception:
                pass


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="cluster-metrics-snapshot.txt")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
