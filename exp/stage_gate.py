#!/usr/bin/env python
"""Per-stage regression gate over BENCH json artifacts (ROADMAP
"per-stage regression gating", wired into CI by ISSUE 5).

bench.py emits a ``telemetry`` block per config — per-stage p50/p99
through the broker's own log-scale histogram buckets — so regressions
can be judged stage-by-stage (decode/admission/staging_wait/h2d/
device_dispatch/d2h/device_batch/fanout/materialize) instead of only on
the end-to-end rate. This gate diffs the two most recent
``BENCH_*.json`` files (or an explicit ``--current``/``--previous``
pair) and fails when any stage's p99 regressed by more than
``--threshold`` (default 25%).

Robustness rules (a gate that cries wolf gets deleted):
- stages are compared only when BOTH runs observed them, with at least
  ``--min-count`` samples each (tiny samples land in log-bucket noise);
- telemetry blocks are matched by their json path, so config 5's
  device_batch never diffs against config 8's;
- stage names present only in the CURRENT run — e.g. the trace plane's
  h2d/device_dispatch/d2h sub-stages against a round recorded before
  the device_batch split, the staging pipeline's per-leg waits
  (``leg_wait_h2d`` / ``leg_wait_d2h``) and the compaction d2h leg
  (``compact_d2h``) against a round recorded before the 3-deep
  overlapped pipeline, or the delivery-latency SLI rows
  (``delivery_local`` / ``delivery_remote``, the ISSUE 14 per-path
  folds of ``mqtt_tpu_delivery_latency_seconds``) against a round
  recorded before the SLO observatory — pass through with a notice,
  never a failure: a new stage has no baseline to regress against
  (``device_batch`` stays populated as their sum for continuity);
- stage names present only in the PREVIOUS run are reported as a
  retirement notice (renames are visible, never silently un-diffed)
  and never fail the gate;
- a run with no telemetry blocks (device-less driver hosts) passes with
  a notice — absence of evidence is not a regression.

Usage:
    python exp/stage_gate.py                      # newest two BENCH_*.json
    python exp/stage_gate.py --current BENCH_r06.json --previous BENCH_r05.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys


def find_telemetry_blocks(doc: object, path: str = "") -> dict[str, dict]:
    """Every ``telemetry`` block in a BENCH json, keyed by its json
    path — e.g. ``/parsed/configs/8_publish_storm/telemetry``."""
    out: dict[str, dict] = {}
    if isinstance(doc, dict):
        for k, v in doc.items():
            p = f"{path}/{k}"
            if k == "telemetry" and isinstance(v, dict):
                out[p] = v
            else:
                out.update(find_telemetry_blocks(v, p))
    elif isinstance(doc, list):
        for i, v in enumerate(doc):
            out.update(find_telemetry_blocks(v, f"{path}[{i}]"))
    return out


def stage_rows(block: dict) -> dict[str, dict]:
    """``stage name -> {count, p99_ms, ...}`` rows from one telemetry
    block (the ``stages`` map plus the batch_service aggregate)."""
    rows: dict[str, dict] = {}
    stages = block.get("stages")
    if isinstance(stages, dict):
        for name, row in stages.items():
            if isinstance(row, dict):
                rows[name] = row
    svc = block.get("batch_service")
    if isinstance(svc, dict) and "p99_ms" in svc:
        rows["batch_service"] = svc
    return rows


def compare(
    current: dict, previous: dict, threshold: float = 0.25, min_count: int = 20
) -> tuple[list[str], list[str]]:
    """``(regressions, compared)`` between two BENCH documents: a
    regression is a stage whose p99 grew past ``(1 + threshold)`` x the
    previous run's, in a telemetry block present at the same json path
    in both runs with enough samples on each side."""
    cur_blocks = find_telemetry_blocks(current)
    prev_blocks = find_telemetry_blocks(previous)
    regressions: list[str] = []
    compared: list[str] = []
    for path, cur in sorted(cur_blocks.items()):
        prev = prev_blocks.get(path)
        if prev is None:
            continue
        prev_rows = stage_rows(prev)
        for name, row in sorted(stage_rows(cur).items()):
            prev_row = prev_rows.get(name)
            if prev_row is None:
                continue
            try:
                c_count = int(row.get("count", 0))
                p_count = int(prev_row.get("count", 0))
                c_p99 = float(row["p99_ms"])
                p_p99 = float(prev_row["p99_ms"])
            except (KeyError, TypeError, ValueError):
                continue
            if c_count < min_count or p_count < min_count:
                continue
            if p_p99 <= 0:
                continue  # a zero baseline means the stage never ran
            compared.append(f"{path}:{name}")
            if c_p99 > p_p99 * (1.0 + threshold):
                regressions.append(
                    f"{path} stage {name!r}: p99 {p_p99:.3f}ms -> "
                    f"{c_p99:.3f}ms (+{100 * (c_p99 / p_p99 - 1):.0f}%, "
                    f"threshold +{100 * threshold:.0f}%)"
                )
    return regressions, compared


def new_stage_names(current: dict, previous: dict) -> list[str]:
    """Stage names the current run observed that the previous run (at
    the same json path) never did — the trace plane's sub-stage split
    lands here on its first round. Reported as a notice by main(); by
    construction compare() never diffs them, so a new stage can never
    fail the gate vacuously."""
    cur_blocks = find_telemetry_blocks(current)
    prev_blocks = find_telemetry_blocks(previous)
    out: set[str] = set()
    for path, cur in cur_blocks.items():
        prev = prev_blocks.get(path)
        if prev is None:
            continue
        prev_rows = stage_rows(prev)
        for name in stage_rows(cur):
            if name not in prev_rows:
                out.add(name)
    return sorted(out)


def removed_stage_names(current: dict, previous: dict) -> list[str]:
    """Stage names the previous run observed (at the same json path)
    that the current run never did — a renamed or retired stage. By
    construction compare() never diffs them (it iterates the CURRENT
    run's stages), so a retirement can't fail the gate; main() surfaces
    the list so a rename is visible instead of silently un-diffed —
    e.g. when the pipeline sub-stage split retires a coarse stage."""
    cur_blocks = find_telemetry_blocks(current)
    prev_blocks = find_telemetry_blocks(previous)
    out: set[str] = set()
    for path, prev in prev_blocks.items():
        cur = cur_blocks.get(path)
        if cur is None:
            continue
        cur_rows = stage_rows(cur)
        for name in stage_rows(prev):
            if name not in cur_rows:
                out.add(name)
    return sorted(out)


def _bench_rank(path: str) -> tuple[int, str]:
    """Order BENCH files by their round number (BENCH_r05 > BENCH_r04)."""
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return (int(m.group(1)) if m else -1, os.path.basename(path))


_CANONICAL_RE = re.compile(r"^BENCH_r\d+\.json$")


def newest_pair(repo: str) -> tuple[str, str] | None:
    """The two newest CANONICAL round artifacts (``BENCH_rNN.json``).
    Suffixed variants (``_local``, ``_cpu_fullscale``) are a different
    machine/backend — diffing one against its plain sibling would gate
    on cpu-vs-device deltas, not regressions — so they participate only
    when fewer than two canonical rounds exist."""
    files = glob.glob(os.path.join(repo, "BENCH_*.json"))
    canonical = [f for f in files if _CANONICAL_RE.match(os.path.basename(f))]
    pool = canonical if len(canonical) >= 2 else files
    pool = sorted(pool, key=_bench_rank)
    if len(pool) < 2:
        return None
    return pool[-1], pool[-2]


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--current", help="newer BENCH json (default: auto-pick)")
    ap.add_argument("--previous", help="older BENCH json (default: auto-pick)")
    ap.add_argument("--repo", default=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--threshold", type=float, default=0.25)
    ap.add_argument("--min-count", type=int, default=20)
    args = ap.parse_args()

    cur_path, prev_path = args.current, args.previous
    if not (cur_path and prev_path):
        explicit = cur_path or prev_path
        if explicit:
            # exactly one side given: pair it with the newest OTHER
            # artifact — naively taking the auto-pick's slot could hand
            # back the explicit file itself (a vacuous self-diff)
            others = [
                f
                for f in glob.glob(os.path.join(args.repo, "BENCH_*.json"))
                if os.path.abspath(f) != os.path.abspath(explicit)
            ]
            canonical = [
                f for f in others if _CANONICAL_RE.match(os.path.basename(f))
            ]
            pool = sorted(canonical or others, key=_bench_rank)
            if not pool:
                print("stage-gate: no artifact to diff against; nothing to do")
                return 0
            cur_path = cur_path or pool[-1]
            prev_path = prev_path or pool[-1]
        else:
            pair = newest_pair(args.repo)
            if pair is None:
                print(
                    "stage-gate: fewer than two BENCH_*.json files; "
                    "nothing to diff"
                )
                return 0
            cur_path, prev_path = pair

    with open(cur_path, encoding="utf-8") as f:
        current = json.load(f)
    with open(prev_path, encoding="utf-8") as f:
        previous = json.load(f)

    regressions, compared = compare(
        current, previous, threshold=args.threshold, min_count=args.min_count
    )
    print(
        f"stage-gate: {cur_path} vs {prev_path}: "
        f"{len(compared)} stage(s) compared"
    )
    fresh = new_stage_names(current, previous)
    if fresh:
        print(
            "stage-gate: new stage(s) without a baseline (not diffed): "
            + ", ".join(fresh)
        )
    retired = removed_stage_names(current, previous)
    if retired:
        print(
            "stage-gate: stage(s) retired since the previous round "
            "(not diffed): " + ", ".join(retired)
        )
    if not compared:
        print(
            "stage-gate: no comparable telemetry blocks (device-less bench "
            "runs emit none); passing"
        )
        return 0
    for line in regressions:
        print(f"stage-gate REGRESSION: {line}")
    if regressions:
        return 1
    print("stage-gate: no stage p99 regressed past the threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
