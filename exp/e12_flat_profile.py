import sys, time, os
sys.path.insert(0, "/root/repo"); sys.path.insert(0, "/root/repo/exp")
import numpy as np, random
import jax, jax.numpy as jnp
from e10_flat_proto import build_flat, flat_match
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]; v1 = [f"device{i}" for i in range(100)]; v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
print("subscribing", flush=True)
for i in range(1_000_000):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))
print("building", flush=True)
built = build_flat(index, max_levels=4, window=16)
dev = tuple(jnp.asarray(a) for a in (built["table"], built["all_ids"], built["pat_kind"], built["pat_depth"], built["pat_mask"]))
jax.block_until_ready(dev)
salt = built["salt"]
B = 16384
res = tuple(jnp.asarray(a) for a in tokenize_topics(
    [f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}" for _ in range(B)], 4, salt)[:4])
jax.block_until_ready(res)
print("compiling", flush=True)
np.asarray(flat_match(*dev, *res, window=16, max_levels=4, out_slots=64)[0].ravel()[0])
print("compiled", flush=True)
os.makedirs("/root/repo/exp/trace2", exist_ok=True)
with jax.profiler.trace("/root/repo/exp/trace2"):
    out = flat_match(*dev, *res, window=16, max_levels=4, out_slots=64)
    np.asarray(out[0].ravel()[0])
print("done", flush=True)
