#!/usr/bin/env python
"""CI metrics-scrape gate: boot a broker with the telemetry plane on,
drive a short publish burst over real TCP, scrape ``GET /metrics`` from
the stats listener, validate it with the pure-Python exposition checker
(mqtt_tpu.telemetry.check_exposition), and write the snapshot to disk —
the workflow uploads it as an artifact so every CI run carries a
stage-level metrics baseline.

Usage: python exp/scrape_metrics.py [--out metrics-snapshot.txt]
Exits non-zero when the scrape fails to parse or the expected stage
histograms are missing.
"""

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


async def main(out_path: str) -> int:
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig, HTTPStats
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes
    from mqtt_tpu.telemetry import check_exposition

    try:  # stage histograms need the device matcher; CPU jax suffices
        import jax  # noqa: F401

        device = True
    except ImportError:
        device = False

    opts = Options(
        device_matcher=device,
        matcher_opts={"max_levels": 4, "background": False} if device else None,
        telemetry_sample=1,  # sample everything: a 2s burst must register
        # two-tenant burst (ISSUE 12): the scrape must carry the
        # tenant-labeled families and the recrypt series
        tenancy=True,
        tenants={
            "scrape-a": {
                "encrypted": ["enc/"],
                "keys": {
                    "scrape-ta": "000102030405060708090a0b0c0d0e0f",
                    "scrape-ta2": "101112131415161718191a1b1c1d1e1f",
                },
            },
            "scrape-b": {},
        },
        tenant_users={
            "scrape-ta": "scrape-a",
            "scrape-ta2": "scrape-a",
            "scrape-tb": "scrape-b",
        },
    )
    srv = Server(opts)
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    srv.add_listener(
        HTTPStats(
            LConfig(type="sysinfo", id="s", address="127.0.0.1:0"),
            srv.info,
            telemetry=srv.telemetry,
        )
    )
    await srv.serve()
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)

        # one subscriber + a small publish burst (the mini bench run)
        sr, sw = await asyncio.open_connection(host, int(port))
        sw.write(_connect_bytes("scrape-sub", version=4))
        await sw.drain()
        await sr.readexactly(4)
        sw.write(_subscribe_bytes(1, "bench/#"))
        await sw.drain()
        await sr.readexactly(5)
        # a PREDICATED subscriber (ISSUE 8): the suffix is stripped at
        # SUBSCRIBE, the rule table evaluates inside the staged batch,
        # and the mqtt_tpu_predicate_* series must validate below
        pr2, pw2 = await asyncio.open_connection(host, int(port))
        pw2.write(_connect_bytes("scrape-pred", version=4))
        await pw2.drain()
        await pr2.readexactly(4)
        pw2.write(_subscribe_bytes(1, "bench/+$GT{v:4.5}"))
        await pw2.drain()
        await pr2.readexactly(5)
        if srv.matcher is not None:
            srv.matcher.flush()

        pr, pw = await asyncio.open_connection(host, int(port))
        pw.write(_connect_bytes("scrape-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        for i in range(200):
            topic = f"bench/{i % 10}".encode()
            payload = b'{"v": %d.0}' % (i % 10)
            body = len(topic).to_bytes(2, "big") + topic + payload
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()
        deadline = asyncio.get_event_loop().time() + 20
        got = 0
        while got < 200 and asyncio.get_event_loop().time() < deadline:
            try:
                # generous first-read budget: the burst's first staged
                # batch pays the match + predicate kernel jit compiles
                data = await asyncio.wait_for(sr.read(65536), 5.0)
            except asyncio.TimeoutError:
                break
            if not data:
                break
            got += data.count(b"bench/")
        print(f"# delivered ~{got}/200 publishes", file=sys.stderr)

        # the first staged batches pay the jit compile: wait for the
        # predicate plane to have decided the burst before asserting on it
        eng = srv._predicates
        loop = asyncio.get_event_loop()
        deadline = loop.time() + 30
        while (
            eng is not None
            and (eng.filtered == 0 or eng.deliveries == 0)
            and loop.time() < deadline
        ):
            await asyncio.sleep(0.2)

        # two-tenant burst (ISSUE 12): tenant A exchanges an ENCRYPTED
        # publish (re-keyed per subscriber), tenant B a plaintext one;
        # the tenant-scoped series must validate below and tenant B's
        # subscriber must see nothing of tenant A's traffic
        ta_r, ta_w = await asyncio.open_connection(host, int(port))
        ta_w.write(_connect_bytes("scrape-ta", version=4))
        await ta_w.drain()
        await ta_r.readexactly(4)
        ta2_r, ta2_w = await asyncio.open_connection(host, int(port))
        ta2_w.write(_connect_bytes("scrape-ta2", version=4))
        await ta2_w.drain()
        await ta2_r.readexactly(4)
        ta2_w.write(_subscribe_bytes(1, "enc/#"))
        await ta2_w.drain()
        await ta2_r.readexactly(5)
        tb_r, tb_w = await asyncio.open_connection(host, int(port))
        tb_w.write(_connect_bytes("scrape-tb", version=4))
        await tb_w.drain()
        await tb_r.readexactly(4)
        tb_w.write(_subscribe_bytes(1, "#"))
        await tb_w.drain()
        await tb_r.readexactly(5)
        if srv.matcher is not None:
            srv.matcher.flush()
        eng_r = srv._recrypt
        sealed = eng_r.seal_with_key(
            bytes.fromhex("000102030405060708090a0b0c0d0e0f"), b"tenant secret"
        )
        for topic_s, payload, writer in (
            ("enc/x", sealed, ta_w),
            ("plain/x", b"tenant-b", tb_w),
        ):
            tb_topic = topic_s.encode()
            body = len(tb_topic).to_bytes(2, "big") + tb_topic + payload
            writer.write(bytes([0x30, len(body)]) + body)
            await writer.drain()
        # tenant A's keyed subscriber must receive the re-keyed publish
        data = await asyncio.wait_for(ta2_r.read(4096), 10.0)
        if b"enc/x" not in data:
            print("FAIL: encrypted-namespace delivery missing", file=sys.stderr)
            return 1
        # tenant B's catch-all sees ITS publish and nothing of tenant A's
        data_b = await asyncio.wait_for(tb_r.read(4096), 10.0)
        if b"plain/x" not in data_b or b"enc/x" in data_b:
            print(f"FAIL: tenant isolation broken: {data_b!r}", file=sys.stderr)
            return 1

        srv.publish_sys_topics()
        from scrapelib import http_get

        head, body = await http_get(srv.listeners.get("s").address(), "/metrics")
        assert b"200" in head.split(b"\r\n", 1)[0], head
        text = body.decode()

        samples = check_exposition(text)
        required = [
            "mqtt_tpu_publish_stage_seconds",
            "mqtt_tpu_messages_received_total",
            "mqtt_tpu_uptime_seconds",
            "mqtt_tpu_predicate_rules",
            "mqtt_tpu_predicate_filtered_total",
            "mqtt_tpu_predicate_oracle_mismatches_total",
            # tenant-scoped series (ISSUE 12): labeled per-tenant
            # families and the recrypt engine's counters
            'mqtt_tpu_tenant_messages_in_total{tenant="scrape-a"}',
            'mqtt_tpu_tenant_connected{tenant="scrape-b"}',
            "mqtt_tpu_recrypt_fanouts_total",
            "mqtt_tpu_recrypt_oracle_mismatches_total",
        ]
        missing = [m for m in required if m not in text]
        if missing:
            print(f"FAIL: metrics missing {missing}", file=sys.stderr)
            return 1
        if eng is None or eng.rule_count != 1:
            print("FAIL: predicated subscribe did not register a rule", file=sys.stderr)
            return 1
        if eng.filtered == 0 or eng.oracle_mismatches:
            print(
                f"FAIL: predicate plane inert or mismatched "
                f"(filtered={eng.filtered} mismatches={eng.oracle_mismatches})",
                file=sys.stderr,
            )
            return 1
        if eng_r.fanouts == 0 or eng_r.oracle_mismatches:
            print(
                f"FAIL: recrypt plane inert or mismatched "
                f"(fanouts={eng_r.fanouts} "
                f"mismatches={eng_r.oracle_mismatches})",
                file=sys.stderr,
            )
            return 1
        with open(out_path, "w") as f:
            f.write(text)
        print(
            f"OK: {samples} samples parsed; snapshot -> {out_path}",
            file=sys.stderr,
        )
        return 0
    finally:
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="metrics-snapshot.txt")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
