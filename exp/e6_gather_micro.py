"""E6: raw gather-primitive microbenchmarks on this TPU."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp
from functools import partial

B = 16384 * 8  # 131072 indices, matches [16384,8]

def bench(fn, *args, iters=5, warm=2):
    f = jax.jit(fn)
    red = jax.jit(lambda o: o.sum())
    for _ in range(warm):
        r = f(*args)
    int(np.asarray(red(f(*args))))
    t0 = time.perf_counter()
    outs = [f(*args) for _ in range(iters)]
    int(np.asarray(red(outs[-1])))
    dt = (time.perf_counter() - t0) / iters
    return dt

key = jax.random.PRNGKey(0)
for N in (1024, 16384, 262144, 1<<20, 1<<22):
    table = jnp.arange(N, dtype=jnp.int32)
    idx = jax.random.randint(key, (B,), 0, N, dtype=jnp.int32)
    idx2d = idx.reshape(16384, 8)
    jax.block_until_ready((table, idx, idx2d))
    t = bench(lambda T, I: T[I], table, idx)
    print(f"N={N:>8}: 1D take [{B}]          {t*1e3:7.2f} ms  {B/t/1e6:8.1f} M elem/s", flush=True)
    t = bench(lambda T, I: T[I], table, idx2d)
    print(f"N={N:>8}: 2D take [16384,8]      {t*1e3:7.2f} ms  {B/t/1e6:8.1f} M elem/s", flush=True)
    sidx = jnp.sort(idx)
    t = bench(lambda T, I: T[I], table, sidx)
    print(f"N={N:>8}: sorted 1D take         {t*1e3:7.2f} ms  {B/t/1e6:8.1f} M elem/s", flush=True)
    if N <= 16384:
        # one-hot matmul gather (f32 exact to 2^24)
        tf = table.astype(jnp.float32)
        def onehot_gather(T, I):
            oh = (I[:, None] == jnp.arange(N)[None, :]).astype(jnp.float32)
            return oh @ T
        t = bench(onehot_gather, tf, idx)
        print(f"N={N:>8}: one-hot matmul         {t*1e3:7.2f} ms  {B/t/1e6:8.1f} M elem/s", flush=True)
