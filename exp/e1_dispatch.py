"""E1: dispatch-floor + batch scaling on the real device.

Q1: what does an async-dispatched trivial kernel cost per call (tunnel floor)?
Q2: does match kernel time scale with B (compute-bound) or stay flat (dispatch-bound)?
"""
import sys, time, os
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp

dev = jax.devices()[0]
print("device:", dev, dev.platform)

# Q1: trivial kernel async-dispatch floor
x = jnp.zeros((8,), jnp.int32)
f = jax.jit(lambda v, i: v + i)
f(x, 0).block_until_ready()
for n in (10, 50):
    t0 = time.perf_counter()
    outs = [f(x, i) for i in range(n)]
    outs[-1].block_until_ready()
    dt = time.perf_counter() - t0
    print(f"trivial async x{n}: {dt*1e3:.1f}ms total, {dt/n*1e3:.2f}ms/call")

# Q2: match kernel scaling with B
from mqtt_tpu.ops import TpuMatcher
from mqtt_tpu.ops.hashing import tokenize_topics
from mqtt_tpu.packets import Subscription
from mqtt_tpu.topics import TopicsIndex

rng = random.Random(7)
v0 = [f"region{i}" for i in range(100)]
v1 = [f"device{i}" for i in range(100)]
v2 = [f"metric{i}" for i in range(100)]
index = TopicsIndex()
N = int(os.environ.get("NSUBS", "200000"))
for i in range(N):
    parts = [rng.choice(v0), rng.choice(v1), rng.choice(v2)]
    if rng.random() < 0.10:
        parts[rng.randrange(3)] = "+"
    index.subscribe(f"cl{i}", Subscription(filter="/".join(parts), qos=i % 3))

matcher = TpuMatcher(index, max_levels=4, frontier=8, out_slots=64, transfer_slots=16)
t0 = time.perf_counter(); matcher.rebuild(); print(f"rebuild {time.perf_counter()-t0:.1f}s nodes={matcher.csr.num_nodes}")
salt = matcher.csr.salt

def topic():
    return f"{rng.choice(v0)}/{rng.choice(v1)}/{rng.choice(v2)}"

for B in (1024, 4096, 16384, 65536):
    topics = [topic() for _ in range(B)]
    res = tuple(jnp.asarray(a) for a in tokenize_topics(topics, 4, salt)[:4])
    jax.block_until_ready(res)
    t0 = time.perf_counter()
    matcher.match_tokens(*res)[0].block_until_ready()
    compile_dt = time.perf_counter() - t0
    # async pipelined
    iters = 8
    t0 = time.perf_counter()
    outs = [matcher.match_tokens(*res)[0] for _ in range(iters)]
    outs[-1].block_until_ready()
    dt = time.perf_counter() - t0
    print(f"B={B}: first={compile_dt*1e3:.0f}ms, {dt/iters*1e3:.1f}ms/batch, {B*iters/dt:,.0f} topics/s")
