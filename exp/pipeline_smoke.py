#!/usr/bin/env python
"""CI pipeline-smoke gate (ISSUE 11): boot a staged broker with
device-resident hit compaction AND the 3-deep overlapped staging
pipeline on, push a 1k-publish burst over real TCP against wildcard
subscribers, and assert

- ZERO oracle mismatches: every subscriber's received topic multiset
  equals the host-trie-derived expectation (the compacted device path
  must be delivery-identical to the reference walk), and
- a nonzero ``device_duty_cycle`` with at least one compacted batch —
  the pipeline actually ran through the device, it did not silently
  degrade to the host walk.

The device duty-cycle/overlap block (plus the compaction transfer
ledger and per-leg staging waits) is written to ``--out`` and uploaded
as a CI artifact, so every run carries the pipeline-health numbers
ROADMAP item 1 gates on.

Usage: python exp/pipeline_smoke.py [--out pipeline-smoke.json]
"""

import argparse
import asyncio
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_PUBLISHES = 1000
SUB_FILTERS = {
    "wild-hash": "burst/#",
    "wild-plus": "burst/+/x",
    "exact": "burst/d7/x",
}


async def _drain_topics(reader, counts, stop):
    """Collect delivered PUBLISH topic names (QoS0 frames) into counts."""
    buf = b""
    while not stop.is_set():
        try:
            data = await asyncio.wait_for(reader.read(65536), 0.5)
        except asyncio.TimeoutError:
            continue
        if not data:
            return
        buf += data
        while len(buf) >= 2:
            if buf[0] >> 4 != 3:  # not PUBLISH: skip one byte defensively
                buf = buf[1:]
                continue
            # single-byte remaining length is enough for this burst's
            # tiny frames; bail to the next read otherwise
            rl = buf[1]
            if rl & 0x80 or len(buf) < 2 + rl:
                break
            frame = buf[2 : 2 + rl]
            tlen = int.from_bytes(frame[:2], "big")
            counts[frame[2 : 2 + tlen].decode()] += 1
            buf = buf[2 + rl :]


async def main(out_path: str) -> int:
    from mqtt_tpu.hooks.auth import AllowHook
    from mqtt_tpu.listeners import Config as LConfig
    from mqtt_tpu.listeners.tcp import TCP
    from mqtt_tpu.server import Options, Server
    from mqtt_tpu.stress import _connect_bytes, _subscribe_bytes

    try:
        import jax  # noqa: F401
    except ImportError:
        print("SKIP: no jax backend; the pipeline smoke needs the device path")
        return 0

    srv = Server(
        Options(
            device_matcher=True,
            matcher_opts={"max_levels": 4, "background": False},
            matcher_compact=True,
            matcher_stage_pipeline_depth=3,
            matcher_stage_window_ms=2.0,
            telemetry_sample=1,
        )
    )
    srv.add_hook(AllowHook())
    srv.add_listener(TCP(LConfig(type="tcp", id="t", address="127.0.0.1:0")))
    await srv.serve()
    stop = asyncio.Event()
    drains = []
    try:
        host, port = srv.listeners.get("t").address().rsplit(":", 1)
        counts: dict = {}
        for name, flt in SUB_FILTERS.items():
            r, w = await asyncio.open_connection(host, int(port))
            w.write(_connect_bytes(f"smoke-{name}", version=4))
            await w.drain()
            await r.readexactly(4)
            w.write(_subscribe_bytes(1, flt))
            await w.drain()
            await r.readexactly(5)
            counts[name] = collections.Counter()
            drains.append(
                asyncio.get_event_loop().create_task(
                    _drain_topics(r, counts[name], stop)
                )
            )
        # fold the subscriptions into a fresh compiled snapshot so the
        # burst takes the compacted device path, not the delta overlay
        srv.matcher.flush()

        # the host-trie oracle: expected per-subscriber delivery counts
        topics = [f"burst/d{i % 20}/{'x' if i % 3 else 'y'}" for i in range(N_PUBLISHES)]
        expected = {name: collections.Counter() for name in SUB_FILTERS}
        for t in topics:
            subs = srv.topics.subscribers(t)
            for cid in subs.subscriptions:
                name = cid.removeprefix("smoke-")
                if name in expected:
                    expected[name][t] += 1

        pr, pw = await asyncio.open_connection(host, int(port))
        pw.write(_connect_bytes("smoke-pub", version=4))
        await pw.drain()
        await pr.readexactly(4)
        for t in topics:
            tb = t.encode()
            body = len(tb).to_bytes(2, "big") + tb + b"p"
            pw.write(bytes([0x30, len(body)]) + body)
        await pw.drain()

        want_total = sum(sum(c.values()) for c in expected.values())
        loop = asyncio.get_event_loop()
        deadline = loop.time() + 60
        while loop.time() < deadline:
            got_total = sum(sum(c.values()) for c in counts.values())
            if got_total >= want_total:
                break
            await asyncio.sleep(0.2)
        stop.set()
        await asyncio.gather(*drains, return_exceptions=True)

        mismatches = 0
        for name in SUB_FILTERS:
            if counts[name] != expected[name]:
                mismatches += 1
                missing = expected[name] - counts[name]
                surplus = counts[name] - expected[name]
                print(
                    f"FAIL: {name} diverged from the host-trie oracle "
                    f"(missing={dict(list(missing.items())[:5])} "
                    f"surplus={dict(list(surplus.items())[:5])})",
                    file=sys.stderr,
                )
        stats = srv.matcher.stats
        block = {
            "publishes": N_PUBLISHES,
            "oracle_mismatched_subscribers": mismatches,
            "device_pipeline": (
                srv.profiler.bench_block() if srv.profiler is not None else {}
            ),
            "matcher": stats.as_dict(),
            "staging": {
                "pipeline_depth": (
                    srv._stage.pipeline_depth if srv._stage is not None else 0
                ),
                "leg_wait_counts": {
                    leg: h.count
                    for leg, h in srv.telemetry.leg_wait.items()
                },
            },
        }
        with open(out_path, "w") as f:
            json.dump(block, f, indent=2)
        print(f"# pipeline block -> {out_path}: {json.dumps(block)}",
              file=sys.stderr)
        if mismatches:
            return 1
        duty = block["device_pipeline"].get("duty_cycle", 0.0)
        if duty <= 0.0:
            print("FAIL: device duty cycle is zero — the pipeline never "
                  "touched the device", file=sys.stderr)
            return 1
        if stats.compact_batches < 1:
            print("FAIL: no batch took the compacted path", file=sys.stderr)
            return 1
        print(
            f"OK: {want_total} oracle-checked deliveries, duty_cycle={duty}, "
            f"compact_batches={stats.compact_batches}",
            file=sys.stderr,
        )
        return 0
    finally:
        stop.set()
        await srv.close()


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="pipeline-smoke.json")
    sys.exit(asyncio.run(main(ap.parse_args().out)))
