"""E10: prototype flat-hash matcher — correctness vs host trie + kernel rate.

Design: filters become entries keyed by whole-path hash (levels hashed with
'+' -> sentinel, '#' patterns keyed by (depth, mask, HASH kind)). The build
enumerates the globally-distinct wildcard shapes; matching probes one bucket
row per shape + one id-window row per hit. No trie walk on device.
"""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np, random
import jax, jax.numpy as jnp
from functools import partial

from mqtt_tpu.topics import TopicsIndex, Subscribers, SHARE_PREFIX
from mqtt_tpu.packets import Subscription
from mqtt_tpu.ops.hashing import hash_token, tokenize_topics
from mqtt_tpu.ops.csr import SubEntry, KIND_CLIENT, KIND_SHARED, KIND_INLINE
from mqtt_tpu.ops.matcher import expand_sids

M1 = np.uint32(0x9E3779B1)
M2 = np.uint32(0x85EBCA77)
PLUS1 = np.uint32(0x9E3779B9)   # sentinel level-hash for '+' (h1 lane)
PLUS2 = np.uint32(0xC2B2AE3D)
KIND_EXACT = np.uint32(0x165667B1)
KIND_HASH = np.uint32(0x27D4EB2F)

def rotl(x, r):
    x = np.uint32(x) if np.isscalar(x) else x
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))

def mix_np(h, t):
    return (rotl(h ^ t, 13) * M1).astype(np.uint32)

def path_hash_np(toks1, toks2, kind, depth):
    """toks*: arrays [n] of level hashes ('+' already sentineled)."""
    h1 = np.uint32(depth) * M2 ^ kind
    h2 = np.uint32(depth) * M1 ^ kind
    for d in range(len(toks1)):
        h1 = mix_np(h1, np.uint32(toks1[d]))
        h2 = mix_np(h2, np.uint32(toks2[d]))
    return np.uint32(h1), np.uint32(h2)

# ---------------- build ----------------

def walk_filters(index: TopicsIndex):
    """Yield (levels, node) for every terminal trie node with subs."""
    stack = [(index.root, [])]
    while stack:
        p, path = stack.pop()
        if p.subscriptions.get_all() or p.shared.get_all() or p.inline_subscriptions.get_all():
            yield path, p
        for key, child in p.particles.items():
            stack.append((child, path + [key]))

def build_flat(index: TopicsIndex, max_levels=8, salt=0, window=16):
    t0 = time.perf_counter()
    entries = []   # (h1, h2, kind, depth, mask, ids: list[(sid, exempt)], n_reg, top_wild, last_plus)
    subs = []
    pat_set = set()  # (kind, depth, mask)
    skipped_deep = 0
    for path, node in walk_filters(index):
        is_hash = bool(path) and path[-1] == "#"
        levels = path[:-1] if is_hash else path
        depth = len(levels)
        if depth > max_levels:
            skipped_deep += 1
            continue
        mask = 0
        t1s, t2s = [], []
        for d, tok in enumerate(levels):
            if tok == "+":
                mask |= 1 << d
                t1s.append(PLUS1); t2s.append(PLUS2)
            else:
                a, b = hash_token(tok, salt)
                t1s.append(np.uint32(a)); t2s.append(np.uint32(b))
        kind = KIND_HASH if is_hash else KIND_EXACT
        h1, h2 = path_hash_np(t1s, t2s, kind, depth)
        reg_ids, inl_ids = [], []
        top_wild = bool(path) and path[0] in ("+", "#")
        for client, sub in node.subscriptions.get_all().items():
            sid = len(subs); subs.append(SubEntry(KIND_CLIENT, client, "", sub))
            reg_ids.append((sid, False))
        for gf in node.shared.get_all().values():
            for client, sub in gf.items():
                sid = len(subs); subs.append(SubEntry(KIND_SHARED, client, sub.filter, sub))
                reg_ids.append((sid, True))
        for ident, isub in node.inline_subscriptions.get_all().items():
            sid = len(subs); subs.append(SubEntry(KIND_INLINE, "", "", isub))
            inl_ids.append((sid, True))
        last_plus = is_hash and depth > 0 and (mask >> (depth - 1)) & 1
        entries.append((h1, h2, kind, depth, mask, reg_ids, inl_ids, top_wild, last_plus))
        pat_set.add((int(kind), depth, mask))

    # global key-collision check
    keys = sorted((int(e[0]) << 32 | int(e[1])) for e in entries)
    for i in range(1, len(keys)):
        if keys[i] == keys[i-1]:
            return build_flat(index, max_levels, salt + 1, window)

    # place into buckets: 4 entries/bucket, saturate flag
    n = len(entries)
    S = 1024
    while S * 2 < n:  # target load <= 0.5 entries/slot -> lambda 2/bucket... tune
        S *= 2
    S *= 2
    for attempt in range(3):
        occ = np.zeros(S, dtype=np.int32)
        slot_of = np.empty(n, dtype=np.int64)
        sat = np.zeros(S, dtype=bool)
        for i, e in enumerate(entries):
            s = int(e[0]) & (S - 1)
            slot_of[i] = s
            occ[s] += 1
        sat = occ > 4
        if sat.sum() * 8 < S * 0.004 or attempt == 2:  # accept tiny saturation
            break
        S *= 2
    # all_ids + table
    all_ids = []
    HDR = 4  # k1,k2,meta,start per entry
    ROW = 4 * HDR
    table = np.zeros((S, ROW), dtype=np.uint32)
    fill = np.zeros(S, dtype=np.int32)
    n_spill = 0
    for s in np.nonzero(sat)[0]:
        table[s, 2] = np.uint32(1 << 19)  # SAT marker in entry0 meta
    for i, (h1, h2, kind, depth, mask, reg, inl, top_wild, last_plus) in enumerate(entries):
        s = int(slot_of[i])
        if sat[s]:
            continue  # saturated: device routes these probes to host
        ids = reg + inl
        start = len(all_ids)
        if len(ids) > window:
            n_spill += 1
            spill = 1
            nreg, ninl = 0, 0
        else:
            spill = 0
            for sid, ex in ids:
                all_ids.append(np.uint32(sid | (0x40000000 if ex else 0)))
            nreg, ninl = len(reg), len(inl)
        j = fill[s]; fill[s] += 1
        meta = (nreg & 0x3FF) | ((ninl & 0x3F) << 10) | (int(top_wild) << 16) | (int(last_plus) << 17) | (spill << 18)
        table[s, j*HDR:(j+1)*HDR] = [h1, h2, np.uint32(meta), np.uint32(start)]
    all_ids = np.asarray(all_ids + [0]*window, dtype=np.uint32)
    # patterns
    pats = sorted(pat_set)
    pat_kind = np.asarray([p[0] for p in pats], dtype=np.uint32)
    pat_depth = np.asarray([p[1] for p in pats], dtype=np.int32)
    pat_mask = np.asarray([p[2] for p in pats], dtype=np.uint32)
    sat_frac = float(sat.mean())
    print(f"build: {n} entries, S={S}, P={len(pats)} patterns, sat={sat.sum()} buckets ({sat_frac:.5f}), "
          f"spill={n_spill}, skipped_deep={skipped_deep}, {time.perf_counter()-t0:.1f}s", flush=True)
    return dict(table=table, all_ids=all_ids, subs=subs, salt=salt,
                pat_kind=pat_kind, pat_depth=pat_depth, pat_mask=pat_mask,
                sat=sat, S=S, window=window, max_levels=max_levels)

# ---------------- device kernel ----------------

def rotl_j(x, r):
    return (x << np.uint32(r)) | (x >> np.uint32(32 - r))

@partial(jax.jit, static_argnames=("window", "max_levels", "out_slots"))
def flat_match(table, all_ids, pat_kind, pat_depth, pat_mask,
               tok1, tok2, lengths, is_dollar, *, window, max_levels, out_slots):
    B, L = tok1.shape
    P = pat_kind.shape[0]
    S = table.shape[0]
    m1 = jnp.uint32(0x9E3779B1); m2 = jnp.uint32(0x85EBCA77)
    # pattern path hashes: [B, P]
    h1 = pat_depth.astype(jnp.uint32) * m2 ^ pat_kind
    h2 = pat_depth.astype(jnp.uint32) * m1 ^ pat_kind
    h1 = jnp.broadcast_to(h1[None, :], (B, P))
    h2 = jnp.broadcast_to(h2[None, :], (B, P))
    for d in range(max_levels):
        use = d < pat_depth  # [P]
        plus = (pat_mask >> np.uint32(d)) & 1  # [P]
        t1 = jnp.where(plus[None, :] == 1, jnp.uint32(0x9E3779B9), tok1[:, d][:, None])
        t2 = jnp.where(plus[None, :] == 1, jnp.uint32(0xC2B2AE3D), tok2[:, d][:, None])
        nh1 = (rotl_j(h1 ^ t1, 13) * m1)
        nh2 = (rotl_j(h2 ^ t2, 13) * m1)
        h1 = jnp.where(use[None, :], nh1, h1)
        h2 = jnp.where(use[None, :], nh2, h2)
    # active: exact: depth == n; hash: depth <= n
    n = lengths[:, None]
    is_hash = pat_kind == jnp.uint32(0x27D4EB2F)
    active = jnp.where(is_hash[None, :], pat_depth[None, :] <= n, pat_depth[None, :] == n)
    slot = (h1 & jnp.uint32(S - 1)).astype(jnp.int32)
    rows = table[jnp.where(active, slot, 0)]  # [B, P, 16] row gather
    # entry select: 4 entries
    ent = rows.reshape(B, P, 4, 4)
    hit = (ent[..., 0] == h1[..., None]) & (ent[..., 1] == h2[..., None])  # [B,P,4]
    hit = hit & active[..., None]
    meta = jnp.where(hit, ent[..., 2], 0).max(axis=-1)   # at most one hit
    start = jnp.where(hit, ent[..., 3], 0).max(axis=-1)
    hit_any = hit.any(axis=-1)
    nreg = (meta & 0x3FF).astype(jnp.int32)
    ninl = ((meta >> 10) & 0x3F).astype(jnp.int32)
    top_wild = (meta >> 16) & 1
    last_plus = (meta >> 17) & 1
    spill = (meta >> 18) & 1
    sat_probe = ((rows.reshape(B, P, 4, 4)[:, :, 0, 2] >> 19) & 1) == 1
    sat_probe = sat_probe & active
    exact_len = n == pat_depth[None, :]
    # '#' exact-length quirk: no match if filter's last level is '+'
    valid_hit = hit_any & ~(is_hash[None, :] & exact_len & (last_plus == 1))
    count = jnp.where(is_hash[None, :] & exact_len, nreg, nreg + ninl)
    count = jnp.where(valid_hit, count, 0)
    # id windows: [B, P, W] via slice-gather
    idx = jnp.where(valid_hit, start.astype(jnp.int32), 0)
    wins = jax.lax.gather(
        all_ids, idx.reshape(B, P, 1),
        jax.lax.GatherDimensionNumbers(offset_dims=(2,), collapsed_slice_dims=(),
                                       start_index_map=(0,), operand_batching_dims=()),
        slice_sizes=(window,), mode="clip",
    ).reshape(B, P, window)
    ks = jnp.arange(window, dtype=jnp.int32)
    validk = ks[None, None, :] < count[..., None]
    exempt = (wins >> np.uint32(30)) & 1
    dollar_drop = is_dollar[:, None, None] & (top_wild[..., None] == 1) & (exempt == 0)
    validk = validk & ~dollar_drop
    sid = (wins & jnp.uint32(0x3FFFFFFF)).astype(jnp.int32)
    flat_sid = jnp.where(validk, sid, -1).reshape(B, P * window)
    totals = validk.reshape(B, P * window).sum(axis=1)
    overflow = ((spill == 1) & valid_hit).any(axis=1) | sat_probe.any(axis=1)
    # saturation: a probe hitting a saturated bucket must host-route; encode:
    # saturated buckets have meta==0 rows but that's also "miss"... handled by
    # passing sat bitmap: (prototype: table rows for saturated buckets are all
    # zero; we mark via separate bitmap gather folded into table col?) --
    # prototype: sat bitmap folded as bit 19 of every entry meta in that bucket.
    return flat_sid, totals, overflow

# ---------------- harness ----------------

def subscribers_flat(built, topics, index):
    tok1, tok2, lengths, is_dollar, len_ovf = tokenize_topics(topics, built["max_levels"], built["salt"])
    dev = built["dev"]
    out, totals, ovf = flat_match(*dev, jnp.asarray(tok1), jnp.asarray(tok2),
                                  jnp.asarray(lengths), jnp.asarray(is_dollar),
                                  window=built["window"], max_levels=built["max_levels"], out_slots=64)
    out = np.asarray(out); ovf = np.asarray(ovf)
    res = []
    sat = built["sat"]
    for i, t in enumerate(topics):
        if not t:
            res.append(Subscribers()); continue
        if ovf[i] or len_ovf[i] or _probes_saturated(built, t):
            res.append(index.subscribers(t)); continue
        row = out[i]
        res.append(expand_sids(built["subs"], row[row >= 0], Subscribers()))
    return res

def _probes_saturated(built, topic):
    # host-side conservative check (prototype only; real impl device-side)
    if not built["sat"].any():
        return False
    return False  # skip in prototype when sat==0

def canon(s):
    return ({c: (sub.qos, tuple(sorted(sub.identifiers.items()))) for c, sub in s.subscriptions.items()},
            {f: set(m) for f, m in s.shared.items()},
            set(s.inline_subscriptions))

# correctness corpus: reference corner cases
def test_correctness():
    idx = TopicsIndex()
    subs = [
        ("c1", "a/b/c"), ("c2", "a/+/c"), ("c3", "a/b/#"), ("c4", "#"),
        ("c5", "+/b/c"), ("c6", "a/b"), ("c7", "a/b/c/d"), ("c8", "zen/#"),
        ("c9", "+"), ("c10", "/a"), ("c11", "+/a"), ("c12", "$SYS/+"),
        ("c13", "a/+/#"), ("c14", "+/+/c"), ("c15", ""),
        ("c16", f"{SHARE_PREFIX}/g1/a/b/c"), ("c17", f"{SHARE_PREFIX}/g1/+/b/c"),
    ]
    for c, f in subs:
        if f:
            idx.subscribe(c, Subscription(filter=f, qos=1))
    from mqtt_tpu.topics import InlineSubscription
    idx.inline_subscribe(InlineSubscription(filter="a/b/#", qos=0, identifier=7, handler=lambda *a: None))
    idx.inline_subscribe(InlineSubscription(filter="a/b", qos=0, identifier=8, handler=lambda *a: None))
    built = build_flat(idx, max_levels=6)
    built["dev"] = tuple(jnp.asarray(a) for a in
                         (built["table"], built["all_ids"], built["pat_kind"], built["pat_depth"], built["pat_mask"]))
    topics = ["a/b/c", "a/b", "a/x/c", "zen", "zen/x", "a", "b", "$SYS/x", "$SYS/broker",
              "/a", "a/b/c/d", "a/b/c/d/e", "x/b/c", "a/x", "", "a/b/x"]
    got = subscribers_flat(built, topics, idx)
    ok = True
    for t, g in zip(topics, got):
        h = idx.subscribers(t) if t else Subscribers()
        if canon(g) != canon(h):
            ok = False
            print(f"MISMATCH {t!r}:\n  dev  {canon(g)}\n  host {canon(h)}", flush=True)
    print("corner-case parity:", "OK" if ok else "FAIL", flush=True)
    return ok

def test_random(n_subs=3000, n_topics=512, seed=11):
    rng = random.Random(seed)
    v = [f"s{i}" for i in range(12)] + ["+"]
    idx = TopicsIndex()
    for i in range(n_subs):
        depth = rng.randint(1, 5)
        parts = [rng.choice(v) for _ in range(depth)]
        if rng.random() < 0.2:
            parts = parts[:rng.randint(0, depth-1)] + ["#"]
        f = "/".join(parts)
        try:
            idx.subscribe(f"cl{i%700}", Subscription(filter=f, qos=i % 3, identifier=i % 5))
        except Exception:
            pass
    built = build_flat(idx, max_levels=6)
    built["dev"] = tuple(jnp.asarray(a) for a in
                         (built["table"], built["all_ids"], built["pat_kind"], built["pat_depth"], built["pat_mask"]))
    vt = [f"s{i}" for i in range(12)]
    topics = ["/".join(rng.choice(vt) for _ in range(rng.randint(1, 6))) for _ in range(n_topics)]
    got = subscribers_flat(built, topics, idx)
    bad = 0
    for t, g in zip(topics, got):
        if canon(g) != canon(idx.subscribers(t)):
            bad += 1
            if bad <= 3:
                print(f"MISMATCH {t!r}", flush=True)
    print(f"random parity: {n_topics-bad}/{n_topics} OK", flush=True)
    return bad == 0

if __name__ == "__main__":
    ok1 = test_correctness()
    ok2 = test_random()
    print("ALL OK" if (ok1 and ok2) else "FAILURES", flush=True)
