"""E7: what costs 15ms? Isolate dispatch floor vs output buffers vs compute."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax, jax.numpy as jnp

B = 131072
key = jax.random.PRNGKey(0)
N = 1 << 20
table = jnp.arange(N, dtype=jnp.int32)
idx = jax.random.randint(key, (B,), 0, N, dtype=jnp.int32)
jax.block_until_ready((table, idx))

def bench(name, fn, *args, iters=20):
    f = jax.jit(fn)
    red = jax.jit(lambda o: o.sum())
    int(np.asarray(red(f(*args))))  # compile + warm
    t0 = time.perf_counter()
    outs = [f(*args) for _ in range(iters)]
    int(np.asarray(red(outs[-1])))
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:34s} {dt*1e3:8.2f} ms/call", flush=True)

bench("elementwise I+1 -> [131072]", lambda T, I: I + 1, table, idx)
bench("elementwise I+1 -> scalar sum", lambda T, I: (I + 1).sum(), table, idx)
bench("gather T[I] -> [131072]", lambda T, I: T[I], table, idx)
bench("gather T[I] -> scalar sum", lambda T, I: T[I].sum(), table, idx)
a = jax.random.normal(key, (512, 512), jnp.bfloat16)
jax.block_until_ready(a)
bench("matmul 512x512 bf16", lambda A, _: A @ A, a, idx)
bench("matmul+sum 512x512", lambda A, _: (A @ A).sum(), a, idx)
# big elementwise: 128MB traffic
big = jnp.zeros((1 << 25,), jnp.float32)
jax.block_until_ready(big)
bench("elementwise on 128MB", lambda X, _: X * 2 + 1, big, idx)
