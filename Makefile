# Developer/CI entry points. `make verify` wraps the ROADMAP.md tier-1
# command verbatim; `make chaos-smoke` runs the slow-marked chaos drills
# (fault-injected matcher + mesh) that the default suite skips.
SHELL := /bin/bash
PY ?= python

.PHONY: verify chaos-smoke test

# the tier-1 gate: full non-slow suite on the CPU backend (ROADMAP.md)
verify:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

test: verify

# slow-marked chaos smoke: seeded dispatch hang/error/corrupt/flap and
# mesh peer kill under live traffic (tests/test_resilience.py), plus the
# sustained publish-storm overload drill (tests/test_overload.py)
chaos-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py \
	  tests/test_overload.py -q -m slow \
	  -p no:cacheprovider -p no:xdist -p no:randomly
