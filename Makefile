# Developer/CI entry points. `make verify` wraps the ROADMAP.md tier-1
# command verbatim (lint runs first — fast fail); `make chaos-smoke`
# runs the slow-marked chaos drills (fault-injected matcher + mesh)
# that the default suite skips; `make lint` is the static-analysis
# bundle (brokerlint + mypy-if-installed + the C gate).
SHELL := /bin/bash
PY ?= python

.PHONY: verify chaos-smoke test lint typecheck c-gate san-gate stage-gate lockgraph loopgraph pipeline-smoke conn-smoke recovery-smoke bench-trend scrape-cluster scrape-devices scenario-smoke scenario-matrix

# static analysis: the repo-specific concurrency/invariant lint pass
# (tools/brokerlint, README "Static analysis"), the mypy gate over the
# typed core modules (skipped with a notice when mypy is not installed —
# CI always installs it), and the C analysis gate over mqtt_tpu/native/
lint:
	$(PY) -m tools.brokerlint mqtt_tpu
	@if $(PY) -c "import mypy" >/dev/null 2>&1; then \
	  $(PY) -m mypy --config-file mypy.ini; \
	else echo "mypy not installed; skipping typecheck (CI runs it)"; fi
	PY=$(PY) tools/c_gate.sh

# hard-required mypy run (fails when mypy is absent)
typecheck:
	$(PY) -m mypy --config-file mypy.ini

# extract the whole-program lock-acquisition-order graph (brokerlint
# R9) and write exp/artifacts/lockgraph.{dot,json}; render the DOT with
# `dot -Tsvg exp/artifacts/lockgraph.dot` when graphviz is installed
lockgraph:
	$(PY) -m tools.brokerlint mqtt_tpu --lock-graph exp/artifacts

# extract the loop-affinity model (brokerlint R10-R15: loop-owned kinds,
# owner-attach sites, blessed marshal seams) and write
# exp/artifacts/loopgraph.{dot,json}
loopgraph:
	$(PY) -m tools.brokerlint mqtt_tpu --loop-graph exp/artifacts

# gcc -fanalyzer (+ cppcheck when installed) over the native C sources
c-gate:
	PY=$(PY) tools/c_gate.sh

# ASAN/UBSAN leg: sanitized rebuild of both native modules + the
# native-facing test subset run under them (ISSUE 13)
san-gate:
	PY=$(PY) tools/c_gate.sh --san

# the tier-1 gate: full non-slow suite on the CPU backend (ROADMAP.md);
# lint runs first so an invariant break fails in seconds, not minutes
# (tests/test_lint.py also asserts a clean tree from inside the suite)
verify: lint
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

test: verify

# slow-marked chaos smoke: seeded dispatch hang/error/corrupt/flap and
# mesh peer kill under live traffic (tests/test_resilience.py), the
# sustained publish-storm overload drill (tests/test_overload.py), the
# partition-storm mesh drill against a flapping 2-worker broker
# (tests/test_cluster.py + stress.py --partition), the multi-worker
# mesh drills (tests/test_mesh_drill.py: the 32-worker partition
# storm, the shaped-TCP two-machine WAN predicate drill, and the
# root-kill failover leg), and the seeded thread-schedule sweeps
# (tests/test_race.py: the switch-interval fuzz plus the 200-schedule
# graph-guided preemption fuzzer)
chaos-smoke:
	env JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py \
	  tests/test_overload.py tests/test_cluster.py tests/test_race.py \
	  tests/test_federation.py tests/test_tree_mesh.py \
	  tests/test_mesh_drill.py \
	  -q -m slow \
	  -p no:cacheprovider -p no:xdist -p no:randomly

# per-stage regression gate over the checked-in BENCH artifacts
# (exp/stage_gate.py): fails on a >25% p99 regression in any stage
stage-gate:
	$(PY) exp/stage_gate.py

# bench-history trend gate (exp/bench_trend.py): fails when the newest
# ledger round's headline fell >25% below the median of the prior
# rounds in the window (BENCH_HISTORY.jsonl, appended by bench.py)
bench-trend:
	$(PY) exp/bench_trend.py

# mesh federation scrape gate (exp/scrape_cluster.py): boot a 3-worker
# tree mesh, drive a cross-worker burst, scrape the root's
# /metrics/cluster + /healthz, validate the federated exposition and
# nonzero remote-path delivery-latency samples
scrape-cluster:
	env JAX_PLATFORMS=cpu $(PY) exp/scrape_cluster.py

# device-observatory scrape gate (exp/scrape_devices.py): boot a broker
# over an 8-way forced host mesh, drive a burst + an 8-way sharded
# matcher, and validate GET /devices + the labeled mqtt_tpu_device_*
# exposition families for all 8 devices (ISSUE 18)
scrape-devices:
	env JAX_PLATFORMS=cpu $(PY) exp/scrape_devices.py

# staged-pipeline smoke (exp/pipeline_smoke.py): boot the broker with
# compaction + the 3-deep pipeline on, 1k-publish burst vs wildcard
# subs, zero host-trie-oracle mismatches and a nonzero device duty
# cycle; writes pipeline-smoke.json (uploaded as a CI artifact)
pipeline-smoke:
	env JAX_PLATFORMS=cpu $(PY) exp/pipeline_smoke.py

# connection-scale smoke (exp/conn_smoke.py): boot the event-loop shard
# fabric (loop_shards>1), ramp thousands of mostly-idle connections +
# a publish burst, assert healthz 200, zero host-trie-oracle delivery
# mismatches, and per-shard connection spread within 2x; writes
# conn-smoke.json (uploaded as a CI artifact)
conn-smoke:
	env JAX_PLATFORMS=cpu $(PY) exp/conn_smoke.py

# scenario lab (exp/scenario_lab.py + mqtt_tpu/scenarios.py, ISSUE 20):
# seeded workload/fault scenarios judged by the delivery oracle AND the
# SLO engine's burn-rate objectives. The smoke tier runs in the CI
# verify job (artifact: exp/artifacts/scenario_lab.json); the full
# matrix — QoS2 kill -9 exactly-once, will storm, 3-worker federation,
# live tenant re-key — rides the nightly chaos leg and appends its
# round to BENCH_HISTORY.jsonl for the bench-trend gate
scenario-smoke:
	env JAX_PLATFORMS=cpu $(PY) exp/scenario_lab.py --smoke

scenario-matrix:
	env JAX_PLATFORMS=cpu $(PY) exp/scenario_lab.py --all

# crash-recovery smoke (exp/recovery_smoke.py): seed a broker subprocess
# with persistent sessions + retained state over the log-structured
# store, kill -9 it, restart on the same directory, assert the recovery
# budget, the healthz recovering->ready flip, exact restored counts, and
# the post-restart delivery oracle (session resume, live routing,
# retained redelivery through the device matcher with zero oracle
# mismatches); writes recovery-smoke.json (uploaded as a CI artifact)
recovery-smoke:
	env JAX_PLATFORMS=cpu $(PY) exp/recovery_smoke.py
