"""End-to-end trace plane: sampled per-publish span trees, Chrome
trace-event export, and the device pipeline profiler.

PR 3's telemetry histograms answer "what is the p99 of each stage" —
they cannot answer "where did THIS slow publish spend its time", which
is the question ROADMAP item 1 (the 40-100x kernel->e2e gap, owned by
host<->device staging) actually needs, and the per-message latency
decomposition the IoT broker benchmarking study treats as the primary
comparison axis (PAPERS.md). This module adds:

- ``Tracer``: a lock-cheap bounded ring of finished spans plus seeded
  trace/span id generation. 1-in-N publishes (``Options.trace_sample``,
  same knob family as ``telemetry_sample``) carry a ``PublishTrace`` —
  a :class:`~mqtt_tpu.telemetry.StageClock` that also owns a trace id —
  and at fan-out the clock's stamps become one span tree: a root
  ``publish`` span with one child per pipeline stage
  (decode -> admission -> staging_wait -> h2d -> device_dispatch ->
  d2h -> fanout), plus per-peer ``forward`` spans at the origin worker
  and a ``remote_fanout`` span at each receiving worker (the trace id
  rides the cluster frames — TD-MQTT-style transparent cross-broker
  tracing). The ring exports as Chrome trace-event JSON
  (Perfetto-loadable) at ``GET /traces`` and in trigger dumps.
- ``DeviceProfiler``: sub-stamps every device batch (tokenize+dispatch
  issue, blocking D2H sync) and folds the windows into the numbers that
  gate ROADMAP item 1's 3-deep-pipeline work: kernel **duty cycle**
  (union of device-busy windows over wall time), **overlap ratio**
  (how much of the summed busy time was pipelined under another
  batch's window), and the **staging idle-gap** histogram (device
  sitting idle between batches — the time the pipeline work must
  reclaim).
- ``check_trace_events``: a ~20-line pure-Python validator for the
  exported JSON (the /traces analog of ``telemetry.check_exposition``),
  used by CI's trace-scrape gate and the test suite.

The unsampled hot path pays one modulo; everything else is on by
default behind ``Options.trace`` / the ``trace_*`` config knobs.
"""

from __future__ import annotations

import collections
import json
import random
import threading
import time
import zlib
from typing import Any, Optional

# DEVICE_SUBSTAGES / TRACE_USER_PROPERTY are canonical in telemetry.py
# (this module imports telemetry, never the reverse); re-exported here
# because they are trace-plane concepts callers look for in this module
from .telemetry import (  # noqa: F401  (re-exports)
    DEVICE_SUBSTAGES,
    TRACE_USER_PROPERTY,
    Histogram,
    StageClock,
)


class PublishTrace(StageClock):
    """A stage clock that is also a trace context: carries the trace id
    and the pre-allocated root span id, so spans recorded BEFORE the
    clock finishes (per-peer forwards) can already parent on the root.
    Rides the pipeline exactly like a plain StageClock — every layer
    that stamps a StageClock stamps this unchanged."""

    __slots__ = ("tracer", "trace_id", "span_id")

    def __init__(self, tracer: "Tracer", trace_id: Optional[str] = None) -> None:
        super().__init__()
        self.tracer = tracer
        self.trace_id = trace_id if trace_id else tracer.new_trace_id()
        self.span_id = tracer.new_span_id()


class Tracer:
    """Bounded span ring + id generation + Chrome trace-event export.

    Spans are stored as plain tuples ``(name, cat, trace_id, span_id,
    parent_id, t0_perf, dur_s, args)``; the ring append is the only
    hot-path cost and runs under a lock held for one append (the same
    posture as the flight recorder's ring). Export converts perf_counter
    times to wall-anchored microseconds, so two workers' exports merge
    into one coherent timeline (same machine, same anchor source).
    ``seed`` makes trace/span ids deterministic for tests."""

    def __init__(
        self,
        sample: int = 64,
        ring: int = 4096,
        seed: Optional[int] = None,
        registry: Any = None,
    ) -> None:
        self.sample = max(0, int(sample))
        # lock-plane adoption (mqtt_tpu.utils.locked): span appends from
        # data-plane threads race /traces exports under this lock
        from .utils.locked import InstrumentedLock

        self._lock = InstrumentedLock("trace_ring")
        self.ring: collections.deque = collections.deque(maxlen=max(16, int(ring)))
        self._rng = random.Random(seed)
        # worker id in a mesh (mqtt_tpu.cluster sets it); the export's
        # Chrome-trace pid, so merged multi-worker files keep one track
        # group per worker
        self.pid = 0
        self.spans_total = 0
        self.publishes_total = 0
        # client-driven adoption (v5 trace-id user property) is rate-
        # bounded: a client stamping EVERY publish must not buy itself
        # 100% tracing (bypassing trace_sample) and flood the ring,
        # evicting the organic samples. 0 disables adoption entirely.
        self.adopt_max_per_s = 64
        self._adopt_window = 0.0  # monotonic second the count belongs to
        self._adopt_count = 0
        # wall anchor for export: perf_counter + anchor = unix seconds.
        # brokerlint: ok=R3 a one-shot wall anchor so exported trace timestamps are operator-correlatable; all durations stay monotonic
        self._anchor = time.time() - time.perf_counter()
        if registry is not None:
            registry.counter(
                "mqtt_tpu_trace_spans_total",
                "Spans recorded into the trace ring",
                fn=lambda: self.spans_total,
            )
            registry.counter(
                "mqtt_tpu_trace_publishes_total",
                "Publishes that carried a sampled trace context",
                fn=lambda: self.publishes_total,
            )

    # -- ids ----------------------------------------------------------------

    def new_trace_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(64):016x}"

    def new_span_id(self) -> str:
        with self._lock:
            return f"{self._rng.getrandbits(48):012x}"

    # -- recording ----------------------------------------------------------

    def publish_trace(self, trace_id: Optional[str] = None) -> PublishTrace:
        """A trace context for one publish (the caller owns the 1-in-N
        sampling verdict — mqtt_tpu.telemetry.Telemetry.publish_clock)."""
        return PublishTrace(self, trace_id)

    def allow_adopt(self) -> bool:
        """The rate verdict for one client-supplied trace-id adoption:
        at most ``adopt_max_per_s`` per wall second, excess publishes
        stay untraced (they still flow normally)."""
        if self.adopt_max_per_s <= 0:
            return False
        now = time.monotonic()
        with self._lock:
            if now - self._adopt_window >= 1.0:
                self._adopt_window = now
                self._adopt_count = 0
            if self._adopt_count >= self.adopt_max_per_s:
                return False
            self._adopt_count += 1
            return True

    def add_span(
        self,
        name: str,
        cat: str,
        trace_id: str,
        span_id: str,
        parent_id: Optional[str],
        t0: float,
        dur: float,
        args: Optional[dict] = None,
    ) -> None:
        """Record one finished span (``t0`` in perf_counter seconds)."""
        with self._lock:
            self.ring.append(
                (name, cat, trace_id, span_id, parent_id, t0, dur, args)
            )
            self.spans_total += 1

    def finish_publish(self, trace: PublishTrace, topic: str = "", qos: int = 0) -> None:
        """Fold one finished publish trace into the ring: the root
        ``publish`` span plus one child span per stamped stage, laid out
        back-to-back from the clock's start (a StageClock records each
        stage's duration since the previous stamp, so the absolute
        boundaries reconstruct exactly)."""
        spans = []
        t = trace.t0
        for stage, dt in trace.stages:
            spans.append(
                (stage, "stage", trace.trace_id, self.new_span_id(),
                 trace.span_id, t, dt, None)
            )
            t += dt
        spans.append(
            ("publish", "publish", trace.trace_id, trace.span_id, None,
             trace.t0, trace.total(),
             # the root span carries the delivery SLI headline (ISSUE
             # 14): a Perfetto view of a breach exemplar shows the same
             # arrival->flush number the delivery-latency histogram
             # recorded, with the stage breakdown nested under it
             {"topic": topic, "qos": qos,
              "delivery_ms": round(trace.total() * 1e3, 3)})
        )
        with self._lock:
            self.ring.extend(spans)
            self.spans_total += len(spans)
            self.publishes_total += 1

    # -- export -------------------------------------------------------------

    def export(self) -> dict:
        """The ring as a Chrome trace-event document (Perfetto loads it
        directly: open ui.perfetto.dev and drop the JSON in). Spans of
        one trace share a ``tid`` derived from the trace id, so
        concurrent traces render as separate nested tracks."""
        with self._lock:
            spans = list(self.ring)
        events = []
        for name, cat, trace_id, span_id, parent_id, t0, dur, args in spans:
            a = {"trace_id": trace_id, "span_id": span_id}
            if parent_id is not None:
                a["parent_id"] = parent_id
            if args:
                a.update(args)
            events.append(
                {
                    "name": name,
                    "cat": cat,
                    "ph": "X",
                    "ts": round((t0 + self._anchor) * 1e6, 3),
                    "dur": round(dur * 1e6, 3),
                    "pid": self.pid,
                    # stable per-trace track id; crc so ADOPTED ids (any
                    # client-chosen string) never break the export
                    "tid": zlib.crc32(trace_id.encode()) % 1_000_000,
                    "args": a,
                }
            )
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def export_json(self) -> str:
        return json.dumps(self.export())


def check_trace_events(doc) -> int:
    """A minimal pure-Python Chrome trace-event checker (the /traces
    analog of ``telemetry.check_exposition``): the document must carry a
    non-empty ``traceEvents`` list of well-formed complete events.
    Unresolved parent ids are allowed — one worker's export of a
    cross-worker trace legitimately references the peer's spans.
    Accepts a JSON string or a parsed dict; returns the event count."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    events = doc.get("traceEvents") if isinstance(doc, dict) else None
    if not isinstance(events, list) or not events:
        raise ValueError("no traceEvents")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or not isinstance(ev.get("name"), str) or not ev["name"]:
            raise ValueError(f"event {i}: missing name")
        if ev.get("ph") != "X":
            raise ValueError(f"event {i}: ph must be 'X' (complete)")
        for k in ("ts", "dur"):
            if not isinstance(ev.get(k), (int, float)) or ev[k] < 0:
                raise ValueError(f"event {i}: bad {k}: {ev.get(k)!r}")
        if not isinstance(ev.get("pid"), int) or not isinstance(ev.get("tid"), int):
            raise ValueError(f"event {i}: pid/tid must be ints")
        args = ev.get("args")
        if args is not None and not isinstance(args, dict):
            raise ValueError(f"event {i}: args must be a dict")
    return len(events)


class BatchProfile:
    """One batch's device-timing record, created at issue and carried
    WITH the batch (the resolver closure and the staging queue both hold
    it), so profile boundaries can never be attributed to a different
    batch — the resilience wrapper resolves batches eagerly on guard
    threads, concurrently and potentially out of order, which rules out
    any "most recent resolve" pairing. Tuple assignments are atomic
    under the GIL; a reader sees either None or a complete window."""

    __slots__ = (
        "dispatch", "d2h", "d2h_bytes", "d2h_bytes_ranges",
        "d2h_bytes_dense", "compact", "compact_overflow", "devices",
    )

    def __init__(self) -> None:
        # (start, end) of the tokenize+dispatch issue leg; None until
        # the batch actually dispatched to the device (the exact-map
        # fast path and host fallbacks never set it)
        self.dispatch: Optional[tuple[float, float]] = None
        # (start, end) of the blocking D2H result sync
        self.d2h: Optional[tuple[float, float]] = None
        # transfer accounting (ROADMAP item 1's compaction gap): the
        # actual D2H result bytes this batch moved, beside the bytes the
        # pre-compaction geometries would have moved — ranges = the
        # packed [B, 2P+2] form, dense = the padded [B, max_hits] slot
        # buffer. 0 = the matcher did not stamp this batch.
        self.d2h_bytes = 0
        self.d2h_bytes_ranges = 0
        self.d2h_bytes_dense = 0
        # True when the result came back as compacted (topic, sid) pairs;
        # compact_overflow marks the per-batch padded-path fallback
        self.compact = False
        self.compact_overflow = False
        # device ids this batch's window ran on, stamped by the matcher
        # at dispatch (TpuMatcher: the output buffer's device; sharded:
        # every mesh device). None = unstamped, folds as device 0.
        self.devices: Optional[tuple] = None


# D2H transfer sizes: single compact rows (~tens of bytes) up to the
# dense padded geometries (tens of MB)
BYTE_BOUNDS = (
    256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0,
    1048576.0, 4194304.0, 16777216.0, 67108864.0,
)


class _DevWindow:
    """One device's replica of the profiler's busy/overlap/idle fold
    (ISSUE 18): same arithmetic, keyed by device id, so a single-device
    run's window 0 is bit-identical to the unlabeled aggregates (the
    test parity oracle) and a sharded run gets one window per chip."""

    __slots__ = (
        "first_t", "last_t", "busy_until", "busy_s", "window_s",
        "overlap_s", "batches", "d2h_bytes_total",
        "issue_hist", "d2h_hist", "idle_hist", "bytes_hist",
    )

    def __init__(self) -> None:
        self.first_t: Optional[float] = None
        self.last_t = 0.0
        self.busy_until = 0.0
        self.busy_s = 0.0
        self.window_s = 0.0
        self.overlap_s = 0.0
        self.batches = 0
        self.d2h_bytes_total = 0
        self.issue_hist = Histogram()
        self.d2h_hist = Histogram()
        self.idle_hist = Histogram()
        self.bytes_hist = Histogram(bounds=BYTE_BOUNDS)

    def duty_cycle(self) -> float:
        if self.first_t is None or self.last_t <= self.first_t:
            return 0.0
        return self.busy_s / (self.last_t - self.first_t)

    def overlap_ratio(self) -> float:
        return self.overlap_s / self.window_s if self.window_s > 0 else 0.0


class DeviceProfiler:
    """Host-side device pipeline profiler: each batch's dispatch and
    D2H windows land on its own :class:`BatchProfile` record and fold
    into duty-cycle / overlap / idle-gap aggregates.

    A batch's **device window** runs from dispatch-return (the kernel is
    queued and the host moves on) to the end of the blocking D2H sync —
    kernel execution plus result transfer, the best host-observable
    proxy without a device-side profiler (``Options.
    trace_jax_profiler_dir`` hooks ``jax.profiler`` for the real
    timeline). Aggregates:

    - ``duty_cycle`` = union of device windows / wall time since the
      first dispatch — how busy the device actually is (ROADMAP item 1:
      "the kernel is idle most of the wall clock").
    - ``overlap_ratio`` = overlapped window time / summed window time —
      how deep the staging pipeline actually runs (0 = strictly serial,
      approaching (depth-1)/depth for a depth-N pipeline).
    - ``idle_gap`` histogram = device-idle stretches between windows —
      exactly the gaps a 3-deep pipeline must close.

    Dispatches and resolves may come from different threads (the
    staging loop issues on the event loop; resolves run in an executor
    or on resilience guard threads); everything mutates under one lock,
    held for arithmetic only."""

    def __init__(self, registry: Any = None) -> None:
        self._lock = threading.Lock()
        self._registry = registry
        # per-device window replicas (ISSUE 18), keyed by device id;
        # mutated under _lock, child registration happens outside it
        self._dev: dict[int, _DevWindow] = {}
        self.batches = 0
        self._first_t: Optional[float] = None
        self._last_t = 0.0
        self._busy_until = 0.0
        self._busy_s = 0.0  # union of device windows
        self._window_s = 0.0  # sum of device windows
        self._overlap_s = 0.0
        # device-resident compaction accounting (ROADMAP item 1): bytes
        # actually transferred vs the pre-compaction geometries, and the
        # compacted-batch / overflow-fallback split — stamped per batch
        # on its BatchProfile by the matcher
        self.compact_batches = 0
        self.compact_overflows = 0
        self.d2h_bytes_total = 0
        self.d2h_bytes_ranges_total = 0
        self.d2h_bytes_dense_total = 0
        self._bytes_batches = 0  # batches that stamped transfer bytes
        if registry is not None:
            self.issue_hist = registry.histogram(
                "mqtt_tpu_device_issue_seconds",
                "Per-batch host tokenize + device dispatch (H2D issue) wall time",
            )
            self.d2h_hist = registry.histogram(
                "mqtt_tpu_device_d2h_seconds",
                "Per-batch blocking D2H result-sync wall time",
            )
            self.idle_gap_hist = registry.histogram(
                "mqtt_tpu_device_idle_gap_seconds",
                "Device-idle stretches between consecutive batch windows",
            )
            self.compact_d2h_hist = registry.histogram(
                "mqtt_tpu_device_compact_d2h_seconds",
                "Blocking D2H sync wall time of compacted-result batches "
                "(the compaction d2h leg)",
            )
            registry.gauge(
                "mqtt_tpu_device_duty_cycle_ratio",
                "Union of device-busy windows over wall time since first dispatch",
                fn=self.duty_cycle,
            )
            registry.gauge(
                "mqtt_tpu_device_overlap_ratio",
                "Overlapped device-window time over summed window time "
                "(pipeline depth proxy)",
                fn=self.overlap_ratio,
            )
        else:
            self.issue_hist = Histogram()
            self.d2h_hist = Histogram()
            self.idle_gap_hist = Histogram()
            self.compact_d2h_hist = Histogram()

    # -- recording (matcher hooks) -----------------------------------------

    def open_batch(self) -> BatchProfile:
        """A fresh per-batch record; the matcher fills it and whoever
        holds the batch (staging drain loop, bench) reads it."""
        return BatchProfile()

    def ensure_device(self, did: int) -> _DevWindow:
        """The window replica for one device id, creating it (and its
        ``device``-labeled metric children) on first sight. Idempotent;
        registration runs outside the fold lock."""
        with self._lock:
            dw = self._dev.get(did)
        if dw is not None:
            return dw
        dw = _DevWindow()
        with self._lock:
            have = self._dev.setdefault(did, dw)
        if have is not dw:
            return have  # lost the race: the winner registered children
        reg = self._registry
        if reg is not None:
            dev = str(did)
            reg.histogram(
                "mqtt_tpu_device_issue_seconds",
                fn=lambda d=dw: d.issue_hist, device=dev,
            )
            reg.histogram(
                "mqtt_tpu_device_d2h_seconds",
                fn=lambda d=dw: d.d2h_hist, device=dev,
            )
            reg.histogram(
                "mqtt_tpu_device_idle_gap_seconds",
                fn=lambda d=dw: d.idle_hist, device=dev,
            )
            reg.histogram(
                "mqtt_tpu_device_d2h_bytes",
                "Per-batch D2H result bytes attributed to each device "
                "(even split across a sharded batch's mesh)",
                bounds=BYTE_BOUNDS,
                fn=lambda d=dw: d.bytes_hist, device=dev,
            )
            reg.gauge(
                "mqtt_tpu_device_duty_cycle_ratio",
                fn=lambda d=dw: d.duty_cycle(), device=dev,
            )
            reg.gauge(
                "mqtt_tpu_device_overlap_ratio",
                fn=lambda d=dw: d.overlap_ratio(), device=dev,
            )
        return dw

    def note_dispatch(self, rec: BatchProfile, t0: float, t1: float) -> None:
        """One batch issued: tokenize + device dispatch ran [t0, t1];
        the device window opens at t1."""
        rec.dispatch = (t0, t1)
        self.issue_hist.observe(t1 - t0)
        for did in rec.devices or (0,):
            self.ensure_device(did).issue_hist.observe(t1 - t0)

    def note_resolve(self, rec: BatchProfile, sync_start: float, sync_end: float) -> None:
        """One batch's blocking D2H sync ran [sync_start, sync_end];
        fold its device window (dispatch-return -> sync end) into the
        busy/overlap/idle accounting. Pairing is exact — the window
        boundaries live on the batch's own record."""
        rec.d2h = (sync_start, sync_end)
        self.d2h_hist.observe(sync_end - sync_start)
        if getattr(rec, "compact", False):
            self.compact_d2h_hist.observe(sync_end - sync_start)
        if rec.dispatch is None:
            return  # never dispatched (shouldn't happen): histogram only
        t_disp = rec.dispatch[1]
        devs = rec.devices or (0,)
        windows = [self.ensure_device(d) for d in devs]
        # transfer bytes attribute evenly across a sharded batch's mesh
        # (each chip moved ~1/n of the result) — exact for one device
        per_dev_bytes = getattr(rec, "d2h_bytes", 0) // len(devs)
        with self._lock:
            if getattr(rec, "d2h_bytes", 0):
                self._bytes_batches += 1
                self.d2h_bytes_total += rec.d2h_bytes
                self.d2h_bytes_ranges_total += rec.d2h_bytes_ranges
                self.d2h_bytes_dense_total += rec.d2h_bytes_dense
            if getattr(rec, "compact", False):
                if rec.compact_overflow:
                    self.compact_overflows += 1
                else:
                    self.compact_batches += 1
            end = max(sync_end, t_disp)
            self.batches += 1
            if self._first_t is None:
                self._first_t = t_disp
            self._last_t = max(self._last_t, end)
            self._window_s += end - t_disp
            if t_disp >= self._busy_until:
                if self._busy_until > 0.0:
                    self.idle_gap_hist.observe(t_disp - self._busy_until)
                self._busy_s += end - t_disp
            else:
                self._overlap_s += max(0.0, min(self._busy_until, end) - t_disp)
                self._busy_s += max(0.0, end - self._busy_until)
            self._busy_until = max(self._busy_until, end)
            # the same fold, replicated per participating device: a
            # single-device run's window 0 tracks the aggregates exactly
            for dw in windows:
                dw.batches += 1
                dw.d2h_hist.observe(sync_end - sync_start)
                if per_dev_bytes:
                    dw.bytes_hist.observe(per_dev_bytes)
                    dw.d2h_bytes_total += per_dev_bytes
                if dw.first_t is None:
                    dw.first_t = t_disp
                dw.last_t = max(dw.last_t, end)
                dw.window_s += end - t_disp
                if t_disp >= dw.busy_until:
                    if dw.busy_until > 0.0:
                        dw.idle_hist.observe(t_disp - dw.busy_until)
                    dw.busy_s += end - t_disp
                else:
                    dw.overlap_s += max(0.0, min(dw.busy_until, end) - t_disp)
                    dw.busy_s += max(0.0, end - dw.busy_until)
                dw.busy_until = max(dw.busy_until, end)

    # -- aggregates ---------------------------------------------------------

    def duty_cycle(self) -> float:
        with self._lock:
            if self._first_t is None or self._last_t <= self._first_t:
                return 0.0
            return self._busy_s / (self._last_t - self._first_t)

    def overlap_ratio(self) -> float:
        with self._lock:
            return self._overlap_s / self._window_s if self._window_s > 0 else 0.0

    def device_snapshot(self) -> dict:
        """Per-device window aggregates keyed by device id — what
        DeviceStatsPlane.snapshot() merges into the /devices body."""
        out: dict[int, dict] = {}
        with self._lock:
            for did, dw in sorted(self._dev.items()):
                out[did] = {
                    "duty_cycle": round(dw.duty_cycle(), 4),
                    "overlap_ratio": round(dw.overlap_ratio(), 4),
                    "batches": dw.batches,
                    "d2h_bytes_total": dw.d2h_bytes_total,
                    "issue_p99_ms": round(
                        dw.issue_hist.percentile(0.99) * 1e3, 3
                    ),
                    "d2h_p99_ms": round(dw.d2h_hist.percentile(0.99) * 1e3, 3),
                    "idle_gap_p99_ms": round(
                        dw.idle_hist.percentile(0.99) * 1e3, 3
                    ),
                }
        return out

    def bench_block(self) -> dict:
        """The BENCH-json device-pipeline block (configs 2 and 8): the
        exact numbers ROADMAP item 1's overlapped-staging work must
        move, baselined per round so the gap is diffable."""
        out = {
            "batches": self.batches,
            "duty_cycle": round(self.duty_cycle(), 4),
            "overlap_ratio": round(self.overlap_ratio(), 4),
            "issue_p99_ms": round(self.issue_hist.percentile(0.99) * 1e3, 3),
            "d2h_p99_ms": round(self.d2h_hist.percentile(0.99) * 1e3, 3),
            "idle_gap_p99_ms": round(
                self.idle_gap_hist.percentile(0.99) * 1e3, 3
            ),
            "idle_gap_count": self.idle_gap_hist.count,
        }
        with self._lock:
            nb = self._bytes_batches
            if nb:
                # the compaction transfer ledger (ROADMAP item 1's D2H
                # criterion): actual result bytes per batch beside the
                # pre-compaction geometries and the reduction they imply
                out["d2h_bytes_per_batch"] = round(self.d2h_bytes_total / nb)
                out["d2h_bytes_ranges_per_batch"] = round(
                    self.d2h_bytes_ranges_total / nb
                )
                out["d2h_bytes_padded_per_batch"] = round(
                    self.d2h_bytes_dense_total / nb
                )
                out["d2h_reduction_vs_padded"] = round(
                    self.d2h_bytes_dense_total / max(1, self.d2h_bytes_total), 2
                )
                out["d2h_reduction_vs_ranges"] = round(
                    self.d2h_bytes_ranges_total / max(1, self.d2h_bytes_total),
                    2,
                )
            out["compact_batches"] = self.compact_batches
            out["compact_overflows"] = self.compact_overflows
        if self.compact_d2h_hist.count:
            out["compact_d2h_p99_ms"] = round(
                self.compact_d2h_hist.percentile(0.99) * 1e3, 3
            )
        return out
