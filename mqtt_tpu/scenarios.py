"""Scenario lab (ISSUE 20): the workload/fault matrix as ONE reproducible
gate.

Every scenario is a declarative :class:`ScenarioSpec` — fleet shape,
traffic mix, seeded fault script, SLO objectives, pass/fail oracles —
executed by one runner that composes the machinery the repo already has:

- the broker itself boots in-process on a real TCP listener (the
  bench.py idiom, port 0 so parallel runs never collide);
- traffic drives through wire-true MQTT clients (:class:`ScenarioClient`
  speaks the full QoS0/1/2 state machine, wills, v5 properties);
- faults come from mqtt_tpu.faults (seeded storms, ``drop_fleet`` mass
  disconnects) and the durable plane's kill -9 crash-image pattern;
- the GATE is the SLO engine: each spec names burn-rate objectives over
  the scenario's own delivery-oracle counters
  (``mqtt_tpu_scenario_*_total``), and the verdict is "no objective
  breached" — the same alerting math production runs, pointed at a
  reproducible drill;
- results append to ``BENCH_HISTORY.jsonl`` via exp/scenario_lab.py so
  a regressing scenario trips exp/bench_trend.py in CI like a bench
  regression would.

Determinism: every scenario runs from its spec seed (``run_scenario``
accepts an override) — fault victims, payload padding, and key material
all draw from that one ``random.Random``, so a red run replays exactly.

The epoch re-key protocol exercised by ``tenant_rekey`` (the tentpole
oracle) is documented in README "Scenario lab": clients that opt into
rotation stamp every nonce with the epoch tag they seal under
(``tenancy.epoch_tag_nonce``) — inert before the first rotation, and
the unambiguous drain discriminator after it.
"""

from __future__ import annotations

import asyncio
import json
import random
import shutil
import tempfile
import time
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from . import packets as pkts
from .packets import (
    CONNACK,
    PINGRESP,
    PUBACK,
    PUBCOMP,
    PUBLISH,
    PUBREC,
    PUBREL,
    SUBACK,
    ConnectParams,
    FixedHeader,
    Packet,
    Properties,
    Subscription,
    decode_length,
    decode_packet,
    encode_packet,
)
from .slo import SLOEngine, parse_objectives

__all__ = [
    "SCENARIOS",
    "ScenarioBroker",
    "ScenarioClient",
    "ScenarioSpec",
    "DeliveryOracle",
    "run_scenario",
    "run_matrix",
    "scenario_names",
]

# one whole-scenario watchdog: a wedged drill must fail, not hang CI
RUN_TIMEOUT_S = 180.0
# synthetic gate span: the delivery oracle settles its counters, then
# the SLO engine sees exactly two snapshots GATE_SPAN_S apart — inside
# both burn windows of every catalog objective, so one bad event burns
GATE_SPAN_S = 3.0

_IO_TIMEOUT = 15.0


# -- declarative specs -------------------------------------------------------


@dataclass(frozen=True)
class ScenarioSpec:
    """One catalog row: everything a run needs except the driver code.

    ``objectives`` are SLO objective spec strings (mqtt_tpu.slo grammar)
    — the gate; ``params`` the fleet/traffic/fault shape the driver
    reads; ``smoke`` marks the cheap rows ``make scenario-smoke`` runs
    in the verify job (the full matrix rides the nightly chaos leg)."""

    name: str
    title: str
    seed: int
    objectives: tuple[str, ...]
    params: dict = field(default_factory=dict)
    smoke: bool = False


# -- the delivery oracle -----------------------------------------------------


class DeliveryOracle:
    """Exactly-once bookkeeping for one scenario: drivers declare every
    delivery they expect (a hashable key per (subscriber, message)) and
    record every delivery that arrives; ``settle`` publishes the verdict
    as ``mqtt_tpu_scenario_*_total`` counters for the SLO gate.

    A delivery nobody expected (a leaked will, a post-retirement
    ciphertext) counts as a duplicate — a message that should not have
    happened is budget spend, not a free event."""

    def __init__(self, scenario: str) -> None:
        self.scenario = scenario
        self.expected: set = set()
        self.got: dict = {}
        self.faults = 0  # injected fault events (drops, stale sends)

    def expect(self, key: Any) -> None:
        self.expected.add(key)

    def deliver(self, key: Any) -> None:
        self.got[key] = self.got.get(key, 0) + 1

    def fault(self, n: int = 1) -> None:
        self.faults += n

    def gaps(self) -> int:
        return sum(1 for k in self.expected if k not in self.got)

    def complete(self) -> bool:
        return self.gaps() == 0

    def summary(self) -> dict:
        dups = sum(c - 1 for k, c in self.got.items() if k in self.expected)
        unexpected = sum(
            c for k, c in self.got.items() if k not in self.expected
        )
        return {
            "expected": len(self.expected),
            "delivered": sum(self.got.values()),
            "gaps": self.gaps(),
            "duplicates": dups + unexpected,
            "faults": self.faults,
        }

    def settle(self, registry: Any) -> dict:
        """Publish the final tallies as labeled counters on the
        scenario broker's registry — the families the catalog's SLO
        objectives (slo.RATIO_SLIS ``scenario_gap``/``scenario_dup``)
        and README's metric table name."""
        s = self.summary()
        lab = {"scenario": self.scenario}
        registry.counter(
            "mqtt_tpu_scenario_expected_total",
            "Deliveries the scenario oracle expected",
            **lab,
        ).inc(s["expected"])
        registry.counter(
            "mqtt_tpu_scenario_delivered_total",
            "Deliveries the scenario oracle observed",
            **lab,
        ).inc(s["delivered"])
        registry.counter(
            "mqtt_tpu_scenario_gaps_total",
            "Expected deliveries that never arrived (lost messages)",
            **lab,
        ).inc(s["gaps"])
        registry.counter(
            "mqtt_tpu_scenario_duplicates_total",
            "Repeat or unexpected deliveries (exactly-once violations)",
            **lab,
        ).inc(s["duplicates"])
        registry.counter(
            "mqtt_tpu_scenario_faults_total",
            "Fault events the scenario script injected",
            **lab,
        ).inc(s["faults"])
        return s


class ScenarioGate:
    """The SLO verdict over one scenario: a dedicated engine on the
    broker's own telemetry registry, driven by a synthetic clock so the
    burn windows close deterministically — baseline tick at t=0, the
    settled counters at t=GATE_SPAN_S, breach iff the spec's budget is
    burnt in both windows (the engine's production entry rule)."""

    def __init__(self, telemetry: Any, objective_specs: tuple) -> None:
        self._now = 0.0
        self.engine = SLOEngine(
            telemetry,
            parse_objectives(list(objective_specs)),
            clock=lambda: self._now,
        )
        self.engine.evaluate()

    def verdict(self) -> tuple[bool, list]:
        self._now += GATE_SPAN_S
        self.engine.evaluate()
        rows = list(self.engine.state().values())
        return (not any(r["breached"] for r in rows)), rows


# -- in-process broker + wire-true client ------------------------------------


class ScenarioBroker:
    """One in-process broker on a real localhost TCP listener. Port 0:
    the kernel assigns, ``start`` reads the bound port back, parallel
    labs never collide. Add hooks (storage, auth) between construction
    and ``start``."""

    def __init__(
        self, options: Optional[Any] = None, listener_id: str = "scn"
    ) -> None:
        from .hooks.auth import AllowHook
        from .listeners import Config as LConfig
        from .listeners.tcp import TCP
        from .server import Options, Server

        self.server = Server(options or Options(inline_client=False))
        self.server.add_hook(AllowHook())
        self._lid = listener_id
        self.server.add_listener(
            TCP(LConfig(type="tcp", id=listener_id, address="127.0.0.1:0"))
        )
        self.port = 0

    async def start(self) -> "ScenarioBroker":
        await self.server.serve()
        addr = self.server.listeners.get(self._lid).address()
        self.port = int(addr.rsplit(":", 1)[1])
        return self

    async def stop(self) -> None:
        await self.server.close()

    def total_inflight(self) -> int:
        """The broker-side inflight oracle: QoS windows still open
        across every session (the QoS2 scenario requires 0 after the
        fleet settles — exactly-once AND fully drained)."""
        with self.server.clients._lock:
            sessions = list(self.server.clients.internal.values())
        return sum(len(cl.state.inflight) for cl in sessions)


async def _read_packet(
    reader: asyncio.StreamReader, version: int, timeout: float = _IO_TIMEOUT
) -> Packet:
    first = await asyncio.wait_for(reader.readexactly(1), timeout)
    buf = bytearray(first)
    while True:
        b = await asyncio.wait_for(reader.readexactly(1), timeout)
        buf += b
        if not (b[0] & 0x80):
            break
    remaining, _ = decode_length(bytes(buf), 1)
    if remaining:
        buf += await asyncio.wait_for(reader.readexactly(remaining), timeout)
    return decode_packet(bytes(buf), version)


class ScenarioClient:
    """A wire-true MQTT client for scenario drivers: real TCP, real
    frames, the full QoS1/QoS2 acknowledgment state machine on both
    directions, wills with v5 delay intervals.

    Inbound QoS2 follows method A (deliver on PUBLISH, guard repeats by
    packet id until PUBREL releases the window); ``withhold_pubcomp``
    freezes the receiver mid-window — the kill -9 scenario's way of
    pinning broker-side QoS2 state for the crash image."""

    def __init__(
        self,
        port: int,
        cid: str,
        version: int = 4,
        host: str = "127.0.0.1",
    ) -> None:
        self.port = port
        self.cid = cid
        self.version = version
        self.host = host
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self.on_publish: Optional[Callable[[str, bytes, Packet], None]] = None
        self.withhold_pubcomp = False
        self.pubrel_seen: set[int] = set()
        self.session_present = False
        self._incoming: set[int] = set()  # inbound QoS2 windows mid-flight
        self._acks: dict[tuple[int, int], asyncio.Future] = {}
        self._pid = 0
        self._pump: Optional[asyncio.Task] = None

    # -- connection lifecycle ---------------------------------------------

    async def connect(
        self,
        clean: bool = True,
        keepalive: int = 120,
        will: Optional[tuple] = None,
        will_delay: int = 0,
    ) -> bool:
        """CONNECT and start the pump; returns session-present. ``will``
        is ``(topic, payload, qos, retain)``; a non-zero ``will_delay``
        needs version 5 (the delay rides the will properties)."""
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )
        cp = ConnectParams(
            protocol_name=b"MQTT",
            clean=clean,
            keepalive=keepalive,
            client_identifier=self.cid,
        )
        if will is not None:
            cp.will_flag = True
            cp.will_topic = will[0]
            cp.will_payload = will[1]
            cp.will_qos = will[2] if len(will) > 2 else 0
            cp.will_retain = bool(will[3]) if len(will) > 3 else False
            if will_delay:
                props = Properties()
                props.will_delay_interval = will_delay
                cp.will_properties = props
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.CONNECT),
            protocol_version=self.version,
            connect=cp,
        )
        self.writer.write(encode_packet(pk))
        await self.writer.drain()
        ack = await _read_packet(self.reader, self.version)
        if ack.fixed_header.type != CONNACK or ack.reason_code != 0:
            raise RuntimeError(
                f"{self.cid}: CONNACK code {ack.reason_code:#x}"
            )
        self.session_present = bool(getattr(ack, "session_present", False))
        self._pump = asyncio.get_running_loop().create_task(self._pump_loop())
        return self.session_present

    async def disconnect(self) -> None:
        """Graceful DISCONNECT then close (wills must NOT fire)."""
        if self.writer is not None:
            self.writer.write(
                encode_packet(
                    Packet(
                        fixed_header=FixedHeader(type=pkts.DISCONNECT),
                        protocol_version=self.version,
                    )
                )
            )
            await self.writer.drain()
        await self.close()

    def abort(self) -> None:
        """TCP-RST teardown, the shape ``faults.drop_fleet`` leaves."""
        if self.writer is not None:
            self.writer.transport.abort()

    async def close(self) -> None:
        if self._pump is not None and not self._pump.done():
            self._pump.cancel()
            try:
                await self._pump
            except (asyncio.CancelledError, Exception):  # noqa: BLE001  # brokerlint: ok=R4 teardown must swallow any transport error shape
                pass
        if self.writer is not None:
            try:
                self.writer.close()
            except (OSError, RuntimeError):
                pass

    # -- wire state machine -----------------------------------------------

    def _send(self, ptype: int, pid: int, qos: int = 0) -> None:
        assert self.writer is not None
        self.writer.write(
            encode_packet(
                Packet(
                    fixed_header=FixedHeader(type=ptype, qos=qos),
                    protocol_version=self.version,
                    packet_id=pid,
                )
            )
        )

    def _future(self, ptype: int, pid: int) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._acks[(ptype, pid)] = fut
        return fut

    def _resolve(self, ptype: int, pid: int, pk: Packet) -> None:
        fut = self._acks.pop((ptype, pid), None)
        if fut is not None and not fut.done():
            fut.set_result(pk)  # brokerlint: ok=R12 pump and submitters share the client's one lab loop

    async def _pump_loop(self) -> None:
        assert self.reader is not None
        try:
            while True:
                pk = await _read_packet(self.reader, self.version, 3600.0)
                t = pk.fixed_header.type
                if t == PUBLISH:
                    self._on_inbound_publish(pk)
                elif t in (PUBACK, PUBREC, PUBCOMP, SUBACK):
                    self._resolve(t, pk.packet_id, pk)
                elif t == PUBREL:
                    self.pubrel_seen.add(pk.packet_id)
                    self._incoming.discard(pk.packet_id)
                    if not self.withhold_pubcomp:
                        self._send(PUBCOMP, pk.packet_id)
                elif t == PINGRESP:
                    pass
        except (
            asyncio.CancelledError,
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            ConnectionError,
            OSError,
        ):
            return

    def _on_inbound_publish(self, pk: Packet) -> None:
        qos = pk.fixed_header.qos
        deliver = True
        if qos == 2:
            if pk.packet_id in self._incoming:
                deliver = False  # broker DUP redelivery of an open window
            else:
                self._incoming.add(pk.packet_id)
            self._send(PUBREC, pk.packet_id)
        elif qos == 1:
            self._send(PUBACK, pk.packet_id)
        if deliver and self.on_publish is not None:
            self.on_publish(pk.topic_name, bytes(pk.payload), pk)

    def next_pid(self) -> int:
        self._pid = self._pid % 65000 + 1
        return self._pid

    async def subscribe(self, flt: str, qos: int = 0) -> None:
        assert self.writer is not None
        pid = self.next_pid()
        fut = self._future(SUBACK, pid)
        self.writer.write(
            encode_packet(
                Packet(
                    fixed_header=FixedHeader(type=pkts.SUBSCRIBE, qos=1),
                    protocol_version=self.version,
                    packet_id=pid,
                    filters=[Subscription(filter=flt, qos=qos)],
                )
            )
        )
        await self.writer.drain()
        await asyncio.wait_for(fut, _IO_TIMEOUT)

    async def publish(
        self,
        topic: str,
        payload: bytes,
        qos: int = 0,
        retain: bool = False,
    ) -> None:
        """PUBLISH and run the ack cycle to completion: QoS1 waits for
        PUBACK; QoS2 waits PUBREC, sends PUBREL, waits PUBCOMP."""
        assert self.writer is not None
        pid = self.next_pid() if qos else 0
        rec = self._future(PUBREC, pid) if qos == 2 else None
        ack = self._future(PUBACK, pid) if qos == 1 else None
        self.writer.write(
            encode_packet(
                Packet(
                    fixed_header=FixedHeader(
                        type=PUBLISH, qos=qos, retain=retain
                    ),
                    protocol_version=self.version,
                    topic_name=topic,
                    packet_id=pid,
                    payload=payload,
                )
            )
        )
        await self.writer.drain()
        if ack is not None:
            await asyncio.wait_for(ack, _IO_TIMEOUT)
        if rec is not None:
            await asyncio.wait_for(rec, _IO_TIMEOUT)
            comp = self._future(PUBCOMP, pid)
            self._send(PUBREL, pid, qos=1)
            await self.writer.drain()
            await asyncio.wait_for(comp, _IO_TIMEOUT)


# -- run context + helpers ---------------------------------------------------


class ScenarioRun:
    """Mutable state one driver threads through: the seeded rng, the
    delivery oracle, driver metrics, structural ``require`` failures,
    and the SLO gate (armed on the scenario's broker, closed at
    ``settle``)."""

    def __init__(self, spec: ScenarioSpec, rng: random.Random) -> None:
        self.spec = spec
        self.rng = rng
        self.oracle = DeliveryOracle(spec.name)
        self.metrics: dict = {}
        self.failures: list[str] = []
        self._gate: Optional[ScenarioGate] = None
        self._slo_passed = True
        self._slo_rows: list = []

    def gate(self, server: Any) -> None:
        self._gate = ScenarioGate(server.telemetry, self.spec.objectives)

    def require(self, cond: bool, msg: str) -> None:
        if not cond:
            self.failures.append(msg)

    def settle(self, server: Any) -> dict:
        s = self.oracle.settle(server.telemetry.registry)
        if self._gate is not None:
            self._slo_passed, self._slo_rows = self._gate.verdict()
        return s

    def result(self, wall_s: float, seed_used: int) -> dict:
        s = self.oracle.summary()
        return {
            "scenario": self.spec.name,
            "title": self.spec.title,
            "seed": seed_used,
            "smoke": self.spec.smoke,
            "passed": self._slo_passed and not self.failures,
            "oracle": s,
            "slo": {"passed": self._slo_passed, "objectives": self._slo_rows},
            "failures": list(self.failures),
            "metrics": dict(self.metrics),
            "wall_s": round(wall_s, 3),
        }


async def _await_complete(
    oracle: DeliveryOracle, timeout: float = 20.0, grace: float = 0.15
) -> None:
    """Poll until every expected delivery landed (or timeout — the gap
    then shows in the settled counters), plus a short grace window so a
    late duplicate still gets counted."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if oracle.complete():
            break
        await asyncio.sleep(0.02)
    await asyncio.sleep(grace)


async def _wait_for(
    cond: Callable[[], bool], timeout: float = 10.0
) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        await asyncio.sleep(0.02)
    return False


def _body(tag: str, size: int, rng: random.Random) -> bytes:
    """A self-describing payload: ``tag:`` header, deterministic pad to
    ``size`` bytes (the oracle key parses back out of the prefix)."""
    head = (tag + ":").encode()
    if len(head) >= size:
        return head
    block = bytes(rng.getrandbits(8) for _ in range(64))
    pad = (block * (size // 64 + 1))[: size - len(head)]
    return head + pad


def _tag_of(payload: bytes) -> str:
    return payload.split(b":", 1)[0].decode("utf-8", "replace")


# -- scenario drivers --------------------------------------------------------


async def _drive_payload_sweep(run: ScenarioRun) -> None:
    """The payload ladder, 16B -> 1MB, through BOTH delivery paths: the
    encode-once plaintext fan-out and the per-subscriber recrypt path
    (client-side sealed publishes re-keyed to each subscriber). On the
    CPU backend the keystream serves from the vectorized host path
    (``recrypt_device_min_blocks`` pushed high, the bench.py default
    off-accelerator)."""
    from .server import Options

    p = run.spec.params
    sizes = list(p["sizes"])
    rc_sizes = list(p["recrypt_sizes"])
    msgs = int(p["msgs_per_size"])
    fanout = int(p["fanout"])

    def recorder(cid: str, opener: Optional[Callable[[bytes], bytes]] = None):
        def on_pub(topic: str, payload: bytes, pk: Packet) -> None:
            body = opener(payload) if opener is not None else payload
            tag = _tag_of(body)
            run.oracle.deliver((cid, tag, len(body)))

        return on_pub

    # leg 1: encode-once plaintext fan-out (the full ladder)
    b = await ScenarioBroker().start()
    clients: list[ScenarioClient] = []
    try:
        for i in range(fanout):
            c = ScenarioClient(b.port, f"swp-s{i}")
            await c.connect()
            c.on_publish = recorder(c.cid)
            await c.subscribe("sweep/#", qos=1)
            clients.append(c)
        pub = ScenarioClient(b.port, "swp-pub")
        await pub.connect()
        clients.append(pub)
        sent_bytes = 0
        for size in sizes:
            for i in range(msgs):
                body = _body(f"p{size}.{i}", size, run.rng)
                for c in clients[:fanout]:
                    run.oracle.expect((c.cid, f"p{size}.{i}", len(body)))
                await pub.publish(f"sweep/{size}", body, qos=1)
                sent_bytes += len(body)
        await _await_complete(run.oracle)
    finally:
        for c in clients:
            await c.close()
        await b.stop()

    # leg 2: the recrypt ladder on a tenancy broker — the gate arms here
    key_pub = bytes(run.rng.getrandbits(8) for _ in range(16))
    key_sub = [
        bytes(run.rng.getrandbits(8) for _ in range(16)) for _ in range(fanout)
    ]
    cids = [f"swp-e{i}" for i in range(fanout)]
    tenants = {
        "lab": {
            "encrypted": ["sealed/"],
            "keys": {
                "swp-epub": key_pub.hex(),
                **{c: k.hex() for c, k in zip(cids, key_sub)},
            },
        }
    }
    users = {c: "lab" for c in cids + ["swp-epub"]}
    b2 = await ScenarioBroker(
        Options(
            inline_client=False,
            tenancy=True,
            tenants=tenants,
            tenant_users=users,
            recrypt_device_min_blocks=1 << 30,
        )
    ).start()
    run.gate(b2.server)
    eng = b2.server._recrypt
    clients = []
    try:
        for i in range(fanout):
            c = ScenarioClient(b2.port, cids[i])
            await c.connect()
            key = key_sub[i]
            c.on_publish = recorder(
                c.cid, opener=lambda w, k=key: eng.open_with_key(k, w)
            )
            await c.subscribe("sealed/#", qos=1)
            clients.append(c)
        pub = ScenarioClient(b2.port, "swp-epub")
        await pub.connect()
        clients.append(pub)
        for size in rc_sizes:
            for i in range(msgs):
                body = _body(f"e{size}.{i}", size, run.rng)
                for cid in cids:
                    run.oracle.expect((cid, f"e{size}.{i}", len(body)))
                wire = eng.seal_with_key(key_pub, body)
                await pub.publish(f"sealed/{size}", wire, qos=1)
                sent_bytes += len(body)
        await _await_complete(run.oracle)
        run.require(
            eng.oracle_mismatches == 0,
            f"recrypt oracle mismatches: {eng.oracle_mismatches}",
        )
        run.metrics.update(
            {
                "sizes": len(sizes),
                "recrypt_sizes": len(rc_sizes),
                "max_payload_bytes": max(sizes),
                "sent_bytes": sent_bytes,
                "recrypt_fanouts": eng.fanouts,
            }
        )
        run.settle(b2.server)
    finally:
        for c in clients:
            await c.close()
        await b2.stop()


async def _drive_mixed_fleet(run: ScenarioRun) -> None:
    """The 1% chatty / 99% idle fleet: one publisher hammers a shared
    topic while the idle majority holds subscriptions open — the fan-out
    must stay exactly-once for every idle session."""
    p = run.spec.params
    idle = int(p["idle"])
    msgs = int(p["msgs"])
    size = int(p["payload"])

    b = await ScenarioBroker().start()
    run.gate(b.server)
    clients: list[ScenarioClient] = []
    try:
        for i in range(idle):
            c = ScenarioClient(b.port, f"mf-i{i}")
            await c.connect(keepalive=600)
            c.on_publish = (
                lambda topic, payload, pk, cid=c.cid: run.oracle.deliver(
                    (cid, _tag_of(payload))
                )
            )
            await c.subscribe("fleet/#", qos=1)
            clients.append(c)
        chatty = ScenarioClient(b.port, "mf-chatty")
        await chatty.connect()
        clients.append(chatty)
        t0 = time.perf_counter()
        for seq in range(msgs):
            body = _body(f"m{seq}", size, run.rng)
            for c in clients[:idle]:
                run.oracle.expect((c.cid, f"m{seq}"))
            await chatty.publish("fleet/chat", body, qos=1)
        await _await_complete(run.oracle)
        wall = time.perf_counter() - t0
        run.metrics.update(
            {
                "fleet": idle + 1,
                "msgs": msgs,
                "deliveries_per_sec": round(idle * msgs / max(wall, 1e-6)),
            }
        )
        run.settle(b.server)
    finally:
        for c in clients:
            await c.close()
        await b.stop()


async def _drive_qos2_fanout(run: ScenarioRun) -> None:
    """QoS2 exactly-once at fan-out, two legs:

    1. the wide leg — ``fanout`` QoS2 subscribers across a sharded
       front-end (``loop_shards``), every PUBREC/PUBREL/PUBCOMP cycle
       runs to completion, the broker-side inflight oracle must read 0;
    2. the kill -9 leg — durable sessions freeze mid-window (receivers
       withhold PUBCOMP), the store image is copied the way a crash
       leaves it, and the next broker life restores the windows through
       the batched inflight plane and finishes the cycle with ZERO
       repeat deliveries."""
    from .hooks.storage.logkv import LogKVOptions, LogKVStore
    from .server import Options

    p = run.spec.params
    fanout = int(p["fanout"])
    msgs = int(p["msgs"])
    shards = int(p["shards"])
    d_subs = int(p["durable_subs"])
    d_msgs = int(p["durable_msgs"])

    # -- leg 1: wide fan-out across loop shards ---------------------------
    b = await ScenarioBroker(
        Options(inline_client=False, loop_shards=shards)
    ).start()
    # the gate arms on the wide leg's broker and closes there too — the
    # oracle spans both legs, so settle() must hit the SAME registry the
    # engine snapshots (the registry outlives the closed server)
    run.gate(b.server)
    gate_server = b.server
    clients: list[ScenarioClient] = []
    try:
        for i in range(fanout):
            c = ScenarioClient(b.port, f"q2-s{i}")
            await c.connect(keepalive=600)
            c.on_publish = (
                lambda topic, payload, pk, cid=c.cid: run.oracle.deliver(
                    (cid, _tag_of(payload))
                )
            )
            await c.subscribe("q2/t", qos=2)
            clients.append(c)
        pub = ScenarioClient(b.port, "q2-pub")
        await pub.connect()
        clients.append(pub)
        t0 = time.perf_counter()
        for seq in range(msgs):
            for c in clients[:fanout]:
                run.oracle.expect((c.cid, f"q{seq}"))
            await pub.publish("q2/t", _body(f"q{seq}", 96, run.rng), qos=2)
        await _await_complete(run.oracle)
        drained = await _wait_for(lambda: b.total_inflight() == 0)
        run.require(
            drained, f"inflight windows not drained: {b.total_inflight()}"
        )
        run.metrics.update(
            {
                "fanout": fanout,
                "qos2_deliveries": fanout * msgs,
                "qos2_deliveries_per_sec": round(
                    fanout * msgs / max(time.perf_counter() - t0, 1e-6)
                ),
            }
        )
    finally:
        for c in clients:
            await c.close()
        await b.stop()

    # -- leg 2: kill -9 mid-window, resume through the restored plane -----
    tmp = tempfile.mkdtemp(prefix="scn-q2-")  # brokerlint: ok=R11 lab harness setup on the lab's own loop, no broker traffic yet
    path = tmp + "/kv"
    crash = tmp + "/kv-crash-image"
    try:
        b1 = ScenarioBroker(Options(inline_client=False))
        store = LogKVStore()
        b1.server.add_hook(store, LogKVOptions(path=path, gc_interval=0))
        await b1.start()
        subs: list[ScenarioClient] = []
        try:
            for i in range(d_subs):
                c = ScenarioClient(b1.port, f"dq2-{i}")
                await c.connect(clean=False, keepalive=600)
                c.withhold_pubcomp = True
                c.on_publish = (
                    lambda topic, payload, pk, cid=c.cid: run.oracle.deliver(
                        (cid, _tag_of(payload))
                    )
                )
                await c.subscribe("dur/q2", qos=2)
                subs.append(c)
            pub = ScenarioClient(b1.port, "dq2-pub")
            await pub.connect()
            for seq in range(d_msgs):
                for c in subs:
                    run.oracle.expect((c.cid, f"d{seq}"))
                await pub.publish(
                    "dur/q2", _body(f"d{seq}", 64, run.rng), qos=2
                )
            # every receiver has PUBREC'd and seen PUBREL; the withheld
            # PUBCOMP pins the broker-side window open
            froze = await _wait_for(
                lambda: all(len(c.pubrel_seen) >= d_msgs for c in subs)
            )
            run.require(froze, "QoS2 windows never reached PUBREL")
            store.sync()  # brokerlint: ok=R11 the freeze IS the scenario: traffic is withheld while the crash image is cut
            shutil.copytree(path, crash)  # the kill -9 freeze-frame
            await pub.close()
        finally:
            for c in subs:
                c.abort()
                await c.close()
            await b1.stop()
            store.stop()

        b2 = ScenarioBroker(Options(inline_client=False))
        b2.server.add_hook(
            LogKVStore(), LogKVOptions(path=crash, gc_interval=0)
        )
        await b2.start()  # serve() replays the crash image (read_store)
        restored = b2.server._durable["restored_inflight"]
        run.require(
            restored >= d_subs * d_msgs,
            f"restored_inflight {restored} < {d_subs * d_msgs}",
        )
        subs2: list[ScenarioClient] = []
        try:
            for i in range(d_subs):
                c = ScenarioClient(b2.port, f"dq2-{i}")
                present = await c.connect(clean=False, keepalive=600)
                run.require(
                    present, f"{c.cid}: no session-present on resume"
                )
                # any repeat PUBLISH here is an exactly-once violation:
                # the oracle already holds life 1's deliveries
                c.on_publish = (
                    lambda topic, payload, pk, cid=c.cid: run.oracle.deliver(
                        (cid, _tag_of(payload))
                    )
                )
                subs2.append(c)
            completed = await _wait_for(
                lambda: all(len(c.pubrel_seen) >= d_msgs for c in subs2)
            )
            run.require(
                completed, "resumed QoS2 windows never re-sent PUBREL"
            )
            drained = await _wait_for(lambda: b2.total_inflight() == 0)
            run.require(
                drained,
                f"restored windows not drained: {b2.total_inflight()}",
            )
            run.metrics["restored_inflight"] = restored
            run.settle(gate_server)
        finally:
            for c in subs2:
                await c.close()
            await b2.stop()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)  # brokerlint: ok=R11 lab teardown, both broker lives already closed


async def _drive_will_storm(run: ScenarioRun) -> None:
    """The will-message storm: a seeded mass disconnect
    (``faults.drop_fleet``) rips ``victims`` transports out in one tick
    — every victim's will must fire (delayed wills after their interval)
    while the control groups stay silent: clean DISCONNECTs and session
    takeovers must NOT leak a will."""
    from .faults import drop_fleet

    p = run.spec.params
    fleet_n = int(p["fleet"])
    victims_n = int(p["victims"])
    delayed_n = int(p["delayed"])
    leavers_n = int(p["clean_leavers"])
    delay_s = int(p["will_delay_s"])

    b = await ScenarioBroker().start()
    run.gate(b.server)
    watcher = ScenarioClient(b.port, "will-watch")
    fleet: list[ScenarioClient] = []
    extra: list[ScenarioClient] = []
    try:
        await watcher.connect(keepalive=600)
        watcher.on_publish = lambda topic, payload, pk: run.oracle.deliver(
            ("will", topic)
        )
        await watcher.subscribe("wills/#", qos=1)

        for i in range(fleet_n):
            c = ScenarioClient(b.port, f"wf-{i}", version=5)
            await c.connect(
                keepalive=600,
                will=(f"wills/w{i}", c.cid.encode(), 1, False),
                will_delay=delay_s if i < delayed_n else 0,
            )
            fleet.append(c)

        # control group 1: clean leavers — DISCONNECT suppresses the will
        for i in range(leavers_n):
            c = ScenarioClient(b.port, f"wl-{i}", version=5)
            await c.connect(will=(f"wills/l{i}", b"leak", 1, False))
            await c.disconnect()

        # control group 2: session takeover — the second CONNECT on the
        # same id must not fire the first incarnation's will
        tk1 = ScenarioClient(b.port, "wt-0", version=5)
        await tk1.connect(will=("wills/t0", b"leak", 1, False))
        tk2 = ScenarioClient(b.port, "wt-0", version=5)
        await tk2.connect(will=("wills/t0", b"leak", 1, False))
        extra.extend([tk1, tk2])

        victims = drop_fleet(
            [c.writer for c in fleet], victims_n, run.rng.randrange(1 << 30)
        )
        run.oracle.fault(len(victims))
        for i in victims:
            run.oracle.expect(("will", f"wills/w{i}"))
        await _await_complete(
            run.oracle, timeout=delay_s + 8.0, grace=0.5
        )
        run.metrics.update(
            {
                "fleet": fleet_n,
                "victims": len(victims),
                "delayed_wills": sum(1 for i in victims if i < delayed_n),
            }
        )
        run.settle(b.server)
    finally:
        for c in [watcher, *fleet, *extra]:
            await c.close()
        await b.stop()


async def _drive_bridge_federation(run: ScenarioRun) -> None:
    """The 3-worker bridge topology: three in-process brokers joined by
    the cluster fabric, publishers on two workers, the subscriber on the
    third — every cross-worker delivery exactly once, zero forwards
    dropped."""
    from .cluster import Cluster

    p = run.spec.params
    workers = int(p["workers"])
    msgs = int(p["msgs_per_publisher"])

    sockdir = tempfile.mkdtemp(prefix="scn-fed-")  # brokerlint: ok=R11 lab harness setup on the lab's own loop, no broker traffic yet
    brokers: list[ScenarioBroker] = []
    clusters: list[Cluster] = []
    clients: list[ScenarioClient] = []
    try:
        for i in range(workers):
            brokers.append(
                await ScenarioBroker(listener_id=f"fed{i}").start()
            )
        for i, br in enumerate(brokers):
            c = Cluster(br.server, i, workers, sockdir)
            clusters.append(c)
            await c.start()
        meshed = await _wait_for(
            lambda: all(c.peer_count == workers - 1 for c in clusters)
        )
        run.require(meshed, "cluster peers never fully meshed")
        run.gate(brokers[-1].server)

        sub = ScenarioClient(brokers[-1].port, "fed-sub")
        await sub.connect(keepalive=600)
        sub.on_publish = lambda topic, payload, pk: run.oracle.deliver(
            _tag_of(payload)
        )
        await sub.subscribe("fed/#", qos=1)
        clients.append(sub)
        # the publishers' workers must see the subscriber's interest
        # before traffic starts (presence gossip, not a barrier)
        seen = await _wait_for(
            lambda: all(
                (workers - 1) in c._interested_peers("fed/x")
                for c in clusters[: workers - 1]
            )
        )
        run.require(seen, "subscriber presence never reached publishers")

        pubs = []
        for w in range(workers - 1):
            pc = ScenarioClient(brokers[w].port, f"fed-pub{w}")
            await pc.connect()
            pubs.append(pc)
            clients.append(pc)
        for seq in range(msgs):
            for w, pc in enumerate(pubs):
                run.oracle.expect(f"w{w}.{seq}")
                await pc.publish(
                    f"fed/w{w}", _body(f"w{w}.{seq}", 96, run.rng), qos=1
                )
        await _await_complete(run.oracle)
        dropped = sum(c.dropped_forwards for c in clusters)
        run.require(dropped == 0, f"{dropped} forwards dropped")
        run.metrics.update(
            {
                "workers": workers,
                "cross_worker_msgs": msgs * (workers - 1),
                "dropped_forwards": dropped,
            }
        )
        run.settle(brokers[-1].server)
    finally:
        for c in clients:
            await c.close()
        for c in clusters:
            await c.stop()
        for br in brokers:
            await br.stop()
        shutil.rmtree(sockdir, ignore_errors=True)  # brokerlint: ok=R11 lab teardown, all workers already closed


async def _drive_tenant_rekey(run: ScenarioRun) -> None:
    """The tentpole oracle: LIVE tenant re-key under sustained publish
    load with zero delivery gaps and zero old-key leaks.

    Protocol under test (README "Scenario lab"): the publisher stamps
    every nonce with the epoch tag it seals under
    (``tenancy.epoch_tag_nonce`` — inert pre-rotation); the broker
    stages the new generation, announces ``distributing`` on
    ``$SYS/broker/tenant/rekey``, re-seals retained ciphertext in
    batched dispatches, activates (``active`` notice carries the new
    epoch), and the publisher switches keys on that notice. In-flight
    old-epoch publishes keep decrypting through the drain; after
    ``retire_tenant_epoch`` they drop as stale and every delivery must
    carry the new epoch's tag."""
    from .server import Options
    from .tenancy import epoch_tag_nonce, nonce_epoch

    p = run.spec.params
    msgs = int(p["msgs"])
    rekey_at = int(p["rekey_at"])
    post_retire = int(p["post_retire_msgs"])
    stale_sends = int(p["stale_sends"])
    size = int(p["payload"])

    k0_pub = bytes(run.rng.getrandbits(8) for _ in range(16))
    k0_sub = bytes(run.rng.getrandbits(8) for _ in range(16))
    k1_pub = bytes(run.rng.getrandbits(8) for _ in range(16))
    k1_sub = bytes(run.rng.getrandbits(8) for _ in range(16))

    b = await ScenarioBroker(
        Options(
            inline_client=False,
            tenancy=True,
            tenants={
                "flt": {
                    "encrypted": ["sealed/"],
                    "keys": {"rk-pub": k0_pub.hex(), "rk-sub": k0_sub.hex()},
                }
            },
            tenant_users={"rk-pub": "flt", "rk-sub": "flt"},
            recrypt_device_min_blocks=1 << 30,
        )
    ).start()
    run.gate(b.server)
    eng = b.server._recrypt
    sub_keys = {0: k0_sub, 1: k1_sub}
    epochs_seen: dict[int, Optional[int]] = {}
    retained_seen: list[Optional[int]] = []
    notices: list[dict] = []
    sub = ScenarioClient(b.port, "rk-sub")
    pub = ScenarioClient(b.port, "rk-pub")
    try:
        await sub.connect(keepalive=600)

        def on_sub(topic: str, payload: bytes, pk: Packet) -> None:
            epoch = nonce_epoch(payload[: eng.nonce_bytes])
            key = sub_keys.get(epoch if epoch is not None else 0)
            if key is None:
                return
            body = eng.open_with_key(key, payload)
            tag = _tag_of(body)
            if tag == "ret":
                retained_seen.append(epoch)
                return
            try:
                seq = int(tag[1:])
            except ValueError:
                return
            epochs_seen[seq] = epoch
            run.oracle.deliver(("seq", seq))

        sub.on_publish = on_sub
        await sub.subscribe("sealed/data", qos=1)

        await pub.connect(keepalive=600)
        pub.on_publish = lambda topic, payload, pk: notices.append(
            json.loads(payload)
        )
        await pub.subscribe("$SYS/broker/tenant/rekey", qos=0)

        # seal state the background publisher reads each tick: the
        # epoch tag is stamped from the START — inert before rotation,
        # the drain discriminator after it
        seal = {"key": k0_pub, "epoch": 0}

        async def publish_seq(seq: int) -> None:
            body = _body(f"s{seq}", size, run.rng)
            nonce = epoch_tag_nonce(eng.next_nonce(), seal["epoch"])
            wire = eng.seal_with_key(seal["key"], body, nonce=nonce)
            run.oracle.expect(("seq", seq))
            await pub.publish("sealed/data", wire, qos=1)

        # retained row pre-rotation (re-sealed across the rekey)
        ret_wire = eng.seal_with_key(
            k0_pub,
            _body("ret", size, run.rng),
            nonce=epoch_tag_nonce(eng.next_nonce(), 0),
        )
        await pub.publish("sealed/retained", ret_wire, qos=1, retain=True)

        for seq in range(rekey_at):
            await publish_seq(seq)

        # sustained load through the rotation
        done = asyncio.Event()

        async def pump_load() -> None:
            for seq in range(rekey_at, msgs):
                await publish_seq(seq)
                await asyncio.sleep(0.003)
            done.set()

        load = asyncio.get_running_loop().create_task(pump_load())
        await asyncio.sleep(0.02)
        res = b.server.rekey_tenant(
            "flt", {"rk-pub": k1_pub, "rk-sub": k1_sub}
        )
        # the publisher switches keys the way a real client would: on
        # the $SYS "active" notice, not on a side channel
        switched = await _wait_for(
            lambda: any(n.get("state") == "active" for n in notices)
        )
        run.require(switched, "no 'active' rekey notice observed")
        seal["key"] = k1_pub
        seal["epoch"] = res["epoch"]
        await done.wait()
        await load
        await _await_complete(run.oracle)

        # drain is complete: retire the old generation
        b.server.retire_tenant_epoch("flt", res["old_epoch"])
        retired = await _wait_for(
            lambda: any(n.get("state") == "retired" for n in notices)
        )
        run.require(retired, "no 'retired' rekey notice observed")

        # stale leg: old-epoch publishes past retirement must DROP
        stale_before = eng.stale_epoch_drops
        for i in range(stale_sends):
            body = _body(f"x{i}", size, run.rng)
            nonce = epoch_tag_nonce(eng.next_nonce(), 0)
            await pub.publish(
                "sealed/data", eng.seal_with_key(k0_pub, body, nonce=nonce),
                qos=1,
            )
            run.oracle.fault()
        dropped = await _wait_for(
            lambda: eng.stale_epoch_drops - stale_before >= stale_sends,
            timeout=5.0,
        )
        run.require(dropped, "stale old-epoch publishes were not dropped")

        # post-retirement traffic: every delivery must carry the new tag
        for seq in range(msgs, msgs + post_retire):
            await publish_seq(seq)
        await _await_complete(run.oracle)

        # retained survived the rotation re-sealed: a fresh subscription
        # decrypts it under the NEW generation
        await sub.subscribe("sealed/retained", qos=1)
        got_ret = await _wait_for(lambda: len(retained_seen) > 0)
        run.require(got_ret, "re-sealed retained message never delivered")
        run.require(
            all(e == res["epoch"] for e in retained_seen),
            f"retained delivered under epochs {retained_seen}",
        )
        run.require(res["resealed"] >= 1, "no retained payloads re-sealed")

        leaks = sum(
            1
            for seq, e in epochs_seen.items()
            if seq >= msgs and e != res["epoch"]
        )
        run.require(leaks == 0, f"{leaks} post-retirement old-key leaks")
        run.require(
            eng.oracle_mismatches == 0,
            f"recrypt oracle mismatches: {eng.oracle_mismatches}",
        )
        run.metrics.update(
            {
                "msgs": msgs + post_retire,
                "epoch": res["epoch"],
                "resealed": res["resealed"],
                "stale_drops": eng.stale_epoch_drops,
                "old_key_leaks": leaks,
                "rekeys": eng.rekeys,
            }
        )
        run.settle(b.server)
    finally:
        await sub.close()
        await pub.close()
        await b.stop()


# -- the catalog -------------------------------------------------------------

_GAP = "scenario_gap ratio < 0.1% over 5s"
_DUP = "scenario_dup ratio < 0.1% over 5s"

SCENARIOS: dict[str, ScenarioSpec] = {
    s.name: s
    for s in (
        ScenarioSpec(
            name="payload_sweep",
            title="payload ladder 16B-1MB, encode-once + recrypt paths",
            seed=101,
            objectives=(_GAP, _DUP),
            params={
                "sizes": (16, 256, 4096, 65536, 1 << 20),
                "recrypt_sizes": (16, 256, 4096, 65536),
                "msgs_per_size": 2,
                "fanout": 2,
            },
            smoke=True,
        ),
        ScenarioSpec(
            name="mixed_fleet",
            title="1% chatty / 99% idle fleet, exactly-once fan-out",
            seed=102,
            objectives=(_GAP, _DUP),
            params={"idle": 99, "msgs": 60, "payload": 240},
            smoke=True,
        ),
        ScenarioSpec(
            name="qos2_fanout",
            title="QoS2 exactly-once at 100-sub fan-out + kill -9 resume",
            seed=103,
            objectives=(_GAP, _DUP),
            params={
                "fanout": 100,
                "msgs": 5,
                "shards": 2,
                "durable_subs": 8,
                "durable_msgs": 2,
            },
        ),
        ScenarioSpec(
            name="will_storm",
            title="will storm on seeded mass disconnect, delay + takeover",
            seed=104,
            # small expected counts: one leaked or lost will must trip
            objectives=(
                "scenario_gap ratio < 1% over 5s",
                "scenario_dup ratio < 1% over 5s",
            ),
            params={
                "fleet": 40,
                "victims": 30,
                "delayed": 8,
                "clean_leavers": 10,
                "will_delay_s": 1,
            },
        ),
        ScenarioSpec(
            name="bridge_federation",
            title="3-worker bridge topology, cross-worker exactly-once",
            seed=105,
            objectives=(_GAP, _DUP),
            params={"workers": 3, "msgs_per_publisher": 40},
        ),
        ScenarioSpec(
            name="tenant_rekey",
            title="live tenant re-key: zero gaps, zero old-key leaks",
            seed=106,
            objectives=(
                _GAP,
                _DUP,
                "rekey_stale ratio < 5% over 5s",
            ),
            params={
                "msgs": 120,
                "rekey_at": 30,
                "post_retire_msgs": 10,
                "stale_sends": 2,
                "payload": 160,
            },
        ),
    )
}

_DRIVERS: dict[str, Callable[[ScenarioRun], Awaitable[None]]] = {
    "payload_sweep": _drive_payload_sweep,
    "mixed_fleet": _drive_mixed_fleet,
    "qos2_fanout": _drive_qos2_fanout,
    "will_storm": _drive_will_storm,
    "bridge_federation": _drive_bridge_federation,
    "tenant_rekey": _drive_tenant_rekey,
}


def scenario_names(smoke_only: bool = False) -> list[str]:
    return [
        n for n, s in SCENARIOS.items() if s.smoke or not smoke_only
    ]


def run_scenario(name: str, seed: Optional[int] = None) -> dict:
    """Execute one catalog scenario end to end; returns the result
    document (oracle tallies, SLO verdict, driver metrics). Raises
    KeyError for an unknown name — the lab CLI lists the catalog."""
    spec = SCENARIOS[name]
    seed_used = spec.seed if seed is None else seed
    rng = random.Random(seed_used)
    run = ScenarioRun(spec, rng)
    t0 = time.perf_counter()
    asyncio.run(
        asyncio.wait_for(_DRIVERS[name](run), timeout=RUN_TIMEOUT_S)
    )
    return run.result(time.perf_counter() - t0, seed_used)


def run_matrix(
    names: Optional[list[str]] = None,
    smoke_only: bool = False,
    seed: Optional[int] = None,
) -> list[dict]:
    """Run a set of scenarios (default: the whole catalog, or the smoke
    rows) sequentially; a crashed driver records as a failed run rather
    than aborting the matrix."""
    out = []
    for name in names if names is not None else scenario_names(smoke_only):
        try:
            out.append(run_scenario(name, seed=seed))
        except Exception as e:  # noqa: BLE001  # brokerlint: ok=R4 one crashed scenario must not sink the matrix; the failure IS the result
            spec = SCENARIOS.get(name)
            out.append(
                {
                    "scenario": name,
                    "title": spec.title if spec else "",
                    "seed": seed if seed is not None else (
                        spec.seed if spec else 0
                    ),
                    "smoke": bool(spec and spec.smoke),
                    "passed": False,
                    "oracle": {},
                    "slo": {"passed": False, "objectives": []},
                    "failures": [f"driver crashed: {e!r}"],
                    "metrics": {},
                    "wall_s": 0.0,
                }
            )
    return out
