"""Unix-domain socket listener.

Behavioral parity with reference ``listeners/unixsock.go:19-102``: removes a
stale socket file before binding.
"""

from __future__ import annotations

import asyncio
import logging
import os
import socket
from typing import Callable

from . import Config, StreamListener


class UnixSock(StreamListener):
    def protocol(self) -> str:
        return "unix"

    def address(self) -> str:
        return self.config.address

    def _fabric_bind(self) -> list:
        # hand-off only: SO_REUSEPORT has no unix-socket meaning
        self._fabric_reuseport = False
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            sock.bind(self.config.address)
            sock.listen(1024)
            sock.setblocking(False)
        except OSError:
            sock.close()
            raise
        return [sock]

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        try:
            # brokerlint: ok=R11 one-time stale-socket removal during init, before the listener accepts (unixsock.go:58)
            os.unlink(self.config.address)
        except FileNotFoundError:
            pass
        if self._fabric is not None:
            self._lsocks = self._fabric_bind()
            return
        self._server = await asyncio.start_unix_server(
            self._on_connection, path=self.config.address
        )

    async def close(self, close_clients: Callable[[str], None]) -> None:
        await super().close(close_clients)
        try:
            # brokerlint: ok=R11 teardown-path unlink after clients are closed; the listener no longer serves
            os.unlink(self.config.address)
        except OSError:
            pass
