"""Mock listener test double with serving/listening introspection.

Behavioral parity with reference ``listeners/mock.go:26-105``.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional

from . import Config, EstablishFn, Listener


class MockListener(Listener):
    """A do-nothing listener exposing its lifecycle flags for tests."""

    def __init__(self, id_: str, address: str) -> None:
        super().__init__(Config(type="mock", id=id_, address=address))
        self.is_listening = False
        self.is_serving = False
        self.err_listen: Optional[Exception] = None
        self.establish: Optional[EstablishFn] = None

    def protocol(self) -> str:
        return "mock"

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        if self.err_listen is not None:
            raise self.err_listen
        self.is_listening = True

    async def serve(self, establish: EstablishFn) -> None:
        self.establish = establish
        self.is_serving = True

    async def close(self, close_clients: Callable[[str], None]) -> None:
        self.is_serving = False
        self.is_listening = False
        close_clients(self.id())
