"""HTTP utility listeners: healthcheck and $SYS stats.

Behavioral parity with reference ``listeners/http_healthcheck.go:19-99``
(200-OK on GET /healthcheck) and ``listeners/http_sysinfo.go:23-121``
(JSON dump of system.Info). Implemented as minimal asyncio HTTP/1.1
responders — no framework dependency.
"""

from __future__ import annotations

import asyncio
import base64
import collections
import hmac
import json
import logging
import threading
import time
from html import escape

from ..system import Info
from ..utils.proc import cpu_seconds, rss_bytes
from . import Config, EstablishFn, StreamListener, split_host_port


class _HttpListener(StreamListener):
    """Shared accept loop for the single-purpose HTTP listeners."""

    def protocol(self) -> str:
        return "https" if self.config.tls_config else "http"

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        host, port = split_host_port(self.config.address)
        self._server = await asyncio.start_server(
            self._on_connection, host, port, ssl=self.config.tls_config
        )

    async def serve(self, establish: EstablishFn) -> None:
        pass  # HTTP listeners never establish MQTT clients

    async def _on_connection(self, reader, writer):  # overrides StreamListener
        try:
            request = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 10)
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            parts = line.split(" ")
            method, path = (parts + ["", ""])[:2]
            if not self._authorized(request):
                writer.write(
                    b"HTTP/1.1 401 Unauthorized\r\n"
                    b'WWW-Authenticate: Basic realm="mqtt_tpu"\r\n'
                    b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                )
            else:
                extra = ""
                try:
                    resp = self._respond(method, path)
                    # handlers may append a dict of extra headers
                    # (Cache-Control on stats/metrics, Allow on 405s)
                    if len(resp) == 4:
                        status, body, ctype, headers = resp
                        extra = "".join(f"{k}: {v}\r\n" for k, v in headers.items())
                    else:
                        status, body, ctype = resp
                except Exception:
                    self.log.exception("http handler failed: path=%s", path)
                    status, body, ctype = "500 Internal Server Error", b"", "text/plain"
                writer.write(
                    (
                        f"HTTP/1.1 {status}\r\n"
                        f"Content-Type: {ctype}\r\n"
                        f"Content-Length: {len(body)}\r\n"
                        f"{extra}"
                        "Connection: close\r\n\r\n"
                    ).encode()
                    + body
                )
            await writer.drain()
        except Exception:  # brokerlint: ok=R4 client hung up mid-response; nothing to serve and nothing to log per-scrape
            pass
        finally:
            try:
                writer.close()
            except Exception:  # brokerlint: ok=R4 teardown; the transport is already gone
                pass

    def _authorized(self, request: bytes) -> bool:
        return True

    def _respond(self, method: str, path: str):
        raise NotImplementedError

    @staticmethod
    def _method_not_allowed():
        """405 for a KNOWN path hit with a non-GET method; unknown paths
        stay 404 whatever the method (RFC 9110 §15.5.6 semantics)."""
        return "405 Method Not Allowed", b"", "text/plain", {"Allow": "GET"}


# point-in-time responses must never be served from a cache
_NO_STORE = {"Cache-Control": "no-store"}


class HTTPHealthCheck(_HttpListener):
    """Responds 200 OK to GET /healthcheck (http_healthcheck.go:59-63)."""

    def _respond(self, method: str, path: str):
        if path == "/healthcheck":
            if method != "GET":
                return self._method_not_allowed()
            return "200 OK", b"", "text/plain"
        return "404 Not Found", b"", "text/plain"


class HTTPStats(_HttpListener):
    """Serves the $SYS info values as JSON (http_sysinfo.go:112-121) and,
    when a telemetry plane is attached (mqtt_tpu.telemetry), its
    Prometheus text exposition at ``GET /metrics``, the trace plane's
    Chrome trace-event export at ``GET /traces`` (mqtt_tpu.tracing;
    load the body straight into Perfetto), and the host profiler's
    exports at ``GET /profile`` (mqtt_tpu.profiling) — collapsed
    flamegraph text by default, ``?format=trace`` for the
    Perfetto-loadable flame chart — and the per-device observability
    snapshot at ``GET /devices`` (mqtt_tpu.ops.devicestats: HBM, duty
    cycles, shard skew, compile ledger).

    Cluster-wide SLO observatory surfaces (ISSUE 14): ``GET
    /metrics/cluster`` renders the mesh-federated per-worker + folded
    exposition (telemetry.ClusterMetrics — the tree root serves the
    whole mesh), ``GET /cluster/slo`` the mesh-wide objective state
    (local SLOEngine + federated slo gauges), and ``GET /healthz`` the
    readiness probe (``health`` is the server's health_report; 200 when
    ready, 503 with the failing components named when not)."""

    def __init__(
        self, config: Config, sys_info: Info, telemetry=None, health=None
    ) -> None:
        super().__init__(config)
        self.sys_info = sys_info
        self.telemetry = telemetry
        self.health = health

    def _respond(self, method: str, path: str):
        # known paths match on the bare path; the query string only
        # selects an export format (/profile?format=trace)
        path, _, query = path.partition("?")
        if path == "/healthz":
            if self.health is None:  # no server wired (bare listener)
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            ok, detail = self.health()
            body = json.dumps(detail, indent=1).encode()
            status = "200 OK" if ok else "503 Service Unavailable"
            return status, body, "application/json", _NO_STORE
        if path == "/metrics/cluster":
            cm = getattr(self.telemetry, "cluster_metrics", None)
            if cm is None:  # telemetry off, or federation disabled
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            body = cm.exposition(
                self.telemetry.registry,
                str(getattr(self.telemetry, "local_worker", "0")),
            ).encode()
            return (
                "200 OK",
                body,
                "text/plain; version=0.0.4; charset=utf-8",
                _NO_STORE,
            )
        if path == "/cluster/slo":
            cm = getattr(self.telemetry, "cluster_metrics", None)
            engine = getattr(self.telemetry, "slo", None)
            if cm is None and engine is None:
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            out = {
                "local": engine.state() if engine is not None else {},
                "workers": (
                    cm.slo_state(
                        self.telemetry.registry,
                        str(getattr(self.telemetry, "local_worker", "0")),
                    )
                    if cm is not None
                    else {}
                ),
            }
            body = json.dumps(out, indent=1).encode()
            return "200 OK", body, "application/json", _NO_STORE
        if path == "/devices":
            plane = getattr(self.telemetry, "device_stats", None)
            if plane is None:  # telemetry off, or the device plane disabled
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            body = json.dumps(plane.snapshot(), indent=1).encode()
            return "200 OK", body, "application/json", _NO_STORE
        if path == "/profile":
            profiler = getattr(self.telemetry, "host_profiler", None)
            if profiler is None:  # telemetry off, or the profiler disabled
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            if "format=trace" in query:
                body = json.dumps(profiler.trace_events()).encode()
                return "200 OK", body, "application/json", _NO_STORE
            body = profiler.collapsed().encode()
            return "200 OK", body, "text/plain; charset=utf-8", _NO_STORE
        if path == "/metrics":
            if self.telemetry is None:
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            body = self.telemetry.exposition().encode()
            return "200 OK", body, "text/plain; version=0.0.4; charset=utf-8", _NO_STORE
        if path == "/traces":
            tracer = getattr(self.telemetry, "tracer", None)
            if tracer is None:  # telemetry off, or tracing disabled
                return "404 Not Found", b"", "text/plain"
            if method != "GET":
                return self._method_not_allowed()
            body = tracer.export_json().encode()
            return "200 OK", body, "application/json", _NO_STORE
        if method != "GET":
            return self._method_not_allowed()
        body = json.dumps(self.sys_info.clone().as_dict()).encode()
        return "200 OK", body, "application/json", _NO_STORE


class Dashboard(_HttpListener):
    """The fork CLI's basic-auth'd status dashboard
    (cmd/server/listener.go:182-358): ``/information`` (indented $SYS JSON),
    ``/connections`` (HTML client table), ``/clientsrawdata`` (per-client
    JSON), ``/processrecords`` (periodic process snapshots).

    ``auth`` maps username -> password for HTTP basic auth; an empty map
    disables the check. The process recorder samples lazily, at most once
    per ``record_interval`` seconds (the reference records on a 60s timer).
    """

    def __init__(
        self,
        config: Config,
        sys_info: Info,
        clients,
        auth: dict[str, str] | None = None,
        listener_summary: str = "",
        record_interval: float = 60.0,
        max_records: int = 7 * 24 * 60,  # reference keeps 7 days of minutes
    ) -> None:
        super().__init__(config)
        self.sys_info = sys_info
        self.clients = clients
        self.auth = auth or {}
        self.listener_summary = listener_summary
        self.record_interval = record_interval
        self._records: collections.deque = collections.deque(maxlen=max_records)
        self._last_record = 0.0

    # -- process recorder ---------------------------------------------------

    def _maybe_record(self) -> None:
        # interval gating is MONOTONIC (brokerlint R3): an NTP step must
        # not stall or burst the recorder; only the record's own
        # timestamp is wall-clock (operators correlate it with logs)
        now = time.monotonic()
        if now - self._last_record < self.record_interval and self._records:
            return
        self._last_record = now
        self._records.append(
            {
                "time": int(time.time()),  # brokerlint: ok=R3 record timestamp is wall-clock by design
                "rss_bytes": rss_bytes(),
                "cpu_seconds": round(cpu_seconds(), 3),
                "threads": threading.active_count(),
                "clients_connected": self.sys_info.clients_connected,
                "messages_received": self.sys_info.messages_received,
                "messages_sent": self.sys_info.messages_sent,
            }
        )

    # -- request handling ---------------------------------------------------

    def _authorized(self, request: bytes) -> bool:
        if not self.auth:
            return True
        for line in request.split(b"\r\n"):
            if line.lower().startswith(b"authorization: basic "):
                try:
                    userpass = base64.b64decode(line.split(b" ", 2)[2]).decode()
                    user, _, pwd = userpass.partition(":")
                except Exception:
                    return False
                # membership must be explicit: a missing user must NOT fall
                # through to comparing against "" (which would authorize any
                # username with an empty password); bytes also keep
                # compare_digest safe for non-ASCII credentials
                expected = self.auth.get(user)
                return (
                    expected is not None
                    and expected != ""
                    and hmac.compare_digest(expected.encode(), pwd.encode())
                )
        return False

    def _client_rows(self) -> tuple[list[list[str]], dict[str, int]]:
        rows = []
        counts: dict[str, int] = {}
        for cl in self.clients.get_all().values():
            if cl.net.listener == "local" or cl.id == "inline":
                continue
            filters = sorted(cl.state.subscriptions.get_all())
            username = (
                cl.properties.username.decode("utf-8", "replace")
                if isinstance(cl.properties.username, (bytes, bytearray))
                else str(cl.properties.username)
            )
            rows.append(
                [
                    username,
                    cl.id,
                    str(cl.net.remote),
                    str(cl.properties.protocol_version),
                    cl.net.listener,
                    str(len(filters)),
                    "\n".join(filters),
                ]
            )
            counts[cl.net.listener] = counts.get(cl.net.listener, 0) + 1
        rows.sort(key=lambda r: r[0] + r[1])
        return rows, counts

    KNOWN_PATHS = ("/information", "/clientsrawdata", "/processrecords", "/connections")

    def _respond(self, method: str, path: str):
        if path not in self.KNOWN_PATHS:
            return "404 Not Found", b"", "text/plain"
        if method != "GET":
            return self._method_not_allowed()
        self._maybe_record()
        if path == "/information":
            body = json.dumps(self.sys_info.clone().as_dict(), indent=2).encode()
            return "200 OK", body, "application/json", _NO_STORE
        if path == "/clientsrawdata":
            out = [
                {
                    "id": cl.id,
                    "remote": cl.net.remote,
                    "listener": cl.net.listener,
                    "protocol_version": cl.properties.protocol_version,
                    "clean_session": cl.properties.clean,
                    "subscriptions": sorted(cl.state.subscriptions.get_all()),
                    "inflight": len(cl.state.inflight),
                    "done": cl.closed,
                    # per-client write-path accounting (mqtt_tpu.profiling):
                    # the client-level face of outbound_{bytes,writes}_total
                    "outbound_queue_depth": cl.state.outbound_qty,
                    "outbound_bytes": cl.state.out_bytes,
                    "outbound_writes": cl.state.out_writes,
                }
                for cl in self.clients.get_all().values()
                if cl.net.listener != "local" and cl.id != "inline"
            ]
            return "200 OK", json.dumps(out, indent=2).encode(), "application/json", _NO_STORE
        if path == "/processrecords":
            return (
                "200 OK",
                json.dumps(list(self._records), indent=2).encode(),
                "application/json",
                _NO_STORE,
            )
        if path == "/connections":
            rows, counts = self._client_rows()
            uptime = self.sys_info.uptime
            cells = "".join(
                "<tr>" + "".join(f"<td>{escape(c)}</td>" for c in row) + "</tr>"
                for row in rows
            )
            body = (
                "<html><head><meta charset='utf-8'>"
                "<meta http-equiv='refresh' content='180'>"
                "<title>mqtt_tpu connections</title>"
                "<style>table{border-collapse:collapse}"
                "td,th{border:1px solid #999;padding:4px 8px;font:14px monospace}"
                "th{background:#eee}</style></head><body>"
                f"<h2>connections</h2>"
                f"<p>uptime: {uptime}s &mdash; {escape(self.listener_summary)}</p>"
                f"<p>{escape('; '.join(f'{k}: {v}' for k, v in sorted(counts.items())))}</p>"
                "<table><tr><th>username</th><th>client id</th><th>remote</th>"
                "<th>ver</th><th>listener</th><th>#subs</th><th>filters</th></tr>"
                f"{cells}</table></body></html>"
            ).encode()
            return "200 OK", body, "text/html; charset=utf-8", _NO_STORE
        return "404 Not Found", b"", "text/plain"  # pragma: no cover - gated above
