"""HTTP utility listeners: healthcheck and $SYS stats.

Behavioral parity with reference ``listeners/http_healthcheck.go:19-99``
(200-OK on GET /healthcheck) and ``listeners/http_sysinfo.go:23-121``
(JSON dump of system.Info). Implemented as minimal asyncio HTTP/1.1
responders — no framework dependency.
"""

from __future__ import annotations

import asyncio
import json
import logging
from ..system import Info
from . import Config, EstablishFn, StreamListener, split_host_port


class _HttpListener(StreamListener):
    """Shared accept loop for the single-purpose HTTP listeners."""

    def protocol(self) -> str:
        return "https" if self.config.tls_config else "http"

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        host, port = split_host_port(self.config.address)
        self._server = await asyncio.start_server(
            self._on_connection, host, port, ssl=self.config.tls_config
        )

    async def serve(self, establish: EstablishFn) -> None:
        pass  # HTTP listeners never establish MQTT clients

    async def _on_connection(self, reader, writer):  # overrides StreamListener
        try:
            request = await asyncio.wait_for(reader.readuntil(b"\r\n\r\n"), 10)
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            parts = line.split(" ")
            method, path = (parts + ["", ""])[:2]
            status, body, ctype = self._respond(method, path)
            writer.write(
                (
                    f"HTTP/1.1 {status}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    def _respond(self, method: str, path: str) -> tuple[str, bytes, str]:
        raise NotImplementedError


class HTTPHealthCheck(_HttpListener):
    """Responds 200 OK to GET /healthcheck (http_healthcheck.go:59-63)."""

    def _respond(self, method: str, path: str) -> tuple[str, bytes, str]:
        if method == "GET" and path == "/healthcheck":
            return "200 OK", b"", "text/plain"
        return "405 Method Not Allowed" if method != "GET" else "404 Not Found", b"", "text/plain"


class HTTPStats(_HttpListener):
    """Serves the $SYS info values as JSON (http_sysinfo.go:112-121)."""

    def __init__(self, config: Config, sys_info: Info) -> None:
        super().__init__(config)
        self.sys_info = sys_info

    def _respond(self, method: str, path: str) -> tuple[str, bytes, str]:
        if method != "GET":
            return "405 Method Not Allowed", b"", "text/plain"
        body = json.dumps(self.sys_info.clone().as_dict()).encode()
        return "200 OK", body, "application/json"
