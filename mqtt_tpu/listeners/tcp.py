"""Plain/TLS TCP listener.

Behavioral parity with reference ``listeners/tcp.go:16-109``: bind on init,
accept loop dispatching each connection to the establish function, close
idempotently.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable, Optional

from . import Config, EstablishFn, Listener


class TCP(Listener):
    """A TCP listener, optionally TLS-wrapped (tcp.go:19-27)."""

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self._server: Optional[asyncio.base_events.Server] = None
        self._establish: Optional[EstablishFn] = None

    def protocol(self) -> str:
        return "tcp"

    def address(self) -> str:
        if self._server is not None and self._server.sockets:
            host, port = self._server.sockets[0].getsockname()[:2]
            return f"{host}:{port}"
        return self.config.address

    async def init(self, log: logging.Logger) -> None:
        """Bind the socket (tcp.go:57-69); the accept callback dispatches
        once serve() has provided the establish function."""
        self.log = log
        host, _, port = self.config.address.rpartition(":")
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]  # IPv6 literal, e.g. [::1]:1883
        self._server = await asyncio.start_server(
            self._on_connection,
            host or "0.0.0.0",
            int(port or 0),
            ssl=self.config.tls_config,
        )

    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        establish = self._establish
        if establish is None:  # not serving yet; drop the connection
            writer.close()
            return
        try:
            await establish(self.id(), reader, writer)
        except Exception as e:
            self.log.debug("establish error on %s: %s", self.id(), e)
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def serve(self, establish: EstablishFn) -> None:
        self._establish = establish

    async def close(self, close_clients: Callable[[str], None]) -> None:
        # Stop accepting, then disconnect attached clients FIRST — their
        # handler tasks must end before wait_closed() can complete.
        if self._server is not None:
            self._server.close()
        close_clients(self.id())
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except Exception:
                pass
            self._server = None
