"""Plain/TLS TCP listener.

Behavioral parity with reference ``listeners/tcp.go:16-109``: bind on init,
accept loop dispatching each connection to the establish function, close
idempotently.
"""

from __future__ import annotations

import asyncio
import logging

from . import Config, StreamListener, split_host_port


class TCP(StreamListener):
    """A TCP listener, optionally TLS-wrapped (tcp.go:19-27)."""

    def protocol(self) -> str:
        return "tcp"

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        host, port = split_host_port(self.config.address)
        self._server = await asyncio.start_server(
            self._on_connection,
            host,
            port,
            ssl=self.config.tls_config,
            reuse_port=self.config.reuse_port or None,
        )
