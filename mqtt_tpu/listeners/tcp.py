"""Plain/TLS TCP listener.

Behavioral parity with reference ``listeners/tcp.go:16-109``: bind on init,
accept loop dispatching each connection to the establish function, close
idempotently.
"""

from __future__ import annotations

import asyncio
import logging
import socket

from . import Config, StreamListener, bind_stream_socket, split_host_port


class TCP(StreamListener):
    """A TCP listener, optionally TLS-wrapped (tcp.go:19-27)."""

    def protocol(self) -> str:
        return "tcp"

    def _fabric_bind(self) -> list:
        host, port = split_host_port(self.config.address)
        if self._fabric_reuseport and hasattr(socket, "SO_REUSEPORT"):
            # one SO_REUSEPORT socket per shard: the kernel load-balances
            # accepts, each shard accepts on its own loop. The first bind
            # resolves an ephemeral port for the rest to join.
            first = bind_stream_socket(host, port, reuse_port=True)
            bound = first.getsockname()[1]
            socks = [first]
            try:
                for _ in range(1, self._fabric.n_shards):
                    socks.append(
                        bind_stream_socket(host, bound, reuse_port=True)
                    )
            except OSError:
                for s in socks:
                    s.close()
                raise
            return socks
        self._fabric_reuseport = False  # hand-off accept
        return [
            bind_stream_socket(
                host, port, reuse_port=bool(self.config.reuse_port)
            )
        ]

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        if self._fabric is not None:
            self._lsocks = self._fabric_bind()
            return
        host, port = split_host_port(self.config.address)
        self._server = await asyncio.start_server(
            self._on_connection,
            host,
            port,
            ssl=self.config.tls_config,
            reuse_port=self.config.reuse_port or None,
        )
