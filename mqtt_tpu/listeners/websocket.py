"""WebSocket listener: HTTP upgrade + binary-frame wrapping of the MQTT
byte stream.

Behavioral parity with reference ``listeners/websocket.go:30-199``: the
upgrade advertises the ``mqtt`` subprotocol, reads reassemble binary frames
into a contiguous byte stream, and each broker write goes out as one binary
frame. Implemented directly over asyncio (a dependency-free RFC 6455
server subset: no extensions, server frames unmasked, handles
ping/pong/close/continuation). Inbound frames are size-capped and the pump
applies backpressure so a hostile peer cannot buffer unbounded memory.
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import logging
import struct

from . import Config, EstablishFn, StreamListener, split_host_port

_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

# Hard cap on a single inbound frame; larger declared lengths close the
# connection (MQTT's own maximum-packet-size applies after reassembly).
MAX_FRAME = 1 << 20
# Pause reading when this much reassembled data is pending in the MQTT
# stream (no transport below the feed StreamReader, so no built-in
# pause_reading backpressure).
MAX_PENDING = 2 * MAX_FRAME


def _accept_key(key: str) -> str:
    return base64.b64encode(hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()


def _encode_frame(opcode: int, data: bytes) -> bytes:
    """One unmasked server frame with FIN set, any payload length."""
    n = len(data)
    if n < 126:
        header = struct.pack("!BB", 0x80 | opcode, n)
    elif n < (1 << 16):
        header = struct.pack("!BBH", 0x80 | opcode, 126, n)
    else:
        header = struct.pack("!BBQ", 0x80 | opcode, 127, n)
    return header + data


class _WsWriter:
    """Wraps a StreamWriter so each write emits one binary frame
    (websocket.go:187-197)."""

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer

    def write(self, data: bytes) -> None:
        self._writer.write(_encode_frame(OP_BINARY, data))

    def close(self) -> None:
        try:
            self._writer.write(_encode_frame(OP_CLOSE, b""))
        except Exception:  # brokerlint: ok=R4 best-effort CLOSE frame; the close() below is the real teardown
            pass
        self._writer.close()

    def get_extra_info(self, name, default=None):
        return self._writer.get_extra_info(name, default)

    async def drain(self) -> None:
        await self._writer.drain()


async def websocket_handshake(reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> bool:
    """Perform the HTTP upgrade; returns True on success."""
    request = await reader.readuntil(b"\r\n\r\n")
    headers = {}
    for line in request.split(b"\r\n")[1:]:
        if b":" in line:
            k, _, v = line.partition(b":")
            headers[k.strip().lower().decode()] = v.strip().decode()
    key = headers.get("sec-websocket-key")
    if not key or "upgrade" not in headers.get("connection", "").lower():
        writer.write(b"HTTP/1.1 400 Bad Request\r\n\r\n")
        await writer.drain()
        return False
    response = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {_accept_key(key)}\r\n"
    )
    # advertise the mqtt subprotocol when requested (websocket.go:48-53)
    protocols = headers.get("sec-websocket-protocol", "")
    if "mqtt" in [p.strip() for p in protocols.split(",")]:
        response += "Sec-WebSocket-Protocol: mqtt\r\n"
    writer.write(response.encode() + b"\r\n")
    await writer.drain()
    return True


async def ws_frame_pump(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    out: asyncio.StreamReader,
) -> None:
    """Read WS frames and feed binary payload bytes into ``out`` so the
    broker sees a contiguous MQTT byte stream (websocket.go:149-183)."""
    try:
        while True:
            head = await reader.readexactly(2)
            fin_op, len7 = head[0], head[1]
            opcode = fin_op & 0x0F
            masked = bool(len7 & 0x80)
            length = len7 & 0x7F
            if length == 126:
                length = struct.unpack("!H", await reader.readexactly(2))[0]
            elif length == 127:
                length = struct.unpack("!Q", await reader.readexactly(8))[0]
            if length > MAX_FRAME:
                writer.write(_encode_frame(OP_CLOSE, struct.pack("!H", 1009)))
                break  # 1009: message too big
            mask = await reader.readexactly(4) if masked else b""
            payload = await reader.readexactly(length) if length else b""
            if masked and payload:
                payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            if opcode in (OP_BINARY, OP_CONT):
                if payload:
                    # backpressure: wait for the broker to drain pending bytes
                    while len(out._buffer) > MAX_PENDING:  # noqa: SLF001
                        await asyncio.sleep(0.005)
                    out.feed_data(payload)
            elif opcode == OP_PING:
                writer.write(_encode_frame(OP_PONG, payload))
            elif opcode == OP_CLOSE:
                break
            # OP_TEXT / OP_PONG ignored (mqtt-over-ws is binary-only)
    except (asyncio.IncompleteReadError, ConnectionError):
        pass
    finally:
        out.feed_eof()


class Websocket(StreamListener):
    """A websocket listener serving MQTT over binary frames."""

    def protocol(self) -> str:
        return "wss" if self.config.tls_config else "ws"

    def _fabric_bind(self) -> list:
        from . import bind_stream_socket

        # hand-off accept only: the upgrade + frame pump run on the
        # shard's loop either way (the fabric routes through _handle)
        self._fabric_reuseport = False
        host, port = split_host_port(self.config.address)
        return [
            bind_stream_socket(
                host, port, reuse_port=bool(self.config.reuse_port)
            )
        ]

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        if self._fabric is not None:
            self._lsocks = self._fabric_bind()
            return
        host, port = split_host_port(self.config.address)
        self._server = await asyncio.start_server(
            self._on_connection,
            host,
            port,
            ssl=self.config.tls_config,
            reuse_port=self.config.reuse_port or None,
        )

    async def _handle(self, reader, writer, establish: EstablishFn) -> None:
        if not await websocket_handshake(reader, writer):
            return
        mqtt_stream = asyncio.StreamReader()
        pump = asyncio.get_running_loop().create_task(
            ws_frame_pump(reader, writer, mqtt_stream)
        )
        try:
            await establish(self.id(), mqtt_stream, _WsWriter(writer))
        finally:
            pump.cancel()
