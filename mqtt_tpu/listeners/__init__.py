"""Network listeners: the listener interface, the id-keyed registry, and the
built-in listener types.

Behavioral parity with reference ``listeners/listeners.go`` (interface :32-39,
registry :42-135). Accept loops are asyncio servers; the registry tracks all
per-client tasks (the reference's ``ClientsWg``) so close can wait for them.
"""

from __future__ import annotations

import asyncio
import logging
import socket
import ssl
from dataclasses import dataclass
from typing import Awaitable, Callable, Optional

# An EstablishFn is called for every new connection: (listener_id, reader,
# writer) -> awaitable (reference listeners.go:25).
EstablishFn = Callable[[str, asyncio.StreamReader, asyncio.StreamWriter], Awaitable]

TYPE_TCP = "tcp"
TYPE_WS = "ws"
TYPE_UNIX = "unix"
TYPE_HEALTHCHECK = "healthcheck"
TYPE_SYSINFO = "sysinfo"
TYPE_MOCK = "mock"


@dataclass
class Config:
    """Listener instantiation config (listeners.go:16-22).

    ``reuse_port`` enables SO_REUSEPORT binding so multiple broker worker
    processes share one address with kernel load-balancing — the
    multi-core data plane's listener mode (mqtt_tpu.cluster).

    ``admission`` gates this listener through the overload governor's
    per-listener CONNECT admission (mqtt_tpu.overload): while the broker
    throttles/sheds, new CONNECTs on admitting listeners refuse with
    CONNACK 0x97. Set False for an ops/debug listener (e.g. a private
    unix socket) that must stay reachable mid-storm."""

    type: str = ""
    id: str = ""
    address: str = ""
    tls_config: Optional[ssl.SSLContext] = None
    reuse_port: bool = False
    admission: bool = True


class Listener:
    """A network interface accepting client connections (listeners.go:32-39)."""

    def __init__(self, config: Config) -> None:
        self.config = config
        self.log = logging.getLogger("mqtt_tpu.listener")

    def id(self) -> str:
        return self.config.id

    def address(self) -> str:
        return self.config.address

    def protocol(self) -> str:
        raise NotImplementedError

    async def init(self, log: logging.Logger) -> None:
        """Bind/prepare the listener; raise on failure."""
        self.log = log

    async def serve(self, establish: EstablishFn) -> None:
        """Begin accepting connections, dispatching each to ``establish``."""
        raise NotImplementedError

    async def close(self, close_clients: Callable[[str], None]) -> None:
        """Stop accepting and run ``close_clients(listener_id)``."""
        close_clients(self.id())


def split_host_port(address: str) -> tuple[str, int]:
    """Parse host:port, handling bracketed IPv6 literals."""
    host, _, port = address.rpartition(":")
    if host.startswith("[") and host.endswith("]"):
        host = host[1:-1]
    return host or "0.0.0.0", int(port or 0)


def bind_stream_socket(
    host: str, port: int, reuse_port: bool = False
) -> socket.socket:
    """A bound, listening, non-blocking TCP socket — the raw-accept
    path the event-loop shard fabric uses (mqtt_tpu.shards): accepted
    connections must reach the shard's loop as bare sockets, never as
    main-loop transports that may already hold read bytes."""
    family = socket.AF_INET6 if ":" in host else socket.AF_INET
    sock = socket.socket(family, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if reuse_port and hasattr(socket, "SO_REUSEPORT"):
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        sock.bind((host, port))
        sock.listen(1024)
        sock.setblocking(False)
    except OSError:
        sock.close()
        raise
    return sock


class StreamListener(Listener):
    """Shared scaffolding for stream-socket listeners: establish dispatch,
    serve arming, and the disconnect-clients-then-wait close ordering.

    With an event-loop shard fabric attached (``attach_fabric``, set by
    the server before ``init`` when ``Options.loop_shards > 1``), the
    listener binds raw sockets instead of an asyncio server: accepted
    sockets are dispatched to the least-loaded shard and wrapped into
    streams ON that shard's loop (mqtt_tpu.shards). ``reuseport`` accept
    mode gives every shard its own SO_REUSEPORT-bound socket + accept
    loop instead (kernel load balancing, no hand-off hop)."""

    def __init__(self, config: Config) -> None:
        super().__init__(config)
        self._server: Optional[asyncio.base_events.Server] = None
        self._establish: Optional[EstablishFn] = None
        # event-loop shard fabric (mqtt_tpu.shards.ShardFabric) or None
        self._fabric = None
        self._fabric_reuseport = False
        self._lsocks: list[socket.socket] = []
        self._accept_task: Optional[asyncio.Task] = None

    def attach_fabric(self, fabric, reuseport: bool = False) -> None:
        """Route this listener's accepts through the shard fabric; must
        be called before ``init``."""
        self._fabric = fabric
        self._fabric_reuseport = reuseport

    def _fabric_bind(self) -> list:
        """Bind the fabric-mode listening socket(s); subclasses that
        support the fabric override this. One socket = hand-off accept
        on the main loop; one socket per shard = per-shard accept."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support the shard fabric"
        )

    def address(self) -> str:
        if self._server is not None and self._server.sockets:
            name = self._server.sockets[0].getsockname()
            if isinstance(name, tuple):
                return f"{name[0]}:{name[1]}"
            return str(name)
        if self._lsocks:
            try:
                name = self._lsocks[0].getsockname()
            except OSError:
                return self.config.address
            if isinstance(name, tuple):
                return f"{name[0]}:{name[1]}"
            return str(name)
        return self.config.address

    async def _handle(self, reader, writer, establish: EstablishFn) -> None:
        """Dispatch one accepted connection; override to wrap the streams
        (e.g. websocket framing)."""
        await establish(self.id(), reader, writer)

    async def _on_connection(self, reader, writer) -> None:
        establish = self._establish
        if establish is None:  # not serving yet
            writer.close()
            return
        try:
            await self._handle(reader, writer, establish)
        except Exception as e:
            self.log.debug("establish error on %s: %s", self.id(), e)
        finally:
            try:
                writer.close()
            except Exception:  # brokerlint: ok=R4 teardown; the transport is already gone
                pass

    async def serve(self, establish: EstablishFn) -> None:
        self._establish = establish
        if self._fabric is None or not self._lsocks:
            return

        async def handler(reader, writer) -> None:
            # through _handle so stream-wrapping listeners (websocket)
            # ride the fabric unchanged
            await self._handle(reader, writer, establish)

        tls = self.config.tls_config
        if self._fabric_reuseport and len(self._lsocks) > 1:
            self._fabric.serve_reuseport(self._lsocks, tls, handler)
            return
        self._accept_task = asyncio.get_running_loop().create_task(
            self._fabric_accept_loop(self._lsocks[0], tls, handler),
            name=f"mqtt-tpu-accept-{self.id()}",
        )

    async def _fabric_accept_loop(self, lsock, tls, handler) -> None:
        """Hand-off accept: the main loop accepts, the fabric routes the
        bare socket to the least-loaded shard."""
        loop = asyncio.get_running_loop()
        while True:
            try:
                sock, _addr = await loop.sock_accept(lsock)
            except (asyncio.CancelledError, GeneratorExit):
                raise
            except OSError:
                return  # listener closed under us
            self._fabric.dispatch(sock, tls, handler)

    async def close(self, close_clients: Callable[[str], None]) -> None:
        # Stop accepting, then disconnect attached clients FIRST — their
        # handler tasks must end before wait_closed() can complete.
        if self._server is not None:
            self._server.close()
        if self._accept_task is not None:
            self._accept_task.cancel()
            self._accept_task = None
        for sock in self._lsocks:
            try:
                sock.close()
            except OSError:
                pass
        self._lsocks = []
        close_clients(self.id())
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), timeout=5)
            except Exception:  # brokerlint: ok=R4 bounded-wait shutdown; a straggler handler must not wedge close
                pass
            self._server = None


class Listeners:
    """Id-keyed listener registry with serve/close-all and a global client
    task group (listeners.go:42-135)."""

    def __init__(self) -> None:
        self.internal: dict[str, Listener] = {}
        self.client_tasks: set[asyncio.Task] = set()  # the ClientsWg analog

    def add(self, val: Listener) -> None:
        self.internal[val.id()] = val

    def get(self, id_: str) -> Optional[Listener]:
        return self.internal.get(id_)

    def delete(self, id_: str) -> None:
        self.internal.pop(id_, None)

    def __len__(self) -> int:
        return len(self.internal)

    def track(self, coro) -> asyncio.Task:
        """Spawn a per-client task, tracked for close-time draining."""
        task = asyncio.get_running_loop().create_task(coro)
        self.client_tasks.add(task)
        task.add_done_callback(self.client_tasks.discard)
        return task

    async def serve_all(self, establish: EstablishFn) -> None:
        for listener in list(self.internal.values()):
            await listener.serve(establish)

    async def close_all(self, close_clients: Callable[[str], None]) -> None:
        for listener in list(self.internal.values()):
            await listener.close(close_clients)
            self.delete(listener.id())
        if self.client_tasks:
            # bounded drain, then cancel: a handler wedged on an
            # unflushable transport (a disconnected-but-stalled reader
            # holding buffered writes) must not hang shutdown — the
            # same posture as the listener's bounded wait_closed above
            tasks = list(self.client_tasks)
            done, pending = await asyncio.wait(tasks, timeout=5)
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)


from .http import Dashboard, HTTPHealthCheck, HTTPStats  # noqa: E402
from .mock import MockListener  # noqa: E402
from .net import Net  # noqa: E402
from .tcp import TCP  # noqa: E402
from .unixsock import UnixSock  # noqa: E402
from .websocket import Websocket  # noqa: E402

__all__ = [
    "Config",
    "EstablishFn",
    "HTTPHealthCheck",
    "Dashboard",
    "HTTPStats",
    "Listener",
    "Listeners",
    "MockListener",
    "Net",
    "TCP",
    "TYPE_HEALTHCHECK",
    "TYPE_MOCK",
    "TYPE_SYSINFO",
    "TYPE_TCP",
    "TYPE_UNIX",
    "TYPE_WS",
    "UnixSock",
    "Websocket",
]
