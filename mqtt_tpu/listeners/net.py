"""Listener over an externally created, already-bound socket.

Behavioral parity with reference ``listeners/net.go:16-92`` (wraps a
pre-made net.Listener).
"""

from __future__ import annotations

import asyncio
import logging
import socket

from . import Config, StreamListener


class Net(StreamListener):
    def __init__(self, id_: str, sock: socket.socket) -> None:
        super().__init__(Config(type="net", id=id_))
        self._sock = sock

    def protocol(self) -> str:
        return "net"

    def address(self) -> str:
        try:
            host, port = self._sock.getsockname()[:2]
            return f"{host}:{port}"
        except OSError:
            return ""

    def _fabric_bind(self) -> list:
        # the caller's pre-bound socket feeds the hand-off accept loop
        self._fabric_reuseport = False
        self._sock.setblocking(False)
        return [self._sock]

    async def init(self, log: logging.Logger) -> None:
        self.log = log
        if self._fabric is not None:
            self._lsocks = self._fabric_bind()
            return
        self._server = await asyncio.start_server(self._on_connection, sock=self._sock)
