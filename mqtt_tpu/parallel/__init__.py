"""Multi-chip device parallelism for the topic matcher.

The reference broker has no distributed backend (clustering is a roadmap
item, reference README.md:59-62); this package is the TPU-native scaling
layer the rebuild adds: subscriptions shard across mesh devices (each shard
holds its own flat-hash index), PUBLISH batches shard across the batch axis,
and per-shard match results union through an ``all_gather`` over ICI —
XLA collectives via ``shard_map`` over a ``jax.sharding.Mesh``, never
host-side gathers.
"""

from .sharded import ShardedTpuMatcher, dryrun_multichip, make_mesh

__all__ = ["ShardedTpuMatcher", "dryrun_multichip", "make_mesh"]
