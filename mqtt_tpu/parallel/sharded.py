"""Subscription-sharded matching over a 2D device mesh.

Mesh axes:

- ``batch`` — data parallelism over the PUBLISH topic batch
- ``subs``  — model-style parallelism over the subscription set: each device
  along this axis holds the flat-hash index (ops/flat.py) of its
  subscription shard

One jitted step matches every (topic-shard, sub-shard) tile locally and
``all_gather``s the per-shard match lists over the ``subs`` axis (ICI), so
every batch row ends with the full union of sub ids. The host maps local
sub ids through per-shard tables and merges — bit-identical to the
single-device matcher, which is bit-identical to the host trie.

Shard assignment is a stable hash of (client, filter) — NOT round-robin
over enumeration order — so one subscription mutation touches exactly one
shard. The matcher keeps a per-shard replica ``TopicsIndex`` maintained
from the trie's mutation stream (``TopicsIndex.add_observer``), marks the
owning shard dirty, and an incremental ``rebuild()`` recompiles only dirty
shards: cost per mutation is bounded by one shard (~1/S of the index)
instead of the full index (reference mutation semantics: topics.go:479-522).
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map

    _REP_KWARG = "check_vma"
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _REP_KWARG = "check_rep"


def shard_map(*args, disable_rep_check=False, **kwargs):
    if disable_rep_check:
        kwargs[_REP_KWARG] = False
    return _shard_map(*args, **kwargs)

from functools import partial

from ..telemetry import FILL_BOUNDS, Histogram
from ..topics import Mutation, Subscribers, TopicsIndex
from ..ops.flat import (
    KIND_CLIENT,
    KIND_INLINE,
    KIND_SHARED,
    SubEntry,
    _bucket,
    _pad_to,
    _walk_terminals,
    build_flat_index,
    flat_match_core,
)
from ..ops.devicestats import KernelWatch
from ..ops.hashing import tokenize_topics
from ..ops.matcher import (
    MatcherStats,
    _accel,
    expand_sids,
    fold_hits_ewma,
    materialize_compact_pairs,
    pick_compact_capacity,
)

_log = logging.getLogger("mqtt_tpu.parallel")


def make_mesh(devices=None, batch_axis: Optional[int] = None) -> Mesh:
    """A 2D (batch, subs) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if batch_axis is None:
        batch_axis = 2 if n % 2 == 0 and n > 1 else 1
    subs_axis = n // batch_axis
    grid = np.array(devices[: batch_axis * subs_axis]).reshape(batch_axis, subs_axis)
    return Mesh(grid, ("batch", "subs"))


def _tile_compact_core(out, totals, overflow, *, cap_local):
    """Compact one batch-tile's gathered result ON DEVICE (ROADMAP item
    1 feeding item 2's cheap all-gather): the device's local
    ``[S, b_local, K]`` -1-padded slot view becomes a topic-major
    ``(shard, sid)`` pair stream sized for the hits that exist, so the
    D2H moves ~``hits x 8`` bytes instead of ``S x B x K x 4``.

    Runs INSIDE a shard_map over the ``batch`` mesh axis (the gathered
    arrays come from a ``check_rep``-disabled shard_map, whose claimed
    replication plain jitted jnp code must not trust — the same reason
    the match step itself is explicit SPMD). Per-tile output row:
    ``[2 + 2*b_local + 2*cap_local]`` = ``(tile_hits, tile_overflow |
    totals[b_local] | overflow[b_local] | pair_shard[cap_local] |
    pair_sid[cap_local])``. Per-segment counts are clamped to ``K`` —
    rows past the slot window are overflow-flagged by the kernel and
    host-routed, so their surplus never reaches the pair stream."""
    import jax.numpy as jnp

    from ..ops.flat import _segment_of_slot

    S, bl, K = out.shape
    out_t = jnp.transpose(out, (1, 0, 2)).reshape(bl * S, K)
    t_flat = jnp.minimum(jnp.transpose(totals, (1, 0)).reshape(bl * S), K)
    cum = jnp.cumsum(t_flat)
    offs = cum - t_flat
    n_hits = cum[-1]
    k = jnp.arange(cap_local, dtype=jnp.int32)
    seg_c = _segment_of_slot(t_flat, offs, cap_local)
    slot = jnp.minimum(k - offs[seg_c].astype(jnp.int32), K - 1)
    sid = out_t[seg_c, slot]
    shard = seg_c % S
    valid = k < n_hits
    per_topic = jnp.minimum(totals, K).sum(axis=0).astype(jnp.int32)
    ovf_topic = overflow.any(axis=0).astype(jnp.int32)
    header = jnp.stack(
        [n_hits.astype(jnp.int32), (n_hits > cap_local).astype(jnp.int32)]
    )
    vec = jnp.concatenate(
        [
            header,
            per_topic,
            ovf_topic,
            jnp.where(valid, shard, -1),
            jnp.where(valid, sid, -1),
        ]
    )
    return vec[None, :]


def shard_of(kind, client: str, filter: str, identifier: int, n_shards: int) -> int:
    """Stable shard assignment: a deterministic hash of the subscription's
    identity, independent of enumeration order or churn history — so the
    same subscription always lands on the same shard and a mutation dirties
    exactly one shard."""
    if kind in (KIND_INLINE, "inline"):
        key = f"\x00inline\x00{identifier}\x00{filter}"
    else:
        key = f"{client}\x00{filter}"
    return zlib.crc32(key.encode("utf-8", "surrogatepass")) % n_shards


class ShardedTpuMatcher:
    """Shards a TopicsIndex's subscriptions across the ``subs`` mesh axis
    and matches topic batches with one SPMD step.

    With ``incremental=True`` (default) the matcher subscribes to the
    trie's mutation stream and ``rebuild()`` recompiles only the shards
    whose subscriptions changed; call :meth:`close` to detach the observer.
    ``frontier`` is accepted for API continuity and ignored (the flat
    matcher has no frontier).
    """

    # rebuild() retries torn walks and quiesces internally — callers (the
    # delta overlay) must NOT wrap it in `with topics._lock`, which would
    # invert this class's rebuild-mutex -> trie-lock order and deadlock
    handles_tears = True

    def __init__(
        self,
        topics: TopicsIndex,
        mesh: Optional[Mesh] = None,
        max_levels: int = 8,
        frontier: int = 16,  # ignored (flat matcher); kept for API compat
        out_slots: int = 64,
        window: int = 16,
        incremental: bool = True,
        compact: bool = True,
        compact_capacity: int = 0,
        hits_estimate: float = 2.0,
        lazy: bool = False,
    ) -> None:
        self.topics = topics
        self.mesh = mesh or make_mesh()
        self.max_levels = max_levels
        self.frontier = frontier
        self.out_slots = out_slots
        self.window = window
        self.n_shards = self.mesh.shape["subs"]
        self.n_batch = self.mesh.shape["batch"]
        self.incremental = incremental
        # device-resident hit compaction of the gathered result (see
        # _gather_compact_core); same knob contract as TpuMatcher
        self.compact = compact
        self.compact_capacity = max(0, compact_capacity)
        # lazy SubscribersView results over the stitched per-tile pair
        # stream (ISSUE 15 satellite closing the ISSUE 13 residual):
        # resolve_compact_views consumes the sharded (sid, shard) form
        # natively — per-hit objects are built only when fan-out asks.
        # The eager expansion stays as the differential oracle, and
        # without the C module laziness silently degrades to eager
        # (materialize_compact_pairs' contract).
        self.lazy = lazy
        self._hits_ewma = max(1.0, float(hits_estimate))
        # sticky per-batch-bucket capacities (TpuMatcher contract: grow
        # immediately, shrink only at 4x oversize — every distinct
        # capacity is one XLA executable)
        self._caps: dict[int, int] = {}
        self.stats = MatcherStats()
        # device pipeline profiler (mqtt_tpu.tracing.DeviceProfiler) or
        # None; same seam as TpuMatcher.profiler (ops/matcher.py) — the
        # SPMD step's dispatch and D2H windows feed duty-cycle/overlap/
        # idle-gap accounting when the server (or bench) attaches one
        self.profiler = None
        # one (arrays, tables, salt, step) tuple swapped atomically so a
        # concurrent match never mixes generations
        self._compiled: Optional[tuple] = None
        self._built_version = -1
        # per-shard replica tries + their last compiled flat indexes +
        # dirty flags; guarded by _state_lock (held briefly — the observer
        # runs under the main trie's lock, so installs must never block)
        self._state_lock = threading.Lock()
        # serializes whole rebuilds: without it, a concurrent rebuild can
        # observe the storm path's intermediate state (fresh replicas,
        # cleared dirty flags, old compiled arrays) and stamp the stale
        # snapshot as current via the empty-dirty early return
        self._rebuild_mutex = threading.Lock()
        self._replicas: Optional[list[TopicsIndex]] = None
        self._flats: Optional[list] = None
        self._dirty = [False] * self.n_shards
        self._salt = 0
        self._step: Optional[Callable] = None
        # jitted per-tile compaction steps, keyed on cap_local (each
        # capacity is one executable; jax re-traces per input shape)
        self._compact_steps: dict[int, Callable] = {}
        # per-shard compile-time histogram SHARDS (mqtt_tpu.telemetry):
        # the thread compiling shard s records into shard s's local
        # histogram — no cross-thread write sharing — and the scrape
        # merges them on demand (merged_shard_compile), the merge()-at-
        # scrape pattern the telemetry plane's Histogram documents
        self.shard_compile_hists = [Histogram() for _ in range(self.n_shards)]
        # per-tile imbalance telemetry (ISSUE 18): cumulative hit counts
        # and per-batch fill histograms, one per batch tile, folded from
        # each resolved compact batch under _tile_lock (arithmetic only).
        # device_skew_ratio() = max/mean over tile_hits — the live gauge
        # the multi-chip frontier's "near-linear scaling" claim reads.
        self._tile_lock = threading.Lock()
        self._tile_hits = np.zeros(self.n_batch, dtype=np.int64)
        self._tile_batches = 0
        self.tile_fill_hists = [
            Histogram(bounds=FILL_BOUNDS) for _ in range(self.n_batch)
        ]
        # mesh device ids, dispatch-stamped onto each BatchProfile so the
        # profiler's per-device windows attribute sharded batches
        self._device_ids = tuple(
            int(getattr(d, "id", i))
            for i, d in enumerate(self.mesh.devices.flat)
        )
        if incremental:
            topics.add_observer(self._on_mutation)

    def tile_hit_counts(self) -> np.ndarray:
        """Cumulative per-batch-tile hit counts (a copy)."""
        with self._tile_lock:
            return self._tile_hits.copy()

    def device_skew_ratio(self) -> float:
        """max/mean per-tile cumulative hits: 1.0 = balanced mesh,
        n_batch = one hot tile, 0.0 = no traffic yet."""
        with self._tile_lock:
            hits = self._tile_hits
            mean = float(hits.mean()) if hits.size else 0.0
            if mean <= 0.0:
                return 0.0
            return float(hits.max()) / mean

    def _fold_tile_hits(self, tile_hits: np.ndarray, cap_local: int) -> None:
        """Fold one resolved batch's per-tile hit counts into the skew
        accounting (called from resolve closures, any thread)."""
        n = min(len(tile_hits), self.n_batch)
        with self._tile_lock:
            self._tile_hits[:n] += tile_hits[:n].astype(np.int64)
            self._tile_batches += 1
            if cap_local > 0:
                for t in range(n):
                    self.tile_fill_hists[t].observe(
                        float(tile_hits[t]) / cap_local
                    )

    def close(self) -> None:
        """Detach from the trie's mutation stream."""
        self.topics.remove_observer(self._on_mutation)

    # -- delta stream --------------------------------------------------------

    def _on_mutation(self, m: Mutation) -> None:
        """Apply one trie mutation to the owning shard's replica and mark it
        dirty. Called under the main trie's lock — must stay fast and must
        never raise into the broker's subscribe path."""
        with self._state_lock:
            reps = self._replicas
            if reps is None:
                return  # first full build will capture current state
            s = shard_of(m.kind, m.client, m.filter, m.identifier, self.n_shards)
            try:
                rep = reps[s]
                if m.kind == "inline":
                    if m.op == "add":
                        rep.inline_subscribe(m.subscription)
                    else:
                        rep.inline_unsubscribe(m.identifier, m.filter)
                else:
                    if m.op == "add":
                        rep.subscribe(m.client, m.subscription)
                    else:
                        rep.unsubscribe(m.filter, m.client)
                self._dirty[s] = True
            except Exception:
                _log.exception("shard replica update failed; forcing full rebuild")
                self._replicas = None

    # -- build -------------------------------------------------------------

    def rebuild(self) -> None:
        """Bring the compiled index up to date.

        Full path (first build, or after a replica fault): walk the live
        trie, partition by stable hash into fresh replicas, compile all
        shards. Incremental path: recompile only dirty shards' replicas and
        restack — cost bounded by the dirty shards, not the index.

        The observer's fault path can null the replicas mid-compile; each
        attempt would then fold nothing, so retry a bounded number of
        times instead of recursing unboundedly under a persistent fault."""
        t0 = time.perf_counter()
        with self._rebuild_mutex:
            # the except runs INSIDE the mutex: re-marking dirty after
            # release would leave a gap where a concurrent rebuild sees
            # empty dirty flags and stamps the stale snapshot as current
            try:
                for attempt in range(4):
                    if self._replicas is None or not self.incremental:
                        done = self._full_rebuild()
                    else:
                        done = self._incremental_rebuild()
                    if done:
                        break
                else:
                    raise RuntimeError(
                        "rebuild could not complete: persistent replica faults"
                    )
            except BaseException:
                # exception safety: a rebuild that dies after clearing dirty
                # flags (e.g. device_put fault in _assemble) must not let the
                # next rebuild's empty-dirty early-return pass off the stale
                # snapshot as current — over-mark everything dirty instead
                with self._state_lock:
                    self._dirty = [True] * self.n_shards
                raise
        self.stats.rebuilds += 1
        self.stats.note_rebuild(time.perf_counter() - t0)

    def _partition_live(self) -> list[TopicsIndex]:
        """Walk the live trie and split its subscriptions into fresh
        per-shard replicas. Concurrent structural mutations can tear the
        walk (RuntimeError/KeyError from dict iteration) — callers retry."""
        replicas = [TopicsIndex() for _ in range(self.n_shards)]
        for _path, node in _walk_terminals(self.topics):
            for client, sub in node.subscriptions.get_all().items():
                s = shard_of(KIND_CLIENT, client, sub.filter, 0, self.n_shards)
                replicas[s].subscribe(client, sub)
            for group in node.shared.get_all().values():
                for client, sub in group.items():
                    s = shard_of(KIND_SHARED, client, sub.filter, 0, self.n_shards)
                    replicas[s].subscribe(client, sub)
            for isub in node.inline_subscriptions.get_all().values():
                s = shard_of(
                    KIND_INLINE, "", isub.filter, isub.identifier, self.n_shards
                )
                replicas[s].inline_subscribe(isub)
        return replicas

    def _full_rebuild(self) -> bool:
        for attempt in range(8):
            v0 = self.topics.version
            try:
                replicas = self._partition_live()
            except (RuntimeError, KeyError):
                continue  # concurrent mutation tore the walk; retry
            flats = self._compile_all(replicas)
            if self.topics.version != v0:
                continue  # doomed: skip the H2D transfer, retry the walk
            # device placement happens OUTSIDE _state_lock: the observer
            # runs under the broker trie's lock and blocks on _state_lock,
            # so holding it across an H2D transfer (65ms+ on tunneled
            # links) would stall every subscribe for the transfer time
            compiled = self._assemble(flats)
            with self._state_lock:
                if self.topics.version == v0:
                    self._replicas = replicas
                    self._flats = flats
                    self._dirty = [False] * self.n_shards
                    self._salt = flats[0].salt
                    self._compiled = compiled
                    self._built_version = v0
                    return True
            # a mutation landed while we walked: the fresh replicas may miss
            # it (the observer was still feeding the OLD replicas) — retry
        # mutation storm: quiesce the trie ONLY long enough to walk it and
        # swap fresh replicas in (pure host work, no device transfers) —
        # subscribes resume while we compile; every mutation from the swap
        # onward feeds the new replicas and marks its shard dirty, and
        # _built_version = v0 keeps `stale` true until they are folded
        with self.topics._lock:
            v0 = self.topics.version
            replicas = self._partition_live()
            with self._state_lock:
                self._replicas = replicas
                self._dirty = [False] * self.n_shards
        flats = self._compile_all(replicas, retry_tears=True)
        compiled = self._assemble(flats)
        with self._state_lock:
            fault = self._replicas is not replicas
            if not fault:
                self._flats = flats
                self._salt = flats[0].salt
                self._compiled = compiled
                self._built_version = v0
        # on fault the observer nulled the replicas mid-compile; returning
        # success would report a rebuild that folded nothing (DeltaMatcher
        # would drop its overlay) — the caller retries, boundedly
        return not fault

    def _incremental_rebuild(self) -> bool:
        # read the version under the trie lock: the trie bumps it BEFORE
        # notifying observers, so a bare read could adopt a version whose
        # mutation hasn't marked its shard dirty yet — stamping that
        # version as built would hide the unfolded shard from `stale`.
        # Holding the trie lock waits out any in-flight notify.
        with self.topics._lock:
            version = self.topics.version
        with self._state_lock:
            # snapshot under the lock: the observer's exception path sets
            # _replicas = None concurrently, and reading a torn
            # replicas/flats/dirty trio would crash the rebuild thread with
            # an exception type no caller retries (TypeError)
            replicas = self._replicas
            if replicas is None or self._flats is None:
                replicas = None  # fall through to a full rebuild below
            else:
                dirty = [s for s in range(self.n_shards) if self._dirty[s]]
                # clear BEFORE compiling: a mutation racing the compile
                # re-marks the shard, so it is recompiled next round even
                # if this walk already included it
                for s in dirty:
                    self._dirty[s] = False
                flats = list(self._flats)
                if not dirty and self._compiled is not None:
                    # nothing to fold: stamp INSIDE the lock — outside it, a
                    # mutation between the dirty check and the stamp could
                    # publish a version whose shard was never folded
                    self._built_version = version
                    return True
        if replicas is None:
            return self._full_rebuild()
        for s in dirty:
            # compile at the generation's bucket count up front: defaulting
            # to the minimum would make _unify recompile the shard again
            flats[s] = self._compile_shard(
                s, replicas, min_buckets=flats[s].table.shape[0]
            )
        flats = self._unify(flats, replicas)
        compiled = self._assemble(flats)
        with self._state_lock:
            fault = self._replicas is not replicas
            if not fault:
                self._flats = flats
                self._salt = flats[0].salt  # keep in sync: a bump here must
                # not force the next incremental round to recompile the world
                self._compiled = compiled
                self._built_version = version
        # on fault: see _full_rebuild — the caller retries, boundedly
        return not fault

    def merged_shard_compile(self) -> Histogram:
        """One merged snapshot of the per-shard compile-time histogram
        shards (scrape-time callback for the telemetry registry)."""
        merged = Histogram()
        for h in self.shard_compile_hists:
            merged.merge(h)
        return merged

    def _compile_shard(
        self,
        s: int,
        replicas,
        salt: Optional[int] = None,
        min_buckets: int = 1024,
        retry_tears: bool = True,
    ):
        t0 = time.perf_counter()
        try:
            return self._compile_shard_inner(
                s, replicas, salt, min_buckets, retry_tears
            )
        finally:
            # shard-local: only the thread compiling shard s writes here
            self.shard_compile_hists[s].observe(time.perf_counter() - t0)

    def _compile_shard_inner(
        self,
        s: int,
        replicas,
        salt: Optional[int] = None,
        min_buckets: int = 1024,
        retry_tears: bool = True,
    ):
        rep = replicas[s]
        salt = self._salt if salt is None else salt
        if retry_tears:
            for _ in range(8):
                try:
                    return build_flat_index(
                        rep,
                        max_levels=self.max_levels,
                        salt=salt,
                        window=self.window,
                        min_buckets=min_buckets,
                    )
                except (RuntimeError, KeyError):
                    continue  # replica mutated mid-walk; retry
            with rep._lock:  # mutation storm on this shard: build quiesced
                return build_flat_index(
                    rep,
                    max_levels=self.max_levels,
                    salt=salt,
                    window=self.window,
                    min_buckets=min_buckets,
                )
        # fresh, unpublished replicas can't tear: no retry wrapper
        return build_flat_index(
            rep,
            max_levels=self.max_levels,
            salt=salt,
            window=self.window,
            min_buckets=min_buckets,
        )

    def _compile_all(self, replicas: list[TopicsIndex], retry_tears: bool = False):
        """Compile every shard at a uniform salt and bucket count. With
        ``retry_tears`` the per-shard compile retries walks torn by
        concurrent replica mutations (live replicas); without it a tear
        propagates to the caller (fresh, unpublished replicas can't tear)."""

        def compile_one(s: int, salt: int, min_buckets: int = 1024):
            return self._compile_shard(
                s, replicas, salt=salt, min_buckets=min_buckets,
                retry_tears=retry_tears,
            )

        flats = [compile_one(s, self._salt) for s in range(len(replicas))]
        return self._unify(flats, replicas, compile_one)

    def _unify(self, flats, replicas, compile_one=None):
        """Recompile shards until all

        - agree on the hash salt (topics tokenize at ONE salt: serving
          mixed-salt shards would silently drop subscribers), and
        - agree on the bucket count (the stacked table is one array; each
          shard's ``slot = h1 & (S-1)`` must use the stacked S).
        """
        if compile_one is None:

            def compile_one(s, salt, min_buckets=1024):
                return self._compile_shard(s, replicas, salt=salt, min_buckets=min_buckets)

        for _ in range(8):
            salts = {f.salt for f in flats}
            sizes = {f.table.shape[0] for f in flats}
            if len(salts) == 1 and len(sizes) == 1:
                return flats
            salt = max(salts)
            S = max(sizes)
            flats = [
                f
                if f.salt == salt and f.table.shape[0] == S
                else compile_one(s, salt, min_buckets=S)
                for s, f in enumerate(flats)
            ]
        if len({(f.salt, f.table.shape[0]) for f in flats}) == 1:
            return flats
        raise RuntimeError("shard salt/size unification failed")

    def _assemble(self, flats) -> tuple:
        """Stack per-shard flat indexes into mesh-placed device arrays and
        return the compiled generation (the caller swaps it in under
        _state_lock — device placement itself must happen lock-free).
        Shapes are power-of-two bucketed so churn rebuilds reuse the jitted
        executable. Padding is inert: pad patterns have depth -1 (never
        active) and pad id slots sit beyond every entry's window."""

        def stack(get, fill=0, min_len=2):
            arrs = [np.asarray(get(f)) for f in flats]
            n = _bucket(max(min_len, max(len(a) for a in arrs)), minimum=min_len)
            return np.stack([_pad_to(a, n, fill) for a in arrs])

        # table bucket counts are unified by _unify; stack directly
        table = np.stack([f.table for f in flats])
        shard_sharding = NamedSharding(self.mesh, P("subs"))
        arrays = tuple(
            jax.device_put(np.asarray(a), shard_sharding)
            for a in (
                table,
                stack(lambda f: f.pat_kind, fill=np.uint32(0)),
                stack(lambda f: f.pat_depth, fill=np.int32(-1)),
                stack(lambda f: f.pat_mask, fill=np.uint32(0)),
            )
        )
        tables = [f.subs for f in flats]
        step = self._get_step()
        return (arrays, tables, flats[0].salt, step)

    def _get_step(self):
        """The jitted SPMD step (cached; jax re-traces per shape)."""
        if self._step is not None:
            return self._step
        mesh = self.mesh
        max_levels, out_slots = self.max_levels, self.out_slots

        def step_fn(
            table, pat_kind, pat_depth, pat_mask,
            tok1, tok2, lengths, is_dollar,
        ):
            # each device: its sub shard (leading dim 1) x its batch tile
            out, totals, overflow = flat_match_core(
                table[0], pat_kind[0], pat_depth[0], pat_mask[0],
                tok1, tok2, lengths, is_dollar,
                max_levels=max_levels, out_slots=out_slots,
            )
            # union across the subs axis rides ICI
            out_g = jax.lax.all_gather(out, "subs")  # [S, b_local, K]
            tot_g = jax.lax.all_gather(totals, "subs")  # [S, b_local]
            ovf_g = jax.lax.all_gather(overflow, "subs")
            return out_g, tot_g, ovf_g

        shard_spec = P("subs")
        batch_spec = P("batch")
        step = KernelWatch(
            "sharded_step",
            jax.jit(
                shard_map(
                    step_fn,
                    mesh=mesh,
                    in_specs=(shard_spec,) * 4 + (batch_spec,) * 4,
                    out_specs=(P(None, "batch", None), P(None, "batch"), P(None, "batch")),
                    disable_rep_check=True,
                )
            ),
        )
        self._step = step
        return step

    def _get_compact_step(self, cap_local: int) -> Callable:
        """The jitted shard_map'd per-tile compaction for one local
        capacity (cached; jax re-traces per input shape)."""
        step = self._compact_steps.get(cap_local)
        if step is None:
            fn = partial(_tile_compact_core, cap_local=cap_local)
            # cap_local is baked into the traced fn, not a call arg: give
            # the watch a per-capacity kernel label so a capacity-churn
            # recompile (the PR 11 incident) attributes to its capacity
            step = KernelWatch(
                f"sharded_tile_compact_c{cap_local}",
                jax.jit(
                    shard_map(
                        fn,
                        mesh=self.mesh,
                        in_specs=(
                            P(None, "batch", None),
                            P(None, "batch"),
                            P(None, "batch"),
                        ),
                        out_specs=P("batch", None),
                        disable_rep_check=True,
                    )
                ),
            )
            self._compact_steps[cap_local] = step
        return step

    @property
    def stale(self) -> bool:
        return self._compiled is None or self._built_version != self.topics.version

    # -- matching ----------------------------------------------------------

    def match_topics_async(self, topics: list[str], route_to_host=None, profile=None):
        """Issue one SPMD match step and return a zero-arg resolver.

        Mirrors ``TpuMatcher.match_topics_async`` (ops/matcher.py): the
        step is dispatched asynchronously; the resolver performs the D2H
        sync plus host-side expansion and returns ``list[Subscribers]``.
        The delta overlay (ops/delta.py) relies on this API existing on
        every snapshot kind. ``profile`` is the caller's optional
        per-batch BatchProfile (mqtt_tpu.tracing), same contract as
        TpuMatcher."""
        if self._compiled is None or self.stale:
            self.rebuild()
        arrays, tables, salt, step = self._compiled
        prof = self.profiler
        rec = None
        if prof is not None:
            rec = profile if profile is not None else prof.open_batch()
            t_issue0 = time.perf_counter()
        b = len(topics)
        # pad ragged batches to a power-of-two bucket (one jitted executable
        # across the staging loop's window sizes), rounded up to a multiple
        # of the batch axis for even sharding
        target = _bucket(max(1, b), minimum=max(2, self.n_batch))
        target += (-target) % self.n_batch
        padded = topics + [""] * (target - b)
        tok1, tok2, lengths, is_dollar, len_overflow = tokenize_topics(
            padded, self.max_levels, salt
        )
        batch_sharding = NamedSharding(self.mesh, P("batch"))
        out_dev, totals_dev, overflow_dev = step(
            *arrays,
            *(
                jax.device_put(np.asarray(a), batch_sharding)
                for a in (tok1, tok2, lengths, is_dollar)
            ),
        )
        bp = len(padded)
        bl = bp // self.n_batch
        cap_local = 0
        compact_dev = None
        if self.compact:
            # compact the gathered result ON DEVICE before any transfer:
            # the [S, B, K] slot buffer collapses to per-tile topic-major
            # (shard, sid) pair streams sized for the hits that exist
            cap_local = max(
                16, self._compact_capacity_for(bp) // self.n_batch
            )
            compact_dev = self._get_compact_step(cap_local)(
                out_dev, totals_dev, overflow_dev
            )
            try:
                compact_dev.copy_to_host_async()
            except AttributeError:  # pragma: no cover - older jax arrays
                pass
        if prof is not None:
            # device pipeline profiler: the SPMD issue leg ends here; every
            # mesh device participated in the step, so the per-device
            # windows (ISSUE 18) each get this batch's window
            rec.devices = self._device_ids
            prof.note_dispatch(rec, t_issue0, time.perf_counter())
        # accept both route forms (ops/matcher.py): a plain predicate or
        # the delta overlay object exposing .affected
        if route_to_host is not None and hasattr(route_to_host, "affected"):
            route_to_host = route_to_host.affected
        # the pre-compaction transfer geometry: the full gathered slot
        # buffer — what the resolver synced before this PR
        bytes_padded = self.n_shards * bp * self.out_slots * 4

        def resolve_full(t_sync0: float) -> list[Subscribers]:
            # brokerlint: ok=R15 the blessed resolve seam: one D2H per array after copy_to_host_async, [S, B, K]
            out = np.asarray(out_dev)
            # brokerlint: ok=R15 same resolve seam, the [B] overflow mask rides the batched readback
            overflow = np.asarray(overflow_dev).any(axis=0) | len_overflow
            self.stats.d2h_bytes += int(out.nbytes)
            if prof is not None:
                rec.d2h_bytes += int(out.nbytes)
                rec.d2h_bytes_ranges += int(out.nbytes)
                rec.d2h_bytes_dense += bytes_padded
                prof.note_resolve(rec, t_sync0, time.perf_counter())
            results = []
            stats = self.stats
            acc = _accel()  # once per batch, not per topic
            for i, topic in enumerate(topics):
                if not topic:
                    results.append(Subscribers())
                elif overflow[i] or (
                    route_to_host is not None and route_to_host(topic)
                ):
                    stats.host_fallbacks += 1
                    stats.overflows += int(overflow[i])
                    results.append(self.topics.subscribers(topic))
                else:
                    results.append(self._expand(tables, out[:, i, :], acc))
            return results

        if compact_dev is None:

            def resolve() -> list[Subscribers]:
                t_sync0 = time.perf_counter() if prof is not None else 0.0
                self.stats.batches += 1
                self.stats.topics += b
                return resolve_full(t_sync0)

            return resolve

        def resolve_compact() -> list[Subscribers]:
            t_sync0 = time.perf_counter() if prof is not None else 0.0
            # [n_batch, 2 + 2*bl + 2*cap_local]: one compacted row per
            # batch tile (shard_map over the batch axis)
            # brokerlint: ok=R15 the blessed resolve seam: ONE compacted-row D2H after copy_to_host_async
            rows = np.asarray(compact_dev)
            stats = self.stats
            stats.batches += 1
            stats.topics += b
            n_hits = int(rows[:, 0].sum())
            batch_ovf = bool(rows[:, 1].any())
            self._observe_hits(n_hits, b)
            # per-tile imbalance fold (ISSUE 18): every resolved batch —
            # including the overflow fallback, whose tile counts are
            # saturated-but-honest — feeds the skew gauge
            self._fold_tile_hits(np.asarray(rows[:, 0]), cap_local)
            if batch_ovf:
                # a tile outgrew its pair buffer: fall back to the full
                # gathered transfer for THIS batch only (the device
                # arrays are still resident — one extra sync, no
                # recompute)
                stats.compact_overflows += 1
                self._hits_ewma = max(self._hits_ewma, n_hits / max(1, b))
                # the compacted stream was synced too: both transfers
                # count (resolve_full adds the full gather's bytes)
                stats.d2h_bytes += int(rows.nbytes)
                if rec is not None:
                    rec.compact = True
                    rec.compact_overflow = True
                    rec.d2h_bytes = int(rows.nbytes)
                return resolve_full(t_sync0)
            stats.compact_batches += 1
            stats.d2h_bytes += int(rows.nbytes)
            if prof is not None:
                rec.d2h_bytes = int(rows.nbytes)
                rec.d2h_bytes_ranges = bytes_padded
                rec.d2h_bytes_dense = bytes_padded
                rec.compact = True
                prof.note_resolve(rec, t_sync0, time.perf_counter())
            # stitch the per-tile streams back into one topic-major batch
            per_topic = rows[:, 2 : 2 + bl].reshape(bp)
            true_overflow = (
                rows[:, 2 + bl : 2 + 2 * bl].reshape(bp).astype(bool)
                | len_overflow
            )
            tile_hits = rows[:, 0]
            pair_shard = np.concatenate(
                [
                    rows[t, 2 + 2 * bl : 2 + 2 * bl + tile_hits[t]]
                    for t in range(rows.shape[0])
                ]
            ) if n_hits else np.zeros(0, dtype=rows.dtype)
            pair_sid = np.concatenate(
                [
                    rows[
                        t,
                        2 + 2 * bl + cap_local : 2 + 2 * bl + cap_local
                        + tile_hits[t],
                    ]
                    for t in range(rows.shape[0])
                ]
            ) if n_hits else np.zeros(0, dtype=rows.dtype)
            host_route = true_overflow.copy()
            if route_to_host is not None:
                for i, topic in enumerate(topics):
                    if topic and route_to_host(topic):
                        host_route[i] = True
            return materialize_compact_pairs(
                stats,
                self.topics.subscribers,
                pair_sid,
                pair_shard,
                per_topic,
                host_route,
                n_hits,
                topics,
                None,
                self.window,
                true_overflow,
                tables=tables,
                lazy=self.lazy,
            )

        return resolve_compact

    def _compact_capacity_for(self, b_padded: int) -> int:
        """Pair-buffer capacity for one gathered batch (the shared
        pick_compact_capacity policy), capped at the slot-buffer bound
        the gather could actually fill."""
        max_hits = b_padded * self.n_shards * self.out_slots
        return pick_compact_capacity(
            self.compact_capacity, self._hits_ewma, b_padded, max_hits,
            self._caps,
        )

    def _observe_hits(self, n_hits: int, b: int) -> None:
        self._hits_ewma = fold_hits_ewma(self._hits_ewma, n_hits, b)

    def match_topics(self, topics: list[str], route_to_host=None) -> list[Subscribers]:
        """Match a batch of topics; every result is bit-identical to the
        host trie (overflowing topics are re-walked on host).

        ``route_to_host`` optionally forces extra topics onto the host walk
        (the delta overlay's affected-check); the host path is always
        correct, so any predicate preserves parity."""
        return self.match_topics_async(topics, route_to_host)()

    def subscribers(self, topic: str) -> Subscribers:
        return self.match_topics([topic])[0]

    def _expand(self, tables, shard_sids: np.ndarray, acc) -> Subscribers:
        """Union per-shard local sub ids into one Subscribers set (the C
        materializer when given — same merge semantics, pinned by the
        tests/test_native.py differentials; expand_sids otherwise). The
        caller resolves ``acc`` once per batch, not per topic."""
        subs = Subscribers()
        if acc is not None:
            for s in range(self.n_shards):
                acc.expand_sids_list(
                    shard_sids[s].tolist(), tables[s].snaps, tables[s].window, subs
                )
            return subs
        for s in range(self.n_shards):
            expand_sids(tables[s], shard_sids[s], subs, seen=set())
        return subs


def dryrun_multichip(n_devices: int) -> None:
    """Create an ``n_devices`` mesh, jit the FULL sharded match step (batch
    DP x subscription sharding with an all_gather union over ICI), and run
    one step on tiny shapes. The driver invokes this on a virtual CPU mesh
    to validate the multi-chip path without hardware."""
    # The environment may pin a single-accelerator default platform (e.g.
    # one real TPU) whose plugin may not even be healthy in the driver
    # sandbox. The dryrun must never touch any non-CPU backend: pin the
    # platform to cpu (both the env var and the live config) and provision
    # n virtual CPU devices BEFORE the first backend query — clients read
    # their config at first use.
    import os

    prior_platforms = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # brokerlint: ok=R4 backend already initialized; the cpu query below still tries
        pass
    try:
        _dryrun_body(n_devices)
    finally:
        # the in-process pin is unavoidably sticky once jax initializes, but
        # the env mutation must not leak into child processes spawned later
        if prior_platforms is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prior_platforms


def _dryrun_body(n_devices: int) -> None:
    import os
    import re

    try:
        # only ever raise the count — the config value overrides a larger
        # XLA_FLAGS request, so clamping down would break later callers
        m = re.search(
            r"--xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        current = max(
            int(m.group(1)) if m else 1,
            int(getattr(jax.config, "jax_num_cpu_devices", 0) or 0),
        )
        jax.config.update("jax_num_cpu_devices", max(n_devices, current))
        provisioned = True
    except Exception:  # already-initialized backend or older jax
        provisioned = False
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None or int(m.group(1)) < n_devices:
            new_flag = f"--xla_force_host_platform_device_count={n_devices}"
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", new_flag, flags
            ) if m else f"{flags} {new_flag}".strip()
            os.environ["XLA_FLAGS"] = flags
    # query ONLY the cpu backend — a bare jax.devices() initializes every
    # registered platform plugin, which is exactly the failure mode in a
    # TPU-unhealthy driver environment (MULTICHIP_r01)
    try:
        devices = jax.devices("cpu")
    except RuntimeError:
        # backends already initialized under a platform set without cpu
        devices = []
    if len(devices) < n_devices:
        # last resort, for a host whose backends were already initialized
        # before this call (so CPU provisioning couldn't apply) but which
        # has n real accelerators: run on those. Never reached when the CPU
        # provisioning above succeeded, so the driver path stays CPU-only.
        try:
            all_devices = jax.devices()
            if len(all_devices) >= n_devices:
                devices = all_devices
        except Exception:  # brokerlint: ok=R4 last-resort device query; the count check below raises the real error
            pass
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}"
            + (
                ""
                if provisioned
                else " — a JAX backend was initialized before dryrun_multichip()"
                " could provision virtual CPU devices; call it first in the"
                " process or set XLA_FLAGS=--xla_force_host_platform_device_count"
                f"={n_devices} before starting python"
            )
        )
    devices = devices[:n_devices]
    mesh = make_mesh(devices)
    from ..packets import Subscription

    index = TopicsIndex()
    filters = ["a/b/c", "a/+/c", "a/#", "d/e", "+/e", "x/y/z", "q/+/+", "#"]
    for i, flt in enumerate(filters * 4):
        index.subscribe(f"cl{i}", Subscription(filter=flt, qos=i % 3))
    matcher = ShardedTpuMatcher(index, mesh=mesh, max_levels=4, out_slots=32)
    try:
        topics = ["a/b/c", "d/e", "x/y/z", "q/w/e", "nope", "a/z/c", "e", "a/b"]
        results = matcher.match_topics(topics)
        # verify against the host oracle — the dryrun must not just compile
        for topic, dev in zip(topics, results):
            host = index.subscribers(topic)
            assert set(dev.subscriptions) == set(host.subscriptions), (
                topic, set(dev.subscriptions), set(host.subscriptions)
            )
        # exercise the incremental path: one mutation must dirty exactly one
        # shard and still produce oracle-identical results after rebuild
        index.subscribe("late", Subscription(filter="a/b/c", qos=1))
        index.unsubscribe("d/e", "cl3")
        for topic in topics:
            dev = matcher.subscribers(topic)
            host = index.subscribers(topic)
            assert set(dev.subscriptions) == set(host.subscriptions), topic
    finally:
        matcher.close()
    # the live-broker configuration: DeltaMatcher folding trie churn over a
    # mesh-sharded snapshot (the round-2 regression shipped because no
    # driver check covered this combination)
    from ..ops.delta import DeltaMatcher

    dm = DeltaMatcher(index, mesh=mesh, max_levels=4, background=False)
    try:
        index.subscribe("churn", Subscription(filter="a/+/c", qos=1))
        for topic in topics:
            dev = dm.subscribers(topic)  # overlay: churned topics host-route
            host = index.subscribers(topic)
            assert set(dev.subscriptions) == set(host.subscriptions), topic
        dm.flush()  # fold the overlay into a fresh per-shard snapshot
        assert dm.pending_deltas == 0
        for topic in topics:
            dev = dm.subscribers(topic)
            host = index.subscribers(topic)
            assert set(dev.subscriptions) == set(host.subscriptions), topic
    finally:
        dm.close()
