"""Subscription-sharded matching over a 2D device mesh.

Mesh axes:

- ``batch`` — data parallelism over the PUBLISH topic batch
- ``subs``  — model-style parallelism over the subscription set: each device
  along this axis holds the CSR trie of its subscription shard

One jitted step matches every (topic-shard, sub-shard) tile locally and
``all_gather``s the per-shard match lists over the ``subs`` axis (ICI), so
every batch row ends with the full union of sub ids. The host maps local
sub ids through per-shard tables and merges — bit-identical to the
single-device matcher, which is bit-identical to the host trie.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map

    _REP_KWARG = "check_vma"
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map

    _REP_KWARG = "check_rep"


def shard_map(*args, disable_rep_check=False, **kwargs):
    if disable_rep_check:
        kwargs[_REP_KWARG] = False
    return _shard_map(*args, **kwargs)

from ..packets import Subscription
from ..topics import Subscribers, TopicsIndex
from ..ops.csr import KIND_CLIENT, KIND_SHARED, build_csr
from ..ops.hashing import tokenize_topics
from ..ops.matcher import _pad_to, expand_sids, match_core


def make_mesh(devices=None, batch_axis: Optional[int] = None) -> Mesh:
    """A 2D (batch, subs) mesh over the given (default: all) devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if batch_axis is None:
        batch_axis = 2 if n % 2 == 0 and n > 1 else 1
    subs_axis = n // batch_axis
    grid = np.array(devices[: batch_axis * subs_axis]).reshape(batch_axis, subs_axis)
    return Mesh(grid, ("batch", "subs"))


class ShardedTpuMatcher:
    """Shards a TopicsIndex's subscriptions across the ``subs`` mesh axis
    and matches topic batches with one SPMD step."""

    def __init__(
        self,
        topics: TopicsIndex,
        mesh: Optional[Mesh] = None,
        max_levels: int = 8,
        frontier: int = 16,
        out_slots: int = 64,
    ) -> None:
        self.topics = topics
        self.mesh = mesh or make_mesh()
        self.max_levels = max_levels
        self.frontier = frontier
        self.out_slots = out_slots
        self.n_shards = self.mesh.shape["subs"]
        self.n_batch = self.mesh.shape["batch"]
        self.shard_tables: list[list] = []
        self.shard_salts: list[int] = []
        self._arrays: Optional[tuple] = None
        self._step = None
        self._built_version = -1
        self._search_iters = 4

    # -- build -------------------------------------------------------------

    def rebuild(self) -> None:
        """Partition subscriptions round-robin into per-shard tries, compile
        each to CSR, pad to common shapes, and stack on the shard axis."""
        version = self.topics.version
        full = build_csr(self.topics)
        shard_indexes = [TopicsIndex() for _ in range(self.n_shards)]
        for i, entry in enumerate(full.subs):
            target = shard_indexes[i % self.n_shards]
            if entry.kind in (KIND_CLIENT, KIND_SHARED):
                target.subscribe(entry.client, entry.subscription)
            else:
                target.inline_subscribe(entry.subscription)
        csrs = [build_csr(ix, salt=full.salt) for ix in shard_indexes]
        self.shard_tables = [c.subs for c in csrs]
        self.shard_salts = [c.salt for c in csrs]
        if len(set(self.shard_salts)) != 1 or self.shard_salts[0] != full.salt:
            # extremely unlikely (per-shard salt bump); rebuild all on the
            # highest salt so topic hashing is uniform across shards
            salt = max(self.shard_salts)
            csrs = [build_csr(ix, salt=salt) for ix in shard_indexes]
            self.shard_tables = [c.subs for c in csrs]
            self.shard_salts = [c.salt for c in csrs]

        def stack(get, fill=0, min_len=1):
            arrs = [np.asarray(get(c)) for c in csrs]
            n = max(min_len, max(len(a) for a in arrs))
            return np.stack([_pad_to(a, n, fill) for a in arrs])

        max_degree = max(c.max_degree for c in csrs)
        self._search_iters = max(1, int(np.ceil(np.log2(max(2, max_degree + 1)))) + 1)
        # place every stacked array on the mesh ONCE, leading (shard) dim
        # split over the ``subs`` axis — an explicit NamedSharding, NOT a
        # default-device jnp.asarray, so no other backend (e.g. a real TPU
        # when the mesh is a virtual CPU one) is ever touched
        shard_sharding = NamedSharding(self.mesh, P("subs"))
        self._arrays = tuple(
            jax.device_put(np.asarray(a), shard_sharding)
            for a in (
                stack(lambda c: c.edge_ptr, min_len=2),
                stack(lambda c: c.edge_tok1.astype(np.uint32)),
                stack(lambda c: c.edge_tok2.astype(np.uint32)),
                stack(lambda c: c.edge_dest, fill=-1),
                stack(lambda c: c.plus_child, fill=-1),
                stack(lambda c: c.hash_child, fill=-1),
                stack(lambda c: c.reg_ptr, min_len=2),
                stack(lambda c: c.inl_ptr, min_len=2),
                stack(
                    lambda c: np.concatenate([c.reg_ids, c.inl_ids]).astype(np.int32),
                    fill=-1,
                ),
                np.asarray([np.int32(len(c.reg_ids)) for c in csrs]),
                stack(lambda c: c.top_wild.astype(bool)),
            )
        )
        self._compile_step()
        self._built_version = version

    def _compile_step(self) -> None:
        mesh = self.mesh
        frontier, out_slots, iters = self.frontier, self.out_slots, self._search_iters

        def step(
            edge_ptr, edge_tok1, edge_tok2, edge_dest, plus_child, hash_child,
            reg_ptr, inl_ptr, all_ids, inl_offset, top_wild,
            tok1, tok2, lengths, is_dollar,
        ):
            # each device: its sub shard (leading dim 1) x its batch tile
            out, totals, overflow = match_core(
                edge_ptr[0], edge_tok1[0], edge_tok2[0], edge_dest[0],
                plus_child[0], hash_child[0], reg_ptr[0], inl_ptr[0],
                all_ids[0], inl_offset[0], top_wild[0],
                tok1, tok2, lengths, is_dollar,
                frontier=frontier, out_slots=out_slots, search_iters=iters,
            )
            # union across the subs axis rides ICI
            out_g = jax.lax.all_gather(out, "subs")  # [S, b_local, K]
            tot_g = jax.lax.all_gather(totals, "subs")  # [S, b_local]
            ovf_g = jax.lax.all_gather(overflow, "subs")
            return out_g, tot_g, ovf_g

        shard_spec = P("subs")
        batch_spec = P("batch")
        self._step = jax.jit(
            shard_map(
                step,
                mesh=mesh,
                in_specs=(shard_spec,) * 9 + (P("subs"), shard_spec)
                + (batch_spec,) * 4,
                out_specs=(P(None, "batch", None), P(None, "batch"), P(None, "batch")),
                disable_rep_check=True,
            )
        )

    @property
    def stale(self) -> bool:
        return self._built_version != self.topics.version

    # -- matching ----------------------------------------------------------

    def match_topics(self, topics: list[str]) -> list[Subscribers]:
        if self._arrays is None or self.stale:
            self.rebuild()
        b = len(topics)
        # pad the batch to a multiple of the batch axis
        pad = (-b) % self.n_batch
        padded = topics + [""] * pad
        tok1, tok2, lengths, is_dollar, len_overflow = tokenize_topics(
            padded, self.max_levels, self.shard_salts[0]
        )
        batch_sharding = NamedSharding(self.mesh, P("batch"))
        out, totals, overflow = self._step(
            *self._arrays,
            *(
                jax.device_put(np.asarray(a), batch_sharding)
                for a in (tok1, tok2, lengths, is_dollar)
            ),
        )
        out = np.asarray(out)  # [S, B, K]
        overflow = np.asarray(overflow).any(axis=0) | len_overflow  # [B]
        results = []
        for i, topic in enumerate(topics):
            if not topic:
                results.append(Subscribers())
            elif overflow[i]:
                results.append(self.topics.subscribers(topic))
            else:
                results.append(self._expand(out[:, i, :]))
        return results

    def subscribers(self, topic: str) -> Subscribers:
        return self.match_topics([topic])[0]

    def _expand(self, shard_sids: np.ndarray) -> Subscribers:
        """Union per-shard local sub ids into one Subscribers set."""
        subs = Subscribers()
        for s in range(self.n_shards):
            expand_sids(self.shard_tables[s], shard_sids[s], subs, seen=set())
        return subs


def dryrun_multichip(n_devices: int) -> None:
    """Create an ``n_devices`` mesh, jit the FULL sharded match step (batch
    DP x subscription sharding with an all_gather union over ICI), and run
    one step on tiny shapes. The driver invokes this on a virtual CPU mesh
    to validate the multi-chip path without hardware."""
    # The environment may pin a single-accelerator default platform (e.g.
    # one real TPU) whose plugin may not even be healthy in the driver
    # sandbox. The dryrun must never touch any non-CPU backend: pin the
    # platform to cpu (both the env var and the live config) and provision
    # n virtual CPU devices BEFORE the first backend query — clients read
    # their config at first use.
    import os

    prior_platforms = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized; the cpu query below still tries
    try:
        _dryrun_body(n_devices)
    finally:
        # the in-process pin is unavoidably sticky once jax initializes, but
        # the env mutation must not leak into child processes spawned later
        if prior_platforms is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prior_platforms


def _dryrun_body(n_devices: int) -> None:
    import os
    import re

    try:
        # only ever raise the count — the config value overrides a larger
        # XLA_FLAGS request, so clamping down would break later callers
        m = re.search(
            r"--xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        current = max(
            int(m.group(1)) if m else 1,
            int(getattr(jax.config, "jax_num_cpu_devices", 0) or 0),
        )
        jax.config.update("jax_num_cpu_devices", max(n_devices, current))
        provisioned = True
    except Exception:  # already-initialized backend or older jax
        provisioned = False
        flags = os.environ.get("XLA_FLAGS", "")
        m = re.search(r"--xla_force_host_platform_device_count=(\d+)", flags)
        if m is None or int(m.group(1)) < n_devices:
            new_flag = f"--xla_force_host_platform_device_count={n_devices}"
            flags = re.sub(
                r"--xla_force_host_platform_device_count=\d+", new_flag, flags
            ) if m else f"{flags} {new_flag}".strip()
            os.environ["XLA_FLAGS"] = flags
    # query ONLY the cpu backend — a bare jax.devices() initializes every
    # registered platform plugin, which is exactly the failure mode in a
    # TPU-unhealthy driver environment (MULTICHIP_r01)
    try:
        devices = jax.devices("cpu")
    except RuntimeError:
        # backends already initialized under a platform set without cpu
        devices = []
    if len(devices) < n_devices:
        # last resort, for a host whose backends were already initialized
        # before this call (so CPU provisioning couldn't apply) but which
        # has n real accelerators: run on those. Never reached when the CPU
        # provisioning above succeeded, so the driver path stays CPU-only.
        try:
            all_devices = jax.devices()
            if len(all_devices) >= n_devices:
                devices = all_devices
        except Exception:
            pass
    if len(devices) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(devices)}"
            + (
                ""
                if provisioned
                else " — a JAX backend was initialized before dryrun_multichip()"
                " could provision virtual CPU devices; call it first in the"
                " process or set XLA_FLAGS=--xla_force_host_platform_device_count"
                f"={n_devices} before starting python"
            )
        )
    devices = devices[:n_devices]
    mesh = make_mesh(devices)
    index = TopicsIndex()
    filters = ["a/b/c", "a/+/c", "a/#", "d/e", "+/e", "x/y/z", "q/+/+", "#"]
    for i, flt in enumerate(filters * 4):
        index.subscribe(f"cl{i}", Subscription(filter=flt, qos=i % 3))
    matcher = ShardedTpuMatcher(index, mesh=mesh, max_levels=4, frontier=8, out_slots=32)
    topics = ["a/b/c", "d/e", "x/y/z", "q/w/e", "nope", "a/z/c", "e", "a/b"]
    results = matcher.match_topics(topics)
    # verify against the host oracle — the dryrun must not just compile
    for topic, dev in zip(topics, results):
        host = index.subscribers(topic)
        assert set(dev.subscriptions) == set(host.subscriptions), (
            topic, set(dev.subscriptions), set(host.subscriptions)
        )
