"""The broker server: lifecycle, CONNECT handshake, packet dispatch, QoS
flows, retained/LWT/$SYS handling, expiry loops, and persistence restore.

Behavioral parity with reference ``server.go`` (the per-symbol map lives in
SURVEY.md §2.1). The reference's goroutine-per-connection becomes an asyncio
task per connection; the five housekeeping tickers become one asyncio event
loop task (server.go:374-395); everything else is a synchronous call graph
identical in shape to the reference's.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from dataclasses import dataclass, field, replace
from typing import Any, Callable, Optional

from . import packets as pkts
from .clients import Client, Clients, ConnectionClosedError, Will
from .hooks import (
    ON_PACKET_ENCODE,
    ON_PACKET_PROCESSED,
    ON_PACKET_READ,
    ON_PACKET_SENT,
    ON_PUBLISH,
    ON_PUBLISHED,
    STORED_CLIENTS,
    STORED_INFLIGHT_MESSAGES,
    STORED_RETAINED_MESSAGES,
    STORED_SUBSCRIPTIONS,
    STORED_SYS_INFO,
    Hook,
    HookOptions,
    Hooks,
)
from .listeners import (
    TYPE_HEALTHCHECK,
    TYPE_MOCK,
    TYPE_SYSINFO,
    TYPE_TCP,
    TYPE_UNIX,
    TYPE_WS,
    Config as ListenerConfig,
    Listener,
    Listeners,
    MockListener,
    TCP,
)
from .packets import (
    CODE_DISCONNECT,
    CODE_DISCONNECT_WILL_MESSAGE,
    CODE_SUCCESS,
    CODE_SUCCESS_IGNORE,
    ERR_BAD_USERNAME_OR_PASSWORD,
    ERR_INLINE_SUBSCRIPTION_HANDLER_INVALID,
    ERR_NOT_AUTHORIZED,
    ERR_PACKET_IDENTIFIER_IN_USE,
    ERR_PACKET_IDENTIFIER_NOT_FOUND,
    ERR_PENDING_CLIENT_WRITES_EXCEEDED,
    ERR_PROTOCOL_VIOLATION_INVALID_SHARED_NO_LOCAL,
    ERR_PROTOCOL_VIOLATION_REQUIRE_FIRST_CONNECT,
    ERR_PROTOCOL_VIOLATION_SECOND_CONNECT,
    ERR_PROTOCOL_VIOLATION_ZERO_NON_ZERO_EXPIRY,
    ERR_QOS_NOT_SUPPORTED,
    ERR_QUOTA_EXCEEDED,
    ERR_RECEIVE_MAXIMUM,
    ERR_REJECT_PACKET,
    ERR_RETAIN_NOT_SUPPORTED,
    ERR_SERVER_BUSY,
    ERR_SERVER_SHUTTING_DOWN,
    ERR_SERVER_UNAVAILABLE,
    ERR_SESSION_TAKEN_OVER,
    ERR_TOPIC_FILTER_INVALID,
    ERR_UNSPECIFIED_ERROR,
    ERR_UNSUPPORTED_PROTOCOL_VERSION,
    QOS_CODES,
    V5_CODES_TO_V3,
    Code,
    FixedHeader,
    Packet,
    PacketStore,
    Properties,
    Subscription,
    UserProperty,
)
from .system import Info
from .utils.mempool import get_buffer, put_buffer
from .utils.loopwitness import DEFAULT_LOOP_PLANE as _LOOP_PLANE
from .utils.proc import rss_bytes
from .topics import (
    NS_CHAR,
    SYS_PREFIX,
    InlineSubFn,
    InlineSubscription,
    TopicsIndex,
    is_shared_filter,
    is_valid_filter,
    ns_local,
    ns_scope_filter,
    ns_scope_topic,
    ns_tenant,
    split_predicate_suffix,
)

VERSION = "0.1.0"  # our framework version (reference tracks 2.7.9)
DEFAULT_SYS_TOPIC_INTERVAL = 1  # seconds between $SYS publishes
LOCAL_LISTENER = "local"
INLINE_CLIENT_ID = "inline"

MAX_INT64 = (1 << 63) - 1
MAX_UINT32 = (1 << 32) - 1


class ListenerIDExistsError(Exception):
    """A listener with the same id already exists."""


class InlineClientNotEnabledError(Exception):
    """Options.inline_client must be True to use inline pub/sub."""


@dataclass
class Compatibilities:
    """Compatibility-mode flags (server.go:86-93)."""

    obscure_not_authorized: bool = False
    passive_client_disconnect: bool = False
    always_return_response_info: bool = False
    restore_sys_info_on_restart: bool = False
    no_inherited_properties_on_ack: bool = False


@dataclass
class Capabilities:
    """Server features and limits (server.go:46-84)."""

    maximum_clients: int = MAX_INT64
    maximum_message_expiry_interval: int = 60 * 60 * 24
    maximum_client_writes_pending: int = 1024 * 8
    maximum_session_expiry_interval: int = MAX_UINT32
    maximum_packet_size: int = 0
    maximum_packet_id: int = 0xFFFF
    receive_maximum: int = 1024
    maximum_inflight: int = 1024 * 8
    topic_alias_maximum: int = 0xFFFF
    shared_sub_available: int = 1
    minimum_protocol_version: int = 3
    compatibilities: Compatibilities = field(default_factory=Compatibilities)
    maximum_qos: int = 2
    retain_available: int = 1
    wildcard_sub_available: int = 1
    sub_id_available: int = 1


@dataclass
class Options:
    """Configurable server options (server.go:96-131)."""

    listeners: list[ListenerConfig] = field(default_factory=list)
    hooks: list[tuple[Hook, Any]] = field(default_factory=list)
    capabilities: Capabilities = field(default_factory=Capabilities)
    client_net_write_buffer_size: int = 0
    client_net_read_buffer_size: int = 0
    logger: Optional[logging.Logger] = None
    sys_topic_resend_interval: int = 0
    inline_client: bool = False
    # route publish-topic matching through the delta-staged device matcher
    # (mqtt_tpu.ops.delta.DeltaMatcher) instead of the host trie walk; results
    # are bit-identical, the index lives on the TPU (SURVEY.md north star)
    device_matcher: bool = False
    # kwargs forwarded to DeltaMatcher (max_levels, out_slots, window,
    # transfer_slots, rebuild_after, rebuild_interval, mesh, ...)
    matcher_opts: Optional[dict] = None
    # publish staging loop (mqtt_tpu.staging): accumulation window and batch
    # cap for device match batches; pipeline depth for in-flight batches
    matcher_stage_window_ms: float = 2.0
    matcher_stage_max_batch: int = 4096
    matcher_stage_max_inflight: int = 4
    # p99 latency budget for one staged publish (staging.MatchStage adapts
    # window + batch cap to hold it); <= 0 disables adaptation
    matcher_stage_latency_budget_ms: float = 250.0
    # overlapped-staging depth (mqtt_tpu.staging): batches in flight
    # across the h2d-tokenize / device-dispatch / d2h-drain legs
    # (ROADMAP item 1); <= 0 falls back to matcher_stage_max_inflight
    matcher_stage_pipeline_depth: int = 3
    # device-resident hit compaction (ops/flat.flat_match_compact):
    # match results transfer as packed (topic_idx, sid) pairs sized for
    # the hits that exist; a batch whose hits outgrow the pair buffer
    # falls back to the padded path for that batch only
    matcher_compact: bool = True
    # pinned pair-buffer capacity; 0 = adaptive from the observed
    # hits-per-topic EWMA (seeded by the TopicSketch's avg_hits_per_topic
    # when the host observatory is on)
    matcher_compact_capacity: int = 0
    # zero-materialization fan-out (ISSUE 13): device match results stay
    # lazy SubscribersView objects over the compacted pair stream /
    # ranges rows (native/accelmod.c); fan-out consumes (client, sub)
    # targets straight off the view and per-hit objects come from a
    # bounded freelist pool. Consumers needing dict semantics
    # (predicates, shared groups, the resilience differential)
    # transparently materialize — bit-identical to the eager path, which
    # stays on as the differential oracle. No C toolchain = eager.
    matcher_lazy_views: bool = True
    # encode-once batched fan-out (ISSUE 13 / ROADMAP item 3): group
    # fan-out targets by (protocol version, effective QoS, retain)
    # variant, encode each variant's wire frame ONCE, patch per-target
    # packet ids in a C writev-style flush that releases the GIL across
    # the delivery batch (per-socket backpressure, slow-consumer
    # eviction and overload accounting all preserved). False = the
    # per-subscriber encode path everywhere.
    fanout_batch: bool = True
    # read-side decode batching: coalesce frame scans from read loops
    # that wake in the same event-loop tick into one native multi-buffer
    # scan call. Opt-in: it adds one loop-callback hop per socket read,
    # which only pays off at high connection counts. Inside the shard
    # fabric (loop_shards > 1) the gate is PER-SHARD and default-on
    # regardless of this knob.
    scan_coalesce: bool = False
    # event-loop shard fabric (mqtt_tpu.shards / ROADMAP item 4): the
    # connection front-end as N threads each running its own event loop
    # owning thousands of connections, with accepted sockets dispatched
    # to the least-loaded shard. 1 (default) preserves today's
    # single-loop behavior bit-for-bit — no fabric code runs at all.
    loop_shards: int = 1
    # fabric accept mode: "handoff" (default — the main loop accepts
    # and routes each bare socket to the least-loaded shard; exact
    # least-loaded spread) or "reuseport" (every shard binds its own
    # SO_REUSEPORT socket and accepts on its own loop; kernel load
    # balancing, no hand-off hop; falls back to handoff where
    # SO_REUSEPORT is unavailable)
    loop_shard_accept: str = "handoff"
    # degradation manager (mqtt_tpu.resilience): wrap every device dispatch
    # in a circuit breaker + hang watchdog; timeouts/errors/corrupt results
    # route matching to the bit-identical host trie and background probes
    # re-admit the device once verified healthy. Default on — a flapping
    # link must degrade, never wedge.
    matcher_resilience: bool = True
    # consecutive failures before the breaker trips OPEN
    breaker_failure_threshold: int = 3
    # per-batch hang budget: a dispatch not resolved within this is
    # abandoned and served from the host walk. A last-resort hang bound,
    # NOT a latency control — keep it above worst-case cold-compile time.
    breaker_watchdog_ms: float = 5000.0
    # half-open probe schedule: exponential backoff from the base delay up
    # to the max, +/- the jitter fraction; this many verified-healthy
    # probes close the breaker
    breaker_probe_backoff_ms: float = 500.0
    breaker_probe_backoff_max_ms: float = 30000.0
    breaker_probe_jitter: float = 0.1
    breaker_probe_successes: int = 2
    # topics differentially re-walked on the host per healthy batch (the
    # corrupt-result tripwire); 0 disables sampling outside probes
    breaker_verify_sample: int = 1
    # raise the process-global CPython GC thresholds for broker throughput
    # (utils/gctune.py). Default on for the standalone broker; an embedding
    # application that wants its own GC cadence sets this False (the change
    # is process-wide and logged at info level)
    gc_tuning: bool = True
    # broker-wide overload control plane (mqtt_tpu.overload): a NORMAL ->
    # THROTTLE -> SHED governor over staging depth, aggregate outbound
    # backlog, cluster peer buffers, and an optional RSS watermark.
    # Default on — a publish storm must degrade predictably (throttled
    # reads, 0x97 sheds, slow-consumer eviction), never OOM.
    overload_control: bool = True
    # hysteresis bands over the max normalized pressure (enter > exit)
    overload_throttle_enter: float = 0.70
    overload_throttle_exit: float = 0.50
    overload_shed_enter: float = 0.90
    overload_shed_exit: float = 0.65
    # minimum ms in a state before de-escalating (escalation is instant)
    overload_min_dwell_ms: float = 500.0
    # governor evaluation cadence (lazy re-sample on the data plane)
    overload_eval_interval_ms: float = 250.0
    # per-client quota window (publish/shed budgets); 0 = eval interval
    overload_quota_window_ms: float = 0.0
    # THROTTLE: per-client publishes per window before reads pause, and
    # the pause applied to each subsequent read
    overload_publish_quota: int = 2048
    overload_throttle_delay_ms: float = 50.0
    # SHED: per-client publishes admitted per window (excess sheds:
    # QoS0 dropped, QoS1/2 acked 0x97 Quota Exceeded)
    overload_shed_quota: int = 256
    # SHED: outbound-queue-full grace before slow-consumer eviction
    # (DISCONNECT 0x97)
    overload_eviction_grace_ms: float = 2000.0
    # staging admission bound: MatchStage._pending never exceeds this
    # (overflow resolves via the deadline-aware host walk)
    overload_stage_max_pending: int = 8192
    # per-client transport write-buffer watermark (bytes): a client whose
    # buffered-but-unsent outbound bytes stay above this past the grace
    # window is a slow consumer (asyncio buffers writes unboundedly — the
    # broker-side OOM vector a non-reading subscriber creates)
    overload_client_buffer_limit_bytes: int = 1024 * 1024
    # aggregate outbound backlog (sum of queued publishes across all
    # clients) that normalizes to pressure 1.0
    overload_max_outbound_backlog: int = 65536
    # RSS watermark in MB that normalizes to pressure 1.0; 0 disables
    # the memory signal
    overload_memory_limit_mb: float = 0.0
    # mesh federation (mqtt_tpu.cluster gossip -> mqtt_tpu.overload):
    # fold peer workers' advertised governor postures into this worker's
    # pressure as a decayed-max "peers" signal, so one shedding worker
    # raises the whole mesh to THROTTLE instead of the rest pumping
    # publishes into it
    overload_federation: bool = True
    # scale applied to the peers signal (< 1 so a SHED advert lands the
    # mesh in THROTTLE, not a sympathetic full-mesh SHED cascade)
    overload_federation_weight: float = 0.9
    # gossip adverts decay linearly to zero over this TTL and then age
    # out entirely (a dead worker must not pin the mesh's posture)
    overload_federation_ttl_ms: float = 15000.0
    # per-listener CONNECT admission: while THROTTLE/SHED, new CONNECTs
    # on admission-gated listeners are refused with CONNACK 0x97 (0x89
    # while the server drains); False disables the gate entirely
    overload_admission: bool = True
    # always-admit reserve per quota window for $SYS/admin-ACL clients
    # (the operator's monitoring session must get in mid-storm)
    overload_admission_reserve: int = 2
    # priority-weighted shedding: class name -> quota multiplier applied
    # to both the shed and publish quotas (None = every client weighs 1)
    overload_priority_classes: Optional[dict] = None
    # username-or-client-id -> class name (assigned at CONNECT; embedders
    # can also set cl.priority_weight directly from an on_connect hook)
    overload_priority_users: Optional[dict] = None
    # mesh peer health (mqtt_tpu.cluster): consecutive unanswered pings
    # before a peer goes SUSPECT (QoS>0 forwards park in a bounded
    # buffer) and before it is declared PARTITIONED (park flushed into
    # the partition drop counters, link aborted for a clean re-dial)
    cluster_peer_health_suspect_pings: int = 2
    cluster_peer_health_partition_pings: int = 5
    # seconds-dialable SUSPECT window (ISSUE 8 satellite): when > 0 this
    # replaces the missed-pong COUNT with a wall-clock grace — the peer
    # goes SUSPECT after ~this many seconds without a pong (rounded up
    # to whole ping intervals). 0 keeps the legacy pings knob.
    cluster_suspect_window_s: float = 0.0
    # byte budget of each SUSPECT peer's park buffer (oldest spill first)
    cluster_peer_park_max_bytes: int = 1 << 20
    # mesh topology (ISSUE 9): "mesh" keeps the PR 5 all-pairs fabric
    # (every worker dials every peer — fine to ~8 workers); "tree" routes
    # over the epoch-stamped spanning tree mqtt_tpu.mesh_topology elects,
    # keeping per-worker links and gossip O(degree) at 32+ workers.
    # Mesh-wide: every worker must run the same mode.
    cluster_topology: str = "mesh"
    # spanning-tree branching factor (per-worker links <= degree + 1)
    cluster_tree_degree: int = 4
    # interest-summary bloom size in bits (per edge; must be a multiple
    # of 8 — bigger = fewer false-positive forwards at more gossip bytes)
    cluster_summary_bits: int = 4096
    # (origin, boot) duplicate-suppression window in sequence numbers
    cluster_dup_window: int = 8192
    # cross-machine mesh transport (ISSUE 17): "unix" keeps the on-box
    # socket-dir fabric; "tcp" listens on cluster_base_port + worker_id
    # (per-worker pins via cluster_peer_addrs: {worker: "host:port"}).
    # Mesh-wide: every worker must run the same transport.
    cluster_transport: str = "unix"
    cluster_host: str = "127.0.0.1"
    cluster_base_port: int = 0
    cluster_peer_addrs: Optional[dict] = None
    # mutual-TLS on TCP peer links: cert/key identify this worker, and a
    # configured CA makes BOTH directions verify (the accepting side
    # demands a client cert too). Empty cert = plaintext TCP.
    cluster_tls_cert: str = ""
    cluster_tls_key: str = ""
    cluster_tls_ca: str = ""
    # WAN dial/keepalive tuning: a blackholed SYN fails onto the backoff
    # ladder after this many seconds; keepalive > 0 arms kernel TCP
    # keepalive probes at that idle interval on every peer link
    cluster_connect_timeout_s: float = 5.0
    cluster_keepalive_s: float = 0.0
    # predicate push-down (ISSUE 17): max interned predicate digests
    # carried per edge summary — past the cap the digest plane degrades
    # to conservative pass-through (0 disables push-down entirely)
    cluster_summary_digests: int = 64
    # MQTT+ payload-predicate subscriptions (mqtt_tpu.predicates): parse
    # `$GT{...}`-style suffixes off SUBSCRIBE filters, filter fan-out by
    # payload, evaluate the compiled rule table on device inside the
    # staged match batch (host interpreter = oracle + degradation path).
    # Default on — an unpredicated broker pays one attribute read per
    # publish and stays bit-identical.
    predicate_filters: bool = True
    # device rule-table cap: rules registered past it are evaluated by
    # the host interpreter only (degraded, never refused)
    predicate_max_rules: int = 1 << 20
    # differential oracle cadence: 1-in-N predicated publishes re-derive
    # every device verdict from the raw payload on the host and count
    # mismatches (0 disables sampling)
    predicate_oracle_sample: int = 64
    # secure multi-tenant plane (mqtt_tpu.tenancy): clients resolve to a
    # tenant at CONNECT (username first, then client id — the
    # overload_priority_users idiom) and from then on every broker key
    # they touch — trie filters, retained topics, $SHARE groups, the
    # client-registry id, cluster interest summaries — carries the
    # tenant's namespace prefix, so cross-tenant delivery is impossible
    # by construction. Off by default: with it off, no tenancy code runs.
    tenancy: bool = False
    # tenant registry: name -> {quota_class: str, encrypted: [topic
    # prefix, ...], keys: {client-id-or-username: 32-hex-char AES-128
    # key, ...}}. quota_class rides the governor's priority-class
    # machinery (overload_priority_classes supplies the weights).
    tenants: Optional[dict] = None
    # username-or-client-id -> tenant name (resolved at CONNECT)
    tenant_users: Optional[dict] = None
    # tenant for unmapped clients; "" keeps them in the global namespace
    tenant_default: str = ""
    # per-tenant durable COUNT caps (ISSUE 16 / MQT-TZ quota residual):
    # the default maximum number of retained topics / stored
    # subscriptions a tenant may hold; a tenant dict may override with
    # its own `max_retained` / `max_subscriptions`. 0 = unlimited.
    # Enforced structurally in the namespaced stores (refused with v5
    # 0x97 Quota exceeded and counted per tenant) so a runaway tenant
    # cannot grow durable memory past its cap. Global (untenanted)
    # clients are uncapped.
    tenant_max_retained: int = 0
    tenant_max_subscriptions: int = 0
    # device-resident retained matching (mqtt_tpu.ops.retained): serve
    # wildcard-SUBSCRIBE retained fan-out from the flat CSR kernel run
    # in reverse, with the host retained walk as 1-in-N differential
    # oracle behind a CircuitBreaker (host wins mismatches; an open
    # breaker degrades all retained matching to the host walk). Off by
    # default: the host walk is exact and retained fan-out is off the
    # publish hot path.
    retained_matcher: bool = False
    # 1-in-N oracle cadence for the retained kernel (0 disables the
    # sampled oracle; breaker probes still verify fully)
    retained_oracle_sample: int = 16
    # restart re-registration batch size: persisted subscriptions and
    # retained messages re-enter the trie through the bulk-insert path
    # in chunks of this many (staging.bulk_register / bulk_retain)
    durable_restore_batch: int = 4096
    # MQT-TZ re-encryption stage (mqtt_tpu.tenancy.RecryptEngine +
    # ops/recrypt): publishes in a tenant's `encrypted` namespaces are
    # decrypted once with the publisher's key and re-encrypted per
    # subscriber as ONE batched AES-CTR keystream dispatch per fan-out
    # tick (vectorized-host oracle + breaker degradation, the
    # matcher/predicate posture). Requires tenancy.
    recrypt: bool = True
    # differential-oracle cadence: 1-in-N device keystream dispatches
    # are re-derived on the host and compared bit-for-bit (0 disables)
    recrypt_oracle_sample: int = 64
    # dispatches below this many 16-byte keystream blocks run on the
    # host outright (a tiny batch's device round trip only adds latency)
    recrypt_device_min_blocks: int = 4
    # unified telemetry plane (mqtt_tpu.telemetry): per-publish stage
    # clock sampled 1-in-N, histogram metrics, Prometheus exposition at
    # GET /metrics (sysinfo listener), the retained
    # $SYS/broker/telemetry/# tree, and a flight recorder that dumps a
    # JSON trace when the governor enters SHED or the breaker trips.
    # Default on — sampling keeps the unsampled hot path at one integer
    # increment per publish.
    telemetry: bool = True
    # stage-clock sampling: 1-in-N publishes carry a clock (0 disables
    # stage sampling; batch/queue histograms still populate)
    telemetry_sample: int = 64
    # flight-recorder ring size (recent sampled stage records)
    telemetry_ring: int = 256
    # flight-recorder dump directory; "" = <tempdir>/mqtt_tpu_flight
    telemetry_dump_dir: str = ""
    # minimum ms between flight-recorder dumps (a flapping posture must
    # not fill the disk)
    telemetry_dump_min_interval_ms: float = 30000.0
    # trace plane (mqtt_tpu.tracing): 1-in-N publishes carry a full
    # trace context — a span tree through decode -> admission ->
    # staging_wait -> h2d -> device_dispatch -> d2h -> fanout plus
    # per-peer forward spans, joined across the worker mesh by the
    # trace id riding cluster frames. Exported as Chrome trace-event
    # JSON at GET /traces and in trigger dumps. Default on (requires
    # telemetry); the unsampled hot path pays one extra modulo.
    trace: bool = True
    # 1-in-N publishes carry a trace (0 disables tracing outright)
    trace_sample: int = 64
    # span-ring size (finished spans retained for /traces and dumps)
    trace_ring: int = 4096
    # per-bucket (value, trace_id) exemplars on the stage histograms,
    # rendered OpenMetrics-style on /metrics — links a p99 bucket to a
    # concrete recorded trace. NOTE: plain Prometheus text-format
    # scrapers that reject exemplar suffixes need this off.
    trace_exemplars: bool = True
    # stamp traced publishes with a v5 `trace-id` user property so
    # subscribers see the trace id (default OFF: it mutates the wire
    # bytes of sampled publishes). Inbound v5 publishes carrying the
    # property ADOPT the client's trace id regardless, rate-bounded by
    # trace_adopt_max_per_s.
    trace_user_property: bool = False
    # client-driven adoptions admitted per second (a client stamping
    # every publish must not bypass trace_sample or flood the span
    # ring); 0 disables adoption entirely
    trace_adopt_max_per_s: int = 64
    # when set, serve() starts a jax.profiler trace into this directory
    # and close() stops it — the deep-dive companion to the host-side
    # duty-cycle numbers ("" disables; requires a device matcher)
    trace_jax_profiler_dir: str = ""
    # host hot-path observatory (mqtt_tpu.profiling): an always-on
    # sampling wall profiler over every broker thread (sys._current_
    # frames at profile_hz, zero per-call cost on the profiled paths),
    # collapsed-stack + Perfetto exports at GET /profile and beside
    # trigger dumps. Default on (requires telemetry).
    profile: bool = True
    # profiler sweep rate; each sweep walks every live thread's stack
    profile_hz: float = 29.0
    # raw samples retained for the /profile?format=trace flame chart
    profile_ring: int = 2048
    # lock-contention plane (mqtt_tpu.utils.locked): arm the named-lock
    # wait/hold instrumentation and export it on /metrics. Opt-out knob
    # — a disarmed lock costs one bool test over the bare acquire.
    profile_locks: bool = True
    # topic-cardinality space-saving sketch capacity (top-K hot topics
    # + avg-hits-per-topic, observed on stage-clock-sampled publishes);
    # 0 disables the sketch
    profile_topics: int = 512
    # cluster-wide SLO observatory (ISSUE 14, mqtt_tpu.slo): the
    # per-tenant delivery-latency SLI (publish arrival at decode ->
    # frame flushed, riding the sampled stage clocks — the unsampled
    # hot path pays nothing, the sampled path one dict probe) and the
    # multi-window burn-rate engine over declared objectives. Default
    # on; False disables SLI stamping AND the engine (the bench A/B
    # arm).
    slo: bool = True
    # declarative objectives, e.g. ["p99 delivery < 50ms over 5m",
    # "shed ratio < 0.1%"] — grammar in mqtt_tpu.slo; unparseable lines
    # are logged and skipped, never fatal. None/empty = SLIs recorded,
    # no engine.
    slo_objectives: Optional[list] = None
    # burn-rate level both windows must exceed to breach (1.0 = the
    # budget is being spent exactly as fast as allowed)
    slo_burn_threshold: float = 1.0
    # per-device observability plane (ISSUE 18, mqtt_tpu.ops.
    # devicestats): per-chip HBM gauges, the compile-event ledger, the
    # shard-skew gauge, GET /devices, $SYS/broker/devices/#, and the
    # devices_*.json trigger-dump sibling. Default on (requires
    # telemetry + a device matcher to say anything interesting, but the
    # plane itself is host-side and backend-agnostic).
    device_stats: bool = True
    # live/limit HBM occupancy at or above which /healthz reports the
    # device plane degraded (never flips readiness); the "hbm ratio"
    # SLO objective is the alerting twin of this knob
    device_hbm_watermark: float = 0.9
    # mesh metric federation (mqtt_tpu.cluster _T_METRICS): per-worker
    # registry summaries ride the mesh at gossip cadence with
    # per-subtree fold; the tree root serves GET /metrics/cluster and
    # /cluster/slo for the whole mesh. False disables send AND store.
    cluster_metrics: bool = True
    # federated summaries older than this age out of scrapes (a dead
    # worker must not pin stale totals)
    cluster_metrics_max_age_s: float = 120.0

    def ensure_defaults(self) -> None:
        """Sane defaults when unset (server.go:208-235)."""
        self.capabilities.maximum_packet_id = 0xFFFF  # spec maximum
        if self.capabilities.maximum_inflight == 0:
            self.capabilities.maximum_inflight = 1024 * 8
        if self.sys_topic_resend_interval == 0:
            self.sys_topic_resend_interval = DEFAULT_SYS_TOPIC_INTERVAL
        if self.client_net_write_buffer_size == 0:
            self.client_net_write_buffer_size = 1024 * 2
        if self.client_net_read_buffer_size == 0:
            self.client_net_read_buffer_size = 1024 * 2
        # staging knobs are config-reachable: a zero/negative max_batch
        # would busy-spin the collector on empty batches, and a zero
        # max_inflight turns the bounded queue unbounded (asyncio.Queue
        # semantics) — normalize both like the buffer sizes above
        if self.matcher_stage_max_batch <= 0:
            self.matcher_stage_max_batch = 4096
        if self.matcher_stage_max_inflight <= 0:
            self.matcher_stage_max_inflight = 4
        if self.matcher_stage_window_ms < 0:
            self.matcher_stage_window_ms = 0.0
        # breaker knobs are config-reachable too: zero/negative values
        # would trip instantly or busy-probe — normalize to the defaults
        if self.breaker_failure_threshold <= 0:
            self.breaker_failure_threshold = 3
        if self.breaker_watchdog_ms <= 0:
            self.breaker_watchdog_ms = 5000.0
        if self.breaker_probe_backoff_ms <= 0:
            self.breaker_probe_backoff_ms = 500.0
        if self.breaker_probe_backoff_max_ms < self.breaker_probe_backoff_ms:
            self.breaker_probe_backoff_max_ms = max(
                self.breaker_probe_backoff_ms, 30000.0
            )
        # overload knobs are config-reachable: inverted hysteresis bands
        # would flap on every evaluation and zero caps would divide the
        # pressure signals — normalize like the knobs above
        if self.overload_throttle_exit > self.overload_throttle_enter:
            self.overload_throttle_exit = self.overload_throttle_enter
        if self.overload_shed_exit > self.overload_shed_enter:
            self.overload_shed_exit = self.overload_shed_enter
        if self.overload_shed_enter < self.overload_throttle_enter:
            self.overload_shed_enter = self.overload_throttle_enter
        if self.overload_stage_max_pending <= 0:
            self.overload_stage_max_pending = 8192
        if self.overload_client_buffer_limit_bytes <= 0:
            self.overload_client_buffer_limit_bytes = 1024 * 1024
        if self.overload_max_outbound_backlog <= 0:
            self.overload_max_outbound_backlog = 65536
        if self.overload_eval_interval_ms <= 0:
            self.overload_eval_interval_ms = 250.0
        if self.overload_quota_window_ms < 0:
            self.overload_quota_window_ms = 0.0
        if self.overload_min_dwell_ms < 0:
            self.overload_min_dwell_ms = 500.0
        if self.overload_throttle_delay_ms < 0:
            self.overload_throttle_delay_ms = 50.0
        if self.overload_eviction_grace_ms < 0:
            # a negative grace would evict on the FIRST sweep after any
            # transient backlog — mass-disconnecting healthy-but-busy
            # consumers the moment the broker sheds
            self.overload_eviction_grace_ms = 2000.0
        if self.overload_publish_quota <= 0:
            self.overload_publish_quota = 2048
        if self.overload_shed_quota <= 0:
            self.overload_shed_quota = 256
        # federation/admission/health knobs are config-reachable too
        if self.overload_priority_classes:
            # sanitize ONCE at startup: _assign_priority_class runs on
            # the CONNECT path, where a non-numeric weight from a config
            # typo would otherwise raise mid-handshake and take out the
            # whole class's connects (no CONNACK at all)
            clean = {}
            for klass, weight in self.overload_priority_classes.items():
                try:
                    clean[klass] = float(weight)
                except (TypeError, ValueError):
                    logging.getLogger("mqtt_tpu").warning(
                        "overload_priority_classes[%r]=%r is not a number; "
                        "class falls back to weight 1.0",
                        klass,
                        weight,
                    )
            self.overload_priority_classes = clean
        if self.overload_federation_weight <= 0:
            self.overload_federation_weight = 0.9
        if self.overload_federation_ttl_ms <= 0:
            self.overload_federation_ttl_ms = 15000.0
        if self.overload_admission_reserve < 0:
            self.overload_admission_reserve = 0
        if self.cluster_peer_health_suspect_pings <= 0:
            self.cluster_peer_health_suspect_pings = 2
        if self.cluster_peer_health_partition_pings <= self.cluster_peer_health_suspect_pings:
            # PARTITIONED must come strictly after SUSPECT, or the park
            # buffer never gets a heal window at all
            self.cluster_peer_health_partition_pings = (
                self.cluster_peer_health_suspect_pings + 3
            )
        if self.cluster_peer_park_max_bytes <= 0:
            self.cluster_peer_park_max_bytes = 1 << 20
        if self.cluster_suspect_window_s < 0:
            self.cluster_suspect_window_s = 0.0  # 0 = legacy pings knob
        # topology knobs are config-reachable: an unknown mode falls back
        # to the all-pairs mesh (never a refused boot), the tree degree
        # needs >= 1 child slot, and the summary bloom must be whole
        # bytes with enough slots to be worth probing
        if str(self.cluster_topology).lower() not in ("mesh", "tree"):
            self.cluster_topology = "mesh"
        else:
            self.cluster_topology = str(self.cluster_topology).lower()
        if self.cluster_tree_degree < 1:
            self.cluster_tree_degree = 4
        if self.cluster_summary_bits < 64 or self.cluster_summary_bits % 8:
            self.cluster_summary_bits = 4096
        if self.cluster_dup_window < 1:
            self.cluster_dup_window = 8192
        # transport knobs are config-reachable: an unknown transport
        # falls back to the on-box unix fabric (never a refused boot),
        # ports clamp into range, and the WAN timers stay sane
        if str(self.cluster_transport).lower() not in ("unix", "tcp"):
            self.cluster_transport = "unix"
        else:
            self.cluster_transport = str(self.cluster_transport).lower()
        if not 0 <= self.cluster_base_port <= 65535:
            self.cluster_base_port = 0
        if self.cluster_connect_timeout_s <= 0:
            self.cluster_connect_timeout_s = 5.0
        if self.cluster_keepalive_s < 0:
            self.cluster_keepalive_s = 0.0
        if self.cluster_summary_digests < 0:
            self.cluster_summary_digests = 64
        # predicate knobs are config-reachable: a zero/negative rule cap
        # would refuse every predicate, a negative sample means "default"
        if self.predicate_max_rules <= 0:
            self.predicate_max_rules = 1 << 20
        if self.predicate_oracle_sample < 0:
            self.predicate_oracle_sample = 64
        # tenancy knobs are config-reachable: a negative oracle sample
        # means "default", the block floor needs >= 1
        if self.recrypt_oracle_sample < 0:
            self.recrypt_oracle_sample = 64
        if self.recrypt_device_min_blocks < 1:
            self.recrypt_device_min_blocks = 4
        # durable-plane knobs are config-reachable: negative caps mean
        # "unlimited", a negative oracle sample means "default", and the
        # restore batch needs >= 1 or bulk chunking never drains
        if self.tenant_max_retained < 0:
            self.tenant_max_retained = 0
        if self.tenant_max_subscriptions < 0:
            self.tenant_max_subscriptions = 0
        if self.retained_oracle_sample < 0:
            self.retained_oracle_sample = 16
        if self.durable_restore_batch < 1:
            self.durable_restore_batch = 4096
        # telemetry knobs are config-reachable: a negative sample rate
        # means "default", a zero one disables stage sampling outright
        if self.telemetry_sample < 0:
            self.telemetry_sample = 64
        if self.telemetry_ring <= 0:
            self.telemetry_ring = 256
        if self.telemetry_dump_min_interval_ms < 0:
            self.telemetry_dump_min_interval_ms = 30000.0
        # trace knobs are config-reachable: a negative sample rate means
        # "default", zero disables tracing; the ring must hold something
        if self.trace_sample < 0:
            self.trace_sample = 64
        if self.trace_ring <= 0:
            self.trace_ring = 4096
        if self.trace_adopt_max_per_s < 0:
            self.trace_adopt_max_per_s = 64
        # fabric knobs are config-reachable: a negative shard count
        # means single-loop, an unknown accept mode falls back to the
        # hand-off router (never a refused boot)
        if self.loop_shards < 1:
            self.loop_shards = 1
        if str(self.loop_shard_accept).lower() not in ("handoff", "reuseport"):
            self.loop_shard_accept = "handoff"
        else:
            self.loop_shard_accept = str(self.loop_shard_accept).lower()
        if self.profile_hz <= 0:
            self.profile_hz = 29.0
        if self.profile_ring <= 0:
            self.profile_ring = 2048
        if self.profile_topics < 0:
            self.profile_topics = 512
        if self.logger is None:
            self.logger = logging.getLogger("mqtt_tpu")


_VIEW_CLS: Any = None
_VIEW_CLS_RESOLVED = False


def _view_class():
    """The C ``SubscribersView`` type (native/accelmod.c) or None —
    resolved once. Without the C module no view can ever reach
    ``_fan_out``, so None simply disables the lazy branch."""
    global _VIEW_CLS, _VIEW_CLS_RESOLVED
    if not _VIEW_CLS_RESOLVED:
        from .native import accel

        mod = accel()
        _VIEW_CLS = getattr(mod, "SubscribersView", None) if mod else None
        _VIEW_CLS_RESOLVED = True
    return _VIEW_CLS


def publish_frame_body_offset(frame: bytes) -> int:
    """Offset of a raw PUBLISH frame's variable header (skips the fixed
    header's remaining-length varint). The caller guarantees a frame the
    scanner accepted, so the varint terminates within 4 bytes."""
    off = 1
    while frame[off] & 0x80:
        off += 1
    return off + 1


def publish_frame_topic(frame: bytes):
    """``(topic, body_offset)`` parsed from a raw PUBLISH frame, or None
    when the frame is truncated or the topic is not valid UTF-8. The one
    shared parse for every fast-path delivery leg — try_fast_publish's
    inline gates, fast_deliver_frame, and the cluster's forwarded-frame
    delivery (mqtt_tpu.cluster) — so framing rules change in one place."""
    body_offset = publish_frame_body_offset(frame)
    n = len(frame)
    if body_offset + 2 > n:
        return None
    tl = (frame[body_offset] << 8) | frame[body_offset + 1]
    t0 = body_offset + 2
    if n < t0 + tl:
        return None
    try:
        return frame[t0 : t0 + tl].decode("utf-8"), body_offset
    except UnicodeDecodeError:
        return None


class _FrameCache:
    """One-encode-per-publish outbound frames for the QoS0 fan-out fast
    path: every eligible subscriber of a publish shares the same wire
    bytes, keyed by (protocol version, effective retain flag). The copy
    drops inbound topic aliases exactly like the per-subscriber slow path
    ([MQTT-3.3.2-7] via ``Packet.copy``)."""

    __slots__ = ("pk", "frames", "telemetry")

    def __init__(self, pk: "Packet", telemetry: Optional[Any] = None) -> None:
        self.pk = pk
        self.frames: dict[tuple[int, bool], bytes] = {}
        self.telemetry = telemetry

    def get(self, version: int, retain: bool) -> bytes:
        key = (version, bool(retain))
        data = self.frames.get(key)
        if data is None:
            # a real encode (cache hits share the bytes): fan-out
            # amplification accounting counts exactly these
            if self.telemetry is not None:
                self.telemetry.publish_encodes.inc()
            out = self.pk.copy(False)
            out.fixed_header.retain = bool(retain)
            out.protocol_version = version
            if out.expiry > 0:
                # the send-time expiry rewrite [MQTT-3.3.2-6], computed once
                # per publish instead of per subscriber write (the queue
                # drains within the same tick)
                out.properties.message_expiry_interval = max(
                    1, out.expiry - int(time.time())  # brokerlint: ok=R3 message expiry is an absolute wall-clock stamp
                )
            buf = get_buffer()
            try:
                pkts.ENCODERS[pkts.PUBLISH](out, buf)
                data = bytes(buf)
            finally:
                put_buffer(buf)
            self.frames[key] = data
        return data


class _Ops:
    """Server values propagated to clients (server.go:159-164).
    ``fast_publish`` is the server's QoS0 frame-passthrough entry point
    (None until the server wires it)."""

    def __init__(self, options: Options, info: Info, hooks: Hooks, log: logging.Logger) -> None:
        self.options = options
        self.info = info
        self.hooks = hooks
        self.log = log
        self.fast_publish: Optional[Callable[..., bool]] = None
        self.fast_publish_eligible: Optional[Callable[..., bool]] = None
        # the overload governor (mqtt_tpu.overload); None = ungoverned.
        # Clients consult it for the THROTTLE read-delay verdict.
        self.overload: Optional[Any] = None
        # the telemetry plane (mqtt_tpu.telemetry); None = uninstrumented.
        # Clients consult it for the publish stage clock and the sampled
        # outbound queue-wait stamps.
        self.telemetry: Optional[Any] = None
        # read-side scan coalescer (clients.ScanGate); None = per-socket
        # scans. Set by the server when Options.scan_coalesce is on.
        self.scan_gate: Optional[Any] = None


class Server:
    """An MQTT broker server; create via ``Server(options)``
    (server.go:135-205)."""

    def __init__(self, options: Optional[Options] = None) -> None:
        opts = options or Options()
        opts.ensure_defaults()
        self.options = opts
        # ensure_defaults() guarantees a logger; the fallback keeps the
        # attribute non-Optional for every `self.log.<level>` call site
        self.log: logging.Logger = opts.logger or logging.getLogger("mqtt_tpu")
        self.info = Info(version=VERSION, started=int(time.time()))  # brokerlint: ok=R3 $SYS start stamp is wall-clock; uptime uses the monotonic anchor
        self.clients = Clients()
        self.topics = TopicsIndex()
        self.listeners = Listeners()
        self.hooks = Hooks(self.log)
        self.will_delayed = PacketStore()
        self.done = asyncio.Event()
        self._event_loop_task: Optional[asyncio.Task] = None
        self.inline_client: Optional[Client] = None
        self._ops = _Ops(opts, self.info, self.hooks, self.log)
        self._ops.fast_publish = self.try_fast_publish
        self._ops.fast_publish_eligible = self.fast_publish_eligible
        self._fastpub_gate_gen = -1  # hooks generation the gate was cached at
        self._fastpub_gate_ok = False
        # encode-once batched fan-out (ISSUE 13): variant grouping + the
        # GIL-released native flush; False = legacy per-subscriber path
        self._fanout_batch = opts.fanout_batch
        if opts.scan_coalesce:
            # read-side decode batching: frame scans from read loops that
            # wake in the same event-loop tick coalesce into one native
            # multi-buffer call (clients.ScanGate)
            from .clients import ScanGate

            self._ops.scan_gate = ScanGate()
        self._fastpub_plans: dict = {}  # topic -> (trie version, fan-out plan)
        # event-loop shard fabric (mqtt_tpu.shards); None = single loop.
        # Built in serve() when Options.loop_shards > 1.
        self._fabric: Optional[Any] = None
        # the loop serve() ran on — the housekeeping tick's loop; under
        # the fabric, clients owned by it (or by no loop) are swept here
        self._main_loop: Optional[asyncio.AbstractEventLoop] = None
        # clients_connected gates maximum_clients: under the fabric the
        # attach/detach paths run on many shard loops, and a bare += on
        # the gauge could drift past the cap
        self._conn_lock = threading.Lock()
        # (timestamp, {loop: queued}) memo so one scrape's N per-shard
        # backlog gauges share a single client-registry walk
        self._shard_backlog_memo: Optional[tuple] = None
        # multi-core worker fabric (mqtt_tpu.cluster); None = single process
        self._cluster: Optional[Any] = None
        # set at the top of close(): CONNECTs arriving mid-drain are
        # refused with CONNACK 0x89 Server Busy instead of 0x97
        self._draining = False
        # the optional planes below stay Any-typed deliberately: each is
        # a lazily imported subsystem (device matcher, staging loop,
        # governor, telemetry/tracing/profiling) whose concrete class
        # never crosses this module's annotated signatures
        self.matcher: Optional[Any] = None  # device matcher; None = host walk
        self._stage: Optional[Any] = None  # publish staging loop (serve())
        self._jax_trace_active = False  # trace_jax_profiler_dir capture
        # broker-wide overload governor (mqtt_tpu.overload): admission,
        # backpressure, and graceful shedding under publish storms.
        # Default on; the staging signal attaches in serve(), the
        # cluster signal in Cluster.__init__.
        self.overload: Optional[Any] = None
        self._outbound_backlog = 0  # last sweep's aggregate (gauge)
        # unified telemetry plane (mqtt_tpu.telemetry): stage clocks,
        # histograms, /metrics exposition, $SYS tree, flight recorder
        self.telemetry: Optional[Any] = None
        # trace plane (mqtt_tpu.tracing): span ring + device profiler
        self.tracer: Optional[Any] = None
        self.profiler: Optional[Any] = None
        # host hot-path observatory (mqtt_tpu.profiling): sampling wall
        # profiler + topic-cardinality sketch; lock plane armed below
        self.host_profiler: Optional[Any] = None
        self.topic_sketch: Optional[Any] = None
        self._lock_plane_armed = False
        if opts.telemetry:
            from .telemetry import Telemetry

            self.telemetry = Telemetry(
                sample=opts.telemetry_sample,
                ring=opts.telemetry_ring,
                dump_dir=opts.telemetry_dump_dir,
                dump_min_interval_s=opts.telemetry_dump_min_interval_ms / 1e3,
            )
            self._ops.telemetry = self.telemetry
            self._register_core_gauges()
            if opts.trace and opts.trace_sample > 0:
                from .tracing import Tracer

                self.tracer = Tracer(
                    sample=opts.trace_sample,
                    ring=opts.trace_ring,
                    registry=self.telemetry.registry,
                )
                self.tracer.adopt_max_per_s = opts.trace_adopt_max_per_s
                self.telemetry.attach_tracer(
                    self.tracer, exemplars=opts.trace_exemplars
                )
            if opts.profile:
                # host hot-path observatory (mqtt_tpu.profiling): the
                # sampling thread starts in serve(), so an embedder that
                # builds but never serves a Server spawns no thread
                from .profiling import SamplingProfiler, TopicSketch

                self.host_profiler = SamplingProfiler(
                    hz=opts.profile_hz,
                    ring=opts.profile_ring,
                    registry=self.telemetry.registry,
                )
                self.telemetry.attach_profiler(self.host_profiler)
                if opts.profile_topics > 0:
                    self.topic_sketch = TopicSketch(k=opts.profile_topics)
                    sk = self.topic_sketch
                    r = self.telemetry.registry
                    r.gauge(
                        "mqtt_tpu_topic_sketch_tracked",
                        "Topics currently tracked by the space-saving sketch",
                        fn=lambda: sk.tracked,
                    )
                    r.gauge(
                        "mqtt_tpu_topic_sketch_avg_hits",
                        "Observed average hits per admitted topic (device "
                        "compaction-buffer sizing; sampled publishes)",
                        fn=sk.avg_hits_per_topic,
                    )
                    r.counter(
                        "mqtt_tpu_topic_sketch_evictions_total",
                        "Space-saving evictions (sketch churn under high "
                        "topic cardinality)",
                        fn=lambda: sk.evictions,
                    )
            if opts.profile_locks:
                # export the per-lock wait/hold families now; ARMING
                # waits for serve() so a constructed-but-never-served
                # Server (embedder probes, test harnesses) costs nothing
                from .utils.locked import DEFAULT_PLANE

                self.telemetry.attach_lock_plane(DEFAULT_PLANE)
        # cluster-wide SLO observatory (ISSUE 14, mqtt_tpu.slo): the
        # delivery-latency SLI gate plus the burn-rate engine when
        # objectives are declared; evaluate() rides the housekeeping tick
        self.slo: Optional[Any] = None
        # per-device observability plane (ISSUE 18); built further down
        # once the matcher + device profiler exist to attach
        self.device_stats: Optional[Any] = None
        if self.telemetry is not None:
            self.telemetry.delivery_sli = bool(opts.slo)
            if opts.slo and opts.slo_objectives:
                from .slo import SLOEngine, parse_objectives

                objectives = parse_objectives(opts.slo_objectives)
                if objectives:
                    self.slo = SLOEngine(
                        self.telemetry,
                        objectives,
                        burn_threshold=opts.slo_burn_threshold,
                        publish=self._publish_slo_transition,
                    )
                    self.telemetry.attach_slo(self.slo)
        if opts.overload_control:
            from .overload import OverloadConfig, OverloadGovernor

            self.overload = OverloadGovernor(
                OverloadConfig(
                    throttle_enter=opts.overload_throttle_enter,
                    throttle_exit=opts.overload_throttle_exit,
                    shed_enter=opts.overload_shed_enter,
                    shed_exit=opts.overload_shed_exit,
                    min_dwell_s=opts.overload_min_dwell_ms / 1e3,
                    eval_interval_s=opts.overload_eval_interval_ms / 1e3,
                    quota_window_s=opts.overload_quota_window_ms / 1e3,
                    publish_quota=opts.overload_publish_quota,
                    throttle_delay_s=opts.overload_throttle_delay_ms / 1e3,
                    shed_quota=opts.overload_shed_quota,
                    eviction_grace_s=opts.overload_eviction_grace_ms / 1e3,
                    admission_reserve=opts.overload_admission_reserve,
                    priority_weights=dict(opts.overload_priority_classes or {}),
                )
            )
            self._ops.overload = self.overload
            self.overload.add_source("outbound", self._outbound_pressure)
            if opts.overload_memory_limit_mb > 0:
                limit = opts.overload_memory_limit_mb * 1024 * 1024
                self.overload.add_source(
                    "memory", lambda: rss_bytes() / limit
                )
        # MQTT+ payload-predicate plane (mqtt_tpu.predicates): suffix
        # registry + host interpreter + device rule table. Built before
        # the matcher so the staging loop can carry its feature batches.
        self._predicates: Optional[Any] = None
        if opts.predicate_filters:
            from .predicates import PredicateEngine

            self._predicates = PredicateEngine(
                max_rules=opts.predicate_max_rules,
                oracle_sample=opts.predicate_oracle_sample,
                registry=(
                    self.telemetry.registry
                    if self.telemetry is not None
                    else None
                ),
            )
        # secure multi-tenant plane (mqtt_tpu.tenancy): tenant registry +
        # CONNECT resolution + the MQT-TZ re-encryption engine. Built
        # before the matcher so the staging loop can carry decrypt jobs.
        self._tenancy: Optional[Any] = None
        self._recrypt: Optional[Any] = None
        if opts.tenancy:
            from .tenancy import RecryptEngine, TenantPlane

            self._tenancy = TenantPlane(
                registry=(
                    self.telemetry.registry
                    if self.telemetry is not None
                    else None
                )
            )
            self._tenancy.configure(
                opts.tenants, opts.tenant_users, opts.tenant_default
            )
            if opts.recrypt:
                self._recrypt = RecryptEngine(
                    self._tenancy.keys,
                    oracle_sample=opts.recrypt_oracle_sample,
                    device_min_blocks=opts.recrypt_device_min_blocks,
                    registry=(
                        self.telemetry.registry
                        if self.telemetry is not None
                        else None
                    ),
                )
        # device-resident retained matching (ISSUE 16, mqtt_tpu.ops.
        # retained): wildcard-SUBSCRIBE fan-out over the retained corpus
        # served by the flat kernel run in reverse, host walk as 1-in-N
        # oracle behind its own breaker. Opt-in; None = host walk only.
        self._retained_engine: Optional[Any] = None
        if opts.retained_matcher:
            from .ops.retained import RetainedMatchEngine

            self._retained_engine = RetainedMatchEngine(
                self.topics,
                oracle_sample=opts.retained_oracle_sample,
            )
        # durable session plane recovery state (read_store / healthz /
        # $SYS/broker/durable): `recovering` holds /healthz at 503 until
        # the restored maps are actually served
        self._durable: dict = {
            "recovering": False,
            "recovery_seconds": 0.0,
            "replayed_keys": 0,
            "restored_subscriptions": 0,
            "restored_retained": 0,
            "restored_inflight": 0,
            "restore_batches": 0,
        }
        if opts.device_matcher:
            from .ops.delta import DeltaMatcher

            # compaction knobs ride beside matcher_opts (which wins on
            # conflict); the hits-per-topic capacity seed comes from the
            # TopicSketch when the host observatory is on (its EWMA then
            # keeps learning from every compacted batch)
            mopts: dict = {
                "compact": opts.matcher_compact,
                "compact_capacity": opts.matcher_compact_capacity,
                "lazy": opts.matcher_lazy_views,
            }
            if self.topic_sketch is not None:
                mopts["hits_estimate"] = max(
                    2.0, self.topic_sketch.avg_hits_per_topic()
                )
            mopts.update(opts.matcher_opts or {})
            self.matcher = DeltaMatcher(self.topics, **mopts)
            if opts.matcher_resilience:
                # degradation manager (mqtt_tpu.resilience): breaker +
                # hang watchdog + half-open probes around every dispatch
                from .resilience import BreakerConfig, ResilientMatcher

                self.matcher = ResilientMatcher(
                    self.matcher,
                    self.topics,
                    BreakerConfig(
                        failure_threshold=opts.breaker_failure_threshold,
                        watchdog_s=opts.breaker_watchdog_ms / 1e3,
                        probe_backoff_s=opts.breaker_probe_backoff_ms / 1e3,
                        probe_backoff_max_s=(
                            opts.breaker_probe_backoff_max_ms / 1e3
                        ),
                        probe_jitter=opts.breaker_probe_jitter,
                        probe_successes=opts.breaker_probe_successes,
                        verify_sample=opts.breaker_verify_sample,
                    ),
                )
        if self.telemetry is not None:
            # degradation triggers dump the flight recorder: entering SHED
            # (overload storm) and a breaker trip (device failure) both
            # leave a JSON trace of the publishes that led up to them
            if self.overload is not None:
                self.overload.on_transition = self._overload_transition
            if self.matcher is not None:
                stats = getattr(self.matcher, "stats", None)
                if stats is not None:
                    # compile/rebuild/fold wall times -> rebuild histogram
                    stats.rebuild_observer = self.telemetry.rebuild_hist.observe
                if self.tracer is not None:
                    # device pipeline profiler (mqtt_tpu.tracing): the
                    # innermost matcher feeds its dispatch/D2H windows
                    # into duty-cycle / overlap / idle-gap accounting,
                    # and the staging drain loop reads the same object
                    # to sub-stamp sampled traces
                    from .tracing import DeviceProfiler

                    self.profiler = DeviceProfiler(
                        registry=self.telemetry.registry
                    )
                    snap = getattr(self.matcher, "_snap", None)
                    if snap is not None and hasattr(snap, "profiler"):
                        snap.profiler = self.profiler
                # mesh-sharded snapshot: per-shard compile times land in
                # shard-local histograms on the rebuild path; the scrape
                # merges them on demand (telemetry callback histogram)
                snap = getattr(self.matcher, "_snap", None)
                merged = getattr(snap, "merged_shard_compile", None)
                if merged is not None:
                    self.telemetry.registry.histogram(
                        "mqtt_tpu_matcher_shard_compile_seconds",
                        "Per-shard flat-index compile wall time (shard-local "
                        "histogram shards, merged at scrape)",
                        fn=merged,
                    )
                breaker = getattr(self.matcher, "breaker", None)
                if breaker is not None:
                    prev_trip = breaker.on_trip

                    def _trip_dump(_prev=prev_trip):
                        # fires AFTER the breaker lock is released
                        # (_fire_on_trip, brokerlint R5) — confirmed by
                        # the lock witness: no matcher_breaker ->
                        # flight_ring edge exists at runtime
                        if _prev is not None:
                            _prev()
                        self.telemetry.trigger_dump(
                            "breaker_trip", {"trigger": "matcher_breaker"}
                        )

                    breaker.on_trip = _trip_dump
            # per-device observability plane (ISSUE 18, ops/devicestats):
            # HBM gauges + the compile-event ledger + the shard-skew
            # gauge; adopts the device profiler's per-device windows and
            # the sharded snapshot's tile-skew state when they exist
            if opts.device_stats:
                from .ops.devicestats import DeviceStatsPlane

                plane = DeviceStatsPlane(
                    registry=self.telemetry.registry,
                    hbm_watermark=opts.device_hbm_watermark,
                )
                if self.profiler is not None:
                    plane.attach_profiler(self.profiler)
                for cand in (
                    getattr(self.matcher, "_snap", None),
                    self.matcher,
                ):
                    if cand is not None and hasattr(cand, "device_skew_ratio"):
                        plane.attach_matcher(cand)
                        break
                self.telemetry.attach_device_stats(plane)
                self.device_stats = plane
            if self._recrypt is not None:
                rbreaker = self._recrypt.breaker
                prev_rtrip = rbreaker.on_trip

                def _recrypt_trip_dump(_prev=prev_rtrip):
                    # fires AFTER the breaker lock is released
                    # (_fire_on_trip, brokerlint R5) — a failing crypto
                    # device leaves a flight-recorder trace, exactly
                    # like the matcher and predicate breakers
                    if _prev is not None:
                        _prev()
                    self.telemetry.trigger_dump(
                        "breaker_trip", {"trigger": "recrypt_breaker"}
                    )

                rbreaker.on_trip = _recrypt_trip_dump
            # durable session plane + retained-match engine observability
            # (ISSUE 16): recovery progress, log-store internals, and the
            # device-vs-host retained oracle all surface on /metrics
            self._register_durable_metrics()
        if opts.inline_client:
            self.inline_client = self.new_client(None, None, LOCAL_LISTENER, INLINE_CLIENT_ID, True)
            self.clients.add_client(self.inline_client)

    # -- construction ------------------------------------------------------

    def new_client(self, reader, writer, listener: str, id_: str, inline: bool) -> Client:
        """A client wired to this server's ops (server.go:241-260)."""
        cl = Client(reader, writer, self._ops)
        cl.id = id_
        cl.net.listener = listener
        if inline:
            cl.net.inline = True
            # don't restrict embedding-application publishes by default
            cl.state.inflight.reset_receive_quota((1 << 31) - 1)
        return cl

    def add_hook(self, hook: Hook, config: Any = None) -> None:
        """Attach a hook, ideally before serve() (server.go:264-272)."""
        hook.set_opts(self.log, HookOptions(capabilities=self.options.capabilities))
        self.log.info("added hook %s", hook.id())
        self.hooks.add(hook, config)

    def add_listener(self, listener: Listener) -> None:
        """Register a listener; init happens during serve (server.go:286-301)."""
        if self.listeners.get(listener.id()) is not None:
            raise ListenerIDExistsError(listener.id())
        self.listeners.add(listener)

    def _listener_from_config(self, conf: ListenerConfig) -> Optional[Listener]:
        t = conf.type.lower()
        if t == TYPE_TCP:
            return TCP(conf)
        if t == TYPE_MOCK:
            return MockListener(conf.id, conf.address)
        if t in (TYPE_WS, TYPE_UNIX, TYPE_HEALTHCHECK, TYPE_SYSINFO):
            # built-in extra listeners are registered lazily to avoid import
            # cycles; they live in mqtt_tpu.listeners.*
            from . import listeners as lmod

            builders = {
                TYPE_WS: getattr(lmod, "Websocket", None),
                TYPE_UNIX: getattr(lmod, "UnixSock", None),
                TYPE_HEALTHCHECK: getattr(lmod, "HTTPHealthCheck", None),
                TYPE_SYSINFO: getattr(lmod, "HTTPStats", None),
            }
            builder = builders.get(t)
            if builder is not None:
                if t == TYPE_SYSINFO:
                    # the stats listener also serves GET /metrics when
                    # the telemetry plane is on (mqtt_tpu.telemetry),
                    # plus /healthz, /metrics/cluster and /cluster/slo
                    # (ISSUE 14 — the SLO observatory's scrape surfaces)
                    return builder(
                        conf, self.info, self.telemetry,
                        health=self.health_report,
                    )
                return builder(conf)
        self.log.error("listener type unavailable by config: %s", conf.type)
        return None

    def add_listeners_from_config(self, configs: list[ListenerConfig]) -> None:
        for conf in configs:
            listener = self._listener_from_config(conf)
            if listener is not None:
                self.add_listener(listener)

    # -- lifecycle ---------------------------------------------------------

    async def serve(self) -> None:
        """Start hooks, restore persisted state, init+serve all listeners,
        begin the housekeeping loop (server.go:334-371)."""
        self.log.info("mqtt_tpu starting version=%s", VERSION)
        if self.options.gc_tuning:
            # process-global: embedders opt out via Options.gc_tuning
            from .utils.gctune import tune_for_throughput

            tune_for_throughput()
            self.log.info(
                "gc thresholds tuned for broker throughput "
                "(Options.gc_tuning=False restores the application's cadence)"
            )
        # warm the native core now — its first-use lazy compile would
        # otherwise block the event loop mid-connection
        from .native import available as _native_available

        await asyncio.get_running_loop().run_in_executor(None, _native_available)
        if self.options.listeners:
            self.add_listeners_from_config(self.options.listeners)
        for hook, config in self.options.hooks:
            self.add_hook(hook, config)

        if self.hooks.provides(
            STORED_CLIENTS,
            STORED_INFLIGHT_MESSAGES,
            STORED_RETAINED_MESSAGES,
            STORED_SUBSCRIPTIONS,
            STORED_SYS_INFO,
        ):
            self.read_store()

        if self.matcher is not None:
            from .staging import MatchStage

            budget_ms = self.options.matcher_stage_latency_budget_ms
            self._stage = MatchStage(
                self.matcher,
                host_fallback=self.topics.subscribers,
                window_s=self.options.matcher_stage_window_ms / 1e3,
                max_batch=self.options.matcher_stage_max_batch,
                max_inflight=self.options.matcher_stage_max_inflight,
                latency_budget_s=(budget_ms / 1e3) if budget_ms > 0 else None,
                max_pending=self.options.overload_stage_max_pending,
                telemetry=self.telemetry,
                profiler=self.profiler,
                predicates=self._predicates,
                pipeline_depth=self.options.matcher_stage_pipeline_depth,
                recrypt=self._recrypt,
            )
            self._stage.start()
            if self.overload is not None:
                self.overload.add_source("staging", self._stage.pressure)
            if self.options.trace_jax_profiler_dir:
                # deep-dive capture hook (mqtt_tpu.tracing): the host-side
                # duty-cycle numbers say WHETHER the device idles; a
                # jax.profiler trace says WHY. Failure to start must
                # never block serving.
                try:
                    import jax

                    jax.profiler.start_trace(
                        self.options.trace_jax_profiler_dir
                    )
                    self._jax_trace_active = True
                    self.log.info(
                        "jax.profiler trace started (dir=%s)",
                        self.options.trace_jax_profiler_dir,
                    )
                except Exception:
                    self.log.exception("jax.profiler trace failed to start")

        if self.host_profiler is not None:
            # the sampling thread is a daemon and samples off every
            # broker lock path (it only reads sys._current_frames), so
            # it starts before traffic and runs for the broker's life
            self.host_profiler.start()
        if (
            self.telemetry is not None
            and self.telemetry.lock_plane is not None
            and not self._lock_plane_armed
        ):
            # arm the lock-contention plane for this broker's lifetime
            # (refcounted: concurrent in-process brokers cannot disarm
            # each other; close() releases this server's hold)
            self.telemetry.lock_plane.arm()
            self._lock_plane_armed = True
        self._main_loop = asyncio.get_running_loop()
        if self.options.loop_shards > 1:
            # event-loop shard fabric (mqtt_tpu.shards / ROADMAP item
            # 4): built before listener init so stream listeners bind
            # raw fabric sockets instead of main-loop asyncio servers
            from .listeners import StreamListener
            from .shards import ShardFabric

            self._fabric = ShardFabric(self.options.loop_shards, server=self)
            reuseport = self.options.loop_shard_accept == "reuseport"
            for lst in self.listeners.internal.values():
                if isinstance(lst, StreamListener):
                    lst.attach_fabric(self._fabric, reuseport=reuseport)
            self._fabric.start()
            if self.telemetry is not None:
                self._fabric.register_metrics(self.telemetry.registry)
            self.log.info(
                "event-loop shard fabric started: shards=%d accept=%s",
                self.options.loop_shards,
                self.options.loop_shard_accept,
            )
        for listener in list(self.listeners.internal.values()):
            await listener.init(self.log)
        self._event_loop_task = asyncio.get_running_loop().create_task(self._event_loop())
        await self.listeners.serve_all(self.establish_connection)
        self.publish_sys_topics()
        self.hooks.on_started()
        if self._durable["recovering"]:
            # the restored maps are now actually served: flip healthz
            # from 503 `recovering` to ready and leave the recovery
            # numbers behind as retained $SYS/broker/durable/# rows
            self._durable["recovering"] = False
            self.publish_durable_sys()
            self.log.info(
                "durable restore complete: seconds=%.3f replayed_keys=%d "
                "subscriptions=%d retained=%d inflight=%d batches=%d",
                self._durable["recovery_seconds"],
                self._durable["replayed_keys"],
                self._durable["restored_subscriptions"],
                self._durable["restored_retained"],
                self._durable["restored_inflight"],
                self._durable["restore_batches"],
            )
        self.log.info("mqtt_tpu server started")

    async def _event_loop(self) -> None:
        """Housekeeping ticks (server.go:374-395): expiry reaping every
        second, $SYS publishing on its own interval."""
        sys_interval = self.options.sys_topic_resend_interval
        next_sys = time.monotonic() + sys_interval
        while not self.done.is_set():
            try:
                await asyncio.wait_for(self.done.wait(), timeout=1.0)
                return
            except asyncio.TimeoutError:
                pass
            now = int(time.time())  # brokerlint: ok=R3 expiry sweeps compare against absolute wall-clock stamps
            self.clear_expired_clients(now)
            self.clear_expired_retained_messages(now)
            self.send_delayed_lwt(now)
            self.clear_expired_inflights(now)
            self.sweep_overload()
            if self.slo is not None:
                # SLO burn-rate evaluation rides the housekeeping tick
                # (mqtt_tpu.slo): a handful of histogram-children walks
                # per second, transitions publish $SYS + dump from here
                # (the event-loop context the $SYS publisher requires)
                try:
                    self.slo.evaluate()
                except Exception:
                    self.log.exception("SLO evaluation failed")
            if time.monotonic() >= next_sys:
                self.publish_sys_topics()
                next_sys = time.monotonic() + sys_interval

    # -- telemetry plane (mqtt_tpu.telemetry) ------------------------------

    @staticmethod
    def _view_materializations() -> int:
        """The C view module's materialization count (0 sans toolchain)."""
        from .ops.matcher import _accel

        acc = _accel()
        if acc is None or not hasattr(acc, "view_stats"):
            return 0
        return acc.view_stats()["materializations"]

    def _register_core_gauges(self) -> None:
        """Scrape-time gauges over state other layers already maintain:
        the $SYS Info counters, matcher stats, and governor posture all
        surface on /metrics without a second bookkeeping path."""
        r = self.telemetry.registry
        info = self.info
        # monotonic Info fields export as callback-backed COUNTERS: the
        # _total suffix promises counter semantics (rate()/increase(),
        # reset detection) and OpenMetrics linting rejects _total gauges
        for name, attr in (
            ("mqtt_tpu_messages_received_total", "messages_received"),
            ("mqtt_tpu_messages_sent_total", "messages_sent"),
            ("mqtt_tpu_messages_dropped_total", "messages_dropped"),
            ("mqtt_tpu_packets_received_total", "packets_received"),
            ("mqtt_tpu_packets_sent_total", "packets_sent"),
            ("mqtt_tpu_bytes_received_total", "bytes_received"),
            ("mqtt_tpu_bytes_sent_total", "bytes_sent"),
        ):
            r.counter(
                name, f"$SYS mirror of Info.{attr}", fn=lambda a=attr: getattr(info, a)
            )
        for name, attr in (
            ("mqtt_tpu_clients_connected", "clients_connected"),
            ("mqtt_tpu_subscriptions", "subscriptions"),
            ("mqtt_tpu_retained_messages", "retained"),
            ("mqtt_tpu_inflight_messages", "inflight"),
        ):
            r.gauge(name, f"$SYS mirror of Info.{attr}", fn=lambda a=attr: getattr(info, a))
        r.gauge(
            "mqtt_tpu_uptime_seconds",
            "Monotonic seconds since broker start (clock-step immune)",
            fn=info.uptime_now,
        )
        r.gauge(
            "mqtt_tpu_overload_state_code",
            "Overload governor posture (0=normal 1=throttle 2=shed)",
            fn=lambda: (
                0 if self.overload is None else self.overload.gauges()["state_code"]
            ),
        )
        r.gauge(
            "mqtt_tpu_overload_pressure",
            "Max normalized pressure across governor signals",
            fn=lambda: 0.0 if self.overload is None else self.overload.pressure,
        )
        r.gauge(
            "mqtt_tpu_stage_pending_depth",
            "Publishes parked in the staging loop",
            fn=lambda: 0 if self._stage is None else self._stage.pending_depth,
        )
        r.gauge(
            "mqtt_tpu_staging_pipeline_depth",
            "Device batches in flight across the staging pipeline legs",
            fn=lambda: (
                0 if self._stage is None else self._stage.inflight_batches
            ),
        )
        # zero-materialization fan-out (ISSUE 13): how often a lazy
        # SubscribersView was forced into the eager dicts (any dict-
        # semantics consumer — shared groups, predicates, differential
        # verification). Near zero on the pure client fan-out path.
        r.counter(
            "mqtt_tpu_fanout_view_materializations_total",
            "Lazy fan-out views forced into materialized Subscribers "
            "dicts (the C view module's own count)",
            fn=self._view_materializations,
        )
        r.counter(
            "mqtt_tpu_staging_compact_overflow_total",
            "Batches whose compacted hits outgrew the pair buffer and "
            "fell back to the padded path (MatcherStats.compact_overflows)",
            fn=lambda: (
                0
                if self.matcher is None
                else getattr(self.matcher.stats, "compact_overflows", 0)
            ),
        )
        r.gauge(
            "mqtt_tpu_outbound_backlog",
            "Aggregate publishes parked in client outbound queues "
            "(last overload-sweep sample)",
            fn=lambda: self._outbound_backlog,
        )
        r.gauge(
            "mqtt_tpu_fanout_amplification_ratio",
            "Outbound PUBLISH encodes per inbound PUBLISH — the "
            "per-subscriber re-encode waste (ROADMAP item 3)",
            fn=lambda: (
                self.telemetry.publish_encodes.value
                / max(1, info.messages_received)
            ),
        )
        for name, field_ in (
            ("mqtt_tpu_matcher_batches_total", "batches"),
            ("mqtt_tpu_matcher_topics_total", "topics"),
            ("mqtt_tpu_matcher_host_fallbacks_total", "host_fallbacks"),
            ("mqtt_tpu_matcher_overflows_total", "overflows"),
            ("mqtt_tpu_matcher_rebuilds_total", "rebuilds"),
            ("mqtt_tpu_matcher_folds_total", "folds"),
            ("mqtt_tpu_matcher_host_fast_total", "host_fast"),
            ("mqtt_tpu_matcher_compact_batches_total", "compact_batches"),
            ("mqtt_tpu_matcher_d2h_bytes_total", "d2h_bytes"),
        ):
            r.counter(
                name,
                f"MatcherStats.{field_} (0 when no device matcher)",
                fn=lambda f=field_: (
                    0
                    if self.matcher is None
                    else getattr(self.matcher.stats, f, 0)
                ),
            )

    def _durable_store_stats(self) -> dict:
        """Merge ``durable_stats()`` across storage hooks that expose one
        (duck-typed — the LogKV store does; third-party hooks may too)."""
        out: dict = {}
        for hook in self.hooks.get_all():
            fn = getattr(hook, "durable_stats", None)
            if not callable(fn):
                continue
            try:
                stats = fn()
            except Exception:  # pragma: no cover  # brokerlint: ok=R4 observability merge must not take the broker down with a hook
                continue
            for k, v in stats.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    out[k] = out.get(k, 0) + v
                else:
                    out.setdefault(k, v)
        return out

    def _register_durable_metrics(self) -> None:
        """Recovery + durable-store + retained-engine families (ISSUE 16).
        All callback-backed: scrape reads the live counters; zeros when
        no durable hook / engine is configured."""
        r = self.telemetry.registry
        r.gauge(
            "mqtt_tpu_durable_recovery_seconds",
            "Wall seconds the last restart spent restoring persisted "
            "state (store replay + bulk re-registration)",
            fn=lambda: self._durable["recovery_seconds"],
        )
        r.counter(
            "mqtt_tpu_durable_replayed_keys_total",
            "Keys replayed from durable-store segments/snapshots at the "
            "last restart (sum across storage hooks)",
            fn=lambda: self._durable["replayed_keys"],
        )
        r.gauge(
            "mqtt_tpu_durable_recovering",
            "1 while restored state is still being re-registered "
            "(healthz holds 503), else 0",
            fn=lambda: 1 if self._durable["recovering"] else 0,
        )
        r.counter(
            "mqtt_tpu_durable_restore_batches_total",
            "Bulk re-registration batches used by the last restore "
            "(subscriptions + retained, staging.bulk_*)",
            fn=lambda: self._durable["restore_batches"],
        )
        r.gauge(
            "mqtt_tpu_durable_segments",
            "Live log segments across durable storage hooks",
            fn=lambda: self._durable_store_stats().get("segments", 0),
        )
        r.gauge(
            "mqtt_tpu_durable_snapshot_age_seconds",
            "Seconds since the newest durable snapshot (-1 when none)",
            fn=lambda: self._durable_store_stats().get(
                "snapshot_age_seconds", -1.0
            ),
        )
        r.counter(
            "mqtt_tpu_durable_replay_corruptions_total",
            "Corrupt records hit during segment replay (CRC/frame "
            "failures — each truncates one segment's tail)",
            fn=lambda: self._durable_store_stats().get("replay_corruptions", 0),
        )
        eng = self._retained_engine
        r.counter(
            "mqtt_tpu_retained_device_matches_total",
            "Retained-topic SUBSCRIBE matches answered by the device "
            "kernel (mqtt_tpu.ops.retained)",
            fn=lambda: 0 if eng is None else eng.device_matches,
        )
        r.counter(
            "mqtt_tpu_retained_oracle_checks_total",
            "Differential host-walk oracle comparisons run by the "
            "retained-match engine",
            fn=lambda: 0 if eng is None else eng.oracle_checks,
        )
        r.counter(
            "mqtt_tpu_retained_oracle_mismatches_total",
            "Oracle comparisons where device and host disagreed (host "
            "won; breaker counted a failure)",
            fn=lambda: 0 if eng is None else eng.oracle_mismatches,
        )
        r.counter(
            "mqtt_tpu_retained_host_fallbacks_total",
            "Retained matches served by the host walk while the engine "
            "was active (depth/filter/overflow/error/breaker classes)",
            fn=lambda: 0 if eng is None else sum(eng.fallbacks.values()),
        )

    def publish_durable_sys(self) -> None:
        """Publish the recovery progress tree as retained
        ``$SYS/broker/durable/#`` rows (ISSUE 16): serve() calls this
        once the restored maps are actually served, and the periodic
        $SYS tick republishes via publish_sys_topics."""
        d = self._durable
        store = self._durable_store_stats()
        rows = {
            "recovering": "1" if d["recovering"] else "0",
            "recovery_seconds": "%.6f" % d["recovery_seconds"],
            "replayed_keys": str(d["replayed_keys"]),
            "restored_subscriptions": str(d["restored_subscriptions"]),
            "restored_retained": str(d["restored_retained"]),
            "restored_inflight": str(d["restored_inflight"]),
            "restore_batches": str(d["restore_batches"]),
        }
        for k in ("segments", "snapshot_seq", "replay_corruptions", "snapshot_invalid"):
            if k in store:
                rows[k] = str(store[k])
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH, retain=True),
            created=int(time.time()),  # brokerlint: ok=R3 $SYS stamps are wall-clock (operator-correlatable)
        )
        for name, payload in rows.items():
            pk.topic_name = SYS_PREFIX + "/broker/durable/" + name
            pk.payload = payload.encode()
            retained = pk.copy(False)
            self.topics.retain_message(retained)
            if self._retained_engine is not None:
                self._retained_engine.note_retained(retained.topic_name, True)
            self.publish_to_subscribers(pk)

    def _publish_slo_transition(self, name: str, payload: dict) -> None:
        """Publish one objective's breach/recovery as a retained
        ``$SYS/broker/slo/<name>`` message (mqtt_tpu.slo calls this on
        transitions only, from the housekeeping tick's event-loop
        context — the same path the periodic $SYS publisher uses)."""
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH, retain=True),
            created=int(time.time()),  # brokerlint: ok=R3 $SYS transition stamps are wall-clock (operator-correlatable)
        )
        pk.topic_name = SYS_PREFIX + "/broker/slo/" + name
        pk.payload = json.dumps(payload).encode()
        self.topics.retain_message(pk.copy(False))
        if self._retained_engine is not None:
            self._retained_engine.note_retained(pk.topic_name, True)
        self.publish_to_subscribers(pk)

    def health_report(self) -> tuple[bool, dict]:
        """The ``GET /healthz`` readiness snapshot (ISSUE 14 satellite).

        503 (not ready) only for conditions under which the broker
        should be pulled from rotation: draining/shutdown, a governor
        in SHED, or a dead staging pipeline. A tripped matcher breaker
        or dark mesh edges DEGRADE (reported in the body, readiness
        holds) — the broker still serves through its fallback paths,
        and flapping a load balancer on a self-healing breaker would
        amplify the incident."""
        not_ready: list[str] = []
        degraded: list[str] = []
        detail: dict = {}
        if self._draining or self.done.is_set():
            not_ready.append("draining")
        if self._durable["recovering"]:
            # restored state is still re-registering: a load balancer
            # must not route sessions at a half-restored map
            not_ready.append("recovering")
        if self._durable["replayed_keys"] or self._durable["restore_batches"]:
            detail["durable"] = {
                "recovering": self._durable["recovering"],
                "recovery_seconds": round(
                    self._durable["recovery_seconds"], 3
                ),
                "replayed_keys": self._durable["replayed_keys"],
            }
        gov = self.overload
        if gov is not None:
            from .overload import SHED

            detail["governor"] = {
                "state": str(gov.state),
                "pressure": round(gov.pressure, 4),
            }
            if gov.state == SHED:
                not_ready.append("governor_shed")
        stage = self._stage
        if stage is not None:
            alive = stage.alive()
            detail["staging"] = {
                "alive": alive,
                "pending": stage.pending_depth,
                "inflight": stage.inflight_batches,
            }
            if not alive:
                not_ready.append("staging_dead")
        if self.matcher is not None:
            breaker = getattr(self.matcher, "breaker", None)
            if breaker is not None:
                state = str(breaker.state)
                detail["matcher_breaker"] = {"state": state}
                if state != "closed":
                    degraded.append("matcher_breaker_" + state)
        if self._retained_engine is not None:
            state = str(self._retained_engine.breaker.state)
            detail["retained_breaker"] = {"state": state}
            if state != "closed":
                # retained matching degrades to the host walk — serve on
                degraded.append("retained_breaker_" + state)
        c = self._cluster
        if c is not None:
            from .cluster import PEER_PARTITIONED

            ch: dict = {"worker": c.worker_id, "peers": c.peer_count}
            partitioned = sorted(
                p
                for p, ph in c._health.items()
                if ph.state == PEER_PARTITIONED
            )
            if partitioned:
                ch["partitioned_peers"] = partitioned
                degraded.append("cluster_partitioned_peers")
            if c.topo is not None:
                neighbors = c.topo.neighbors()
                links = sum(1 for p in neighbors if p in c._writers)
                ch["epoch"] = c.topo.epoch_num()
                ch["tree_links"] = links
                ch["tree_neighbors"] = len(neighbors)
                ch["is_root"] = c.topo.is_root()
                if links < len(neighbors):
                    degraded.append("cluster_tree_edges_down")
            detail["cluster"] = ch
        if self.slo is not None:
            breached = sorted(
                name
                for name, st in self.slo.state().items()
                if st.get("breached")
            )
            detail["slo"] = {"objectives": len(self.slo.objectives)}
            if breached:
                detail["slo"]["breached"] = breached
                degraded.append("slo_breached")
        plane = self.device_stats
        if plane is not None:
            # device plane (ISSUE 18): HBM past the watermark or a
            # breached skew objective DEGRADE — the broker still
            # serves, but the multi-chip frontier is unhealthy and the
            # body says which chip-level instrument tripped. Readiness
            # NEVER flips on device telemetry.
            ratio = plane.hbm_ratio()
            detail["devices"] = {
                "hbm_ratio": round(ratio, 4),
                "hbm_watermark": plane.hbm_watermark,
                "skew_ratio": round(plane.skew_ratio(), 4),
            }
            if ratio >= plane.hbm_watermark and ratio > 0.0:
                degraded.append("hbm_watermark")
            if self.slo is not None and any(
                st.get("breached")
                and st.get("family") == "mqtt_tpu_device_skew_ratio"
                for st in self.slo.state().values()
            ):
                degraded.append("device_skew")
        ok = not not_ready
        detail["ok"] = ok
        detail["not_ready"] = not_ready
        detail["degraded"] = degraded
        return ok, detail

    def _overload_transition(self, old: str, new: str) -> None:
        """Governor transition observer: entering SHED dumps the flight
        recorder — the storm arrives with a stage-level trace attached."""
        from .overload import SHED

        if new == SHED:
            extra = {"from": old, "to": new}
            try:
                extra["gauges"] = self.overload.gauges()
            except Exception:  # pragma: no cover  # brokerlint: ok=R4 best-effort dump context; the flight dump itself still fires
                pass
            self.telemetry.trigger_dump("overload_shed", extra)

    def host_profile_block(self) -> dict:
        """The BENCH-json host-profile block: profiler aggregates, the
        topic sketch, the fan-out amplification numbers, and the top-3
        contended locks — config 8's artifact fields (the ROADMAP item 3
        success criteria, measured per round)."""
        out: dict = {}
        if self.host_profiler is not None:
            out["profiler"] = self.host_profiler.bench_block()
        if self.topic_sketch is not None:
            out["topics"] = self.topic_sketch.bench_block()
        if self.telemetry is not None:
            out["fanout"] = self.telemetry.fanout_block(
                self.info.messages_received
            )
            plane = self.telemetry.lock_plane
            if plane is not None:
                out["top_contended_locks"] = plane.top_contended(3)
        return out

    # -- overload control plane (mqtt_tpu.overload) ------------------------

    def _outbound_pressure(self) -> float:
        """Aggregate outbound backlog — publishes parked in every
        client's bounded outbound queue — normalized against the
        configured cap (the governor's 'subscribers are not draining'
        signal)."""
        clients = self.clients
        try:
            # lock-free iteration: the signal is a statistical sample,
            # and copying the whole registry per evaluation would cost
            # an O(clients) allocation 4x/second at the target scale
            total = sum(
                cl.state.outbound_qty for cl in clients.internal.values()
            )
        except RuntimeError:  # a connect/disconnect resized mid-walk
            total = sum(
                cl.state.outbound_qty for cl in clients.get_all().values()
            )
        self._outbound_backlog = total
        return total / self.options.overload_max_outbound_backlog

    def sweep_overload(self) -> None:
        """One governor housekeeping pass (event-loop tick, 1 Hz): force
        a pressure evaluation, then evict slow consumers while shedding —
        DISCONNECT 0x97 Quota Exceeded, the reference's drop-on-slow-
        consumer posture escalated to eviction so their backlog frees.

        A slow consumer shows up two ways: its bounded outbound queue
        stays full (drops accumulate — ``outbound_full_since`` from the
        drop paths), or its TRANSPORT write buffer stays past the
        configured watermark (asyncio buffers unsent bytes unboundedly,
        which is the actual OOM vector a non-reading subscriber
        creates). Either condition persisting past the grace window
        while SHED evicts the client."""
        ov = self.overload
        if ov is None:
            return
        ov.evaluate(force=True)
        # under the shard fabric each shard sweeps ITS clients on its
        # own loop (mqtt_tpu.shards LoopShard._tick) — transport-buffer
        # reads and eviction disconnects stay loop-local, exactly the
        # single-loop sweep's invariant; the main tick covers clients
        # the main loop owns (and loop-less ones: tests, mocks)
        try:
            here: Optional[asyncio.AbstractEventLoop] = (
                asyncio.get_running_loop()
            )
        except RuntimeError:
            here = self._main_loop
        self.sweep_clients_for_loop(here, include_unowned=True)

    def sweep_clients_for_loop(
        self,
        loop: Optional[asyncio.AbstractEventLoop],
        include_unowned: bool = False,
    ) -> int:
        """One slow-consumer eviction pass over the clients ``loop``
        owns (every client when no fabric is attached — the single-loop
        path unchanged). Returns the evictions performed; the shard
        housekeeping tick feeds it into the per-shard counter."""
        ov = self.overload
        if ov is None:
            return 0
        buf_limit = self.options.overload_client_buffer_limit_bytes
        now = time.monotonic()
        evicted = 0
        for cl in self.clients.get_all().values():
            if cl.net.inline or cl.closed:
                continue
            if self._fabric is not None:
                owner = cl.net.loop
                if owner is not loop and not (
                    owner is None and include_unowned
                ):
                    continue
            buffered = 0
            if cl.net.writer is not None:
                try:
                    buffered = cl.net.writer.transport.get_write_buffer_size()
                except Exception:
                    buffered = 0
            qfull = cl.state.outbound.full()
            # a consumer whose buffer SHRANK since the last sweep is
            # draining — behind, but alive; only a backlog that never
            # recedes marks a stalled consumer
            draining = buffered < cl.state.sweep_buffered
            cl.state.sweep_buffered = buffered
            if draining or not (buffered > buf_limit or qfull):
                cl.state.backlog_over_since = None
            elif cl.state.backlog_over_since is None:
                cl.state.backlog_over_since = now
            over_since = cl.state.backlog_over_since
            # the drop clock may predate this sweep's first observation
            full_since = cl.state.outbound_full_since
            if qfull and not draining and full_since is not None:
                over_since = (
                    full_since
                    if over_since is None
                    else min(over_since, full_since)
                )
            if over_since is not None and ov.evict_due(over_since):
                ov.note_eviction()
                evicted += 1
                self.log.warning(
                    "evicting slow consumer under overload: client=%s "
                    "backlogged_for=%.1fs buffered=%dB queue_full=%s",
                    cl.id,
                    now - over_since,
                    buffered,
                    qfull,
                )
                try:
                    self.disconnect_client(cl, ERR_QUOTA_EXCEEDED)
                except Code:
                    pass
                # deliberately a GRACEFUL close: a victim that resumes
                # reading still sees its queued publishes + the 0x97
                # DISCONNECT (the contract test_overload pins); one that
                # never reads leaves an unflushable transport, which the
                # BOUNDED close_all drain (listeners.Listeners) reaps at
                # shutdown instead of wedging on it
        return evicted

    def shard_backlog(self, loop: Any) -> int:
        """Queued outbound publishes across the clients one shard loop
        owns (the per-shard face of the aggregate backlog gauge). One
        scrape calls this once PER SHARD, so the walk over the client
        registry is computed once and memoized briefly — N shard gauges
        cost one pass, not N (the memo staleness is far below the
        scrape interval)."""
        now = time.monotonic()
        cached = self._shard_backlog_memo
        if cached is None or now - cached[0] > 0.5:
            totals: dict = {}
            for cl in self.clients.get_all().values():
                if not cl.closed:
                    owner = cl.net.loop
                    totals[owner] = (
                        totals.get(owner, 0) + cl.state.outbound.qsize()
                    )
            cached = (now, totals)
            self._shard_backlog_memo = cached
        return cached[1].get(loop, 0)

    def _resolve_tenant(self, cl: Client) -> None:
        """CONNECT-time tenant resolution (mqtt_tpu.tenancy): map the
        client (username first, then client id) to its tenant, scope the
        registry identity into the tenant namespace — two tenants' equal
        client ids can never collide or take each other's sessions over
        — and apply the tenant's quota class through the governor's
        priority-class machinery. Runs AFTER authentication (an
        unauthenticated client must not resolve into a tenant) and
        BEFORE _assign_priority_class (a per-user class mapping
        overrides the tenant-wide one)."""
        plane = self._tenancy
        if plane is None or cl.net.inline:
            return
        from .tenancy import scope_client_id

        username = cl.properties.username
        uname = (
            username.decode("utf-8", "replace")
            if isinstance(username, (bytes, bytearray))
            else (username or "")
        )
        tenant = plane.resolve(uname, cl.id)
        if tenant is None:
            return
        cl.tenant = tenant
        cl.id = scope_client_id(tenant.name, cl.id)
        if tenant.quota_class:
            weights = self.options.overload_priority_classes or {}
            cl.priority_class = tenant.quota_class
            cl.priority_weight = float(weights.get(tenant.quota_class, 1.0))

    def _assign_priority_class(self, cl: Client) -> None:
        """Resolve the client's shed-priority class at CONNECT
        (mqtt_tpu.overload priority-weighted shedding): the config map
        keys on username first, then client id; the resolved class's
        quota multiplier is cached on the client so the admit/read-delay
        hot paths pay one attribute read. Embedders may overwrite
        ``cl.priority_weight`` from an on_connect hook."""
        users = self.options.overload_priority_users
        if not users:
            return
        username = cl.properties.username
        uname = (
            username.decode("utf-8", "replace")
            if isinstance(username, (bytes, bytearray))
            else username
        )
        cid = cl.id
        if cid[:1] == NS_CHAR:
            # tenant clients are registered under their SCOPED id
            # (_resolve_tenant); the operator's map keys on the id the
            # client actually sent
            from .tenancy import local_client_id

            cid = local_client_id(cid)
        klass = users.get(uname) or users.get(cid)
        if klass is None:
            return
        cl.priority_class = klass
        weights = self.options.overload_priority_classes or {}
        cl.priority_weight = float(weights.get(klass, 1.0))

    def _connect_admission(self, cl: Client, listener: str) -> Optional[Code]:
        """The per-listener CONNECT admission verdict: None admits; a
        Code refuses (the caller CONNACKs it and drops the connection).
        Local/inline attachments and listeners configured with
        ``admission=False`` are exempt; admin-ACL clients (read access
        to the $SYS tree) draw from the governor's always-admit
        reserve."""
        ov = self.overload
        if (
            ov is None
            or not self.options.overload_admission
            or cl.net.inline
            or listener == LOCAL_LISTENER
        ):
            return None
        lst = self.listeners.get(listener)
        if lst is not None and not getattr(lst.config, "admission", True):
            return None
        if self._draining:
            ov.note_connect_refused()  # the gauge counts 0x89s too
            return ERR_SERVER_BUSY  # 0x89: drain, not quota
        # the ACL walk runs LAZILY inside admit_connect: only when the
        # governor would otherwise refuse and reserve budget remains —
        # the steady-state NORMAL CONNECT never pays it
        if ov.admit_connect(
            admin=lambda: self.hooks.on_acl_check(
                cl, SYS_PREFIX + "/broker/overload/state", False
            )
        ):
            return None
        return ERR_QUOTA_EXCEEDED  # 0x97

    async def establish_connection(self, listener: str, reader, writer) -> None:
        """Attach a newly accepted connection (server.go:398-401)."""
        from .shards import SHARD_TASK_ATTR

        task = asyncio.current_task()
        if task is not None and getattr(task, SHARD_TASK_ATTR, None) is None:
            # ClientsWg analog (listeners.go:43). Shard-fabric tasks are
            # tracked by their OWN shard (mqtt_tpu.shards) — the main
            # loop must never gather a foreign loop's tasks
            self.listeners.client_tasks.add(task)
            task.add_done_callback(self.listeners.client_tasks.discard)
        cl = self.new_client(reader, writer, listener, "", False)
        await self.attach_client(cl, listener)

    async def attach_client(self, cl: Client, listener: str) -> None:
        """Validate an incoming connection, run the CONNECT handshake, and
        read packets until disconnect (server.go:405-494)."""
        # the loop OWNING this transport: every cross-shard write/close
        # marshals onto it (mqtt_tpu.shards); single-loop brokers record
        # the main loop and every check short-circuits loop-local
        cl.net.loop = asyncio.get_running_loop()
        cl._handler_task = asyncio.current_task()
        if self._fabric is not None:
            # per-shard read-side decode batching, default-on inside
            # the fabric (ISSUE 15)
            cl.scan_gate = self._fabric.gate_for(cl.net.loop)
        cl.start_write_loop()
        err: Optional[Exception] = None
        connected = False
        try:
            pk = await self.read_connection_packet(cl)
            cl.parse_connect(listener, pk)
            if self.info.clients_connected >= self.options.capabilities.maximum_clients:
                if cl.properties.protocol_version < 5:
                    self.send_connack(cl, ERR_SERVER_UNAVAILABLE, False, None)
                else:
                    self.send_connack(cl, ERR_SERVER_BUSY, False, None)
                raise ERR_SERVER_BUSY()

            code = self.validate_connect(cl, pk)  # [MQTT-3.1.4-1] [MQTT-3.1.4-2]
            if code != CODE_SUCCESS:
                self.send_connack(cl, code, False, None)
                raise code()  # [MQTT-3.2.2-7] [MQTT-3.1.4-6]

            self.hooks.on_connect(cl, pk)  # error aborts

            cl.refresh_deadline(cl.state.keepalive)
            if not self.hooks.on_connect_authenticate(cl, pk):  # [MQTT-3.1.4-2]
                self.send_connack(cl, ERR_BAD_USERNAME_OR_PASSWORD, False, None)
                raise ERR_BAD_USERNAME_OR_PASSWORD()

            self._resolve_tenant(cl)
            self._assign_priority_class(cl)
            # per-listener admission (mqtt_tpu.overload federation): a
            # broker in THROTTLE/SHED refuses NEW connections up front —
            # CONNACK 0x97 Quota Exceeded (0x89 while draining) — except
            # the small always-admit reserve for admin-ACL clients.
            # AFTER authentication, deliberately: an unauthenticated
            # client claiming the admin identity must not be able to
            # burn the operator's reserve slots
            refusal = self._connect_admission(cl, listener)
            if refusal is not None:
                if cl.properties.protocol_version < 5:
                    # v3 CONNACK codes stop at 5: 0x97/0x89 have no
                    # translation, so the v3 wire answer is the same
                    # one the maximum_clients refusal uses
                    self.send_connack(cl, ERR_SERVER_UNAVAILABLE, False, None)
                else:
                    self.send_connack(cl, refusal, False, None)
                raise refusal()

            with self._conn_lock:
                self.info.clients_connected += 1
            connected = True
            if cl.tenant is not None and self._tenancy is not None:
                self._tenancy.note_connect(cl.tenant)

            self.hooks.on_session_establish(cl, pk)

            # cross-shard takeover quiesce (mqtt_tpu.shards): the
            # session migration below clones/clears the EXISTING
            # client's inflight + subscriptions, which is only safe
            # once its owner loop has stopped serving it — disconnect
            # it ON that loop and AWAIT completion before touching its
            # state (the single-loop path needs none of this: the
            # migration and the old client share one loop)
            if self._fabric is not None:
                existing = self.clients.get(cl.id)
                if existing is not None and not self._client_loop_local(
                    existing
                ):
                    await self._quiesce_takeover(existing)

            session_present = self.inherit_client_session(pk, cl)
            self.clients.add_client(cl)  # [MQTT-4.1.0-1]

            self.send_connack(cl, code, session_present, None)  # [MQTT-3.1.4-5]
            self.will_delayed.delete(cl.id)  # [MQTT-3.1.3-9]

            if session_present:
                cl.resend_inflight_messages(True)

            self.hooks.on_session_established(cl, pk)

            try:
                await cl.read(self.receive_packet)
            except Exception as e:
                err = e
                self.send_lwt(cl)
                cl.stop(e)
            else:
                cl.properties.will = Will()  # [MQTT-3.14.4-3] [MQTT-3.1.2-10]

            self.log.debug(
                "client disconnected: error=%s client=%s remote=%s listener=%s",
                err, cl.id, cl.net.remote, listener,
            )

            expire = (
                cl.properties.protocol_version == 5
                and cl.properties.props.session_expiry_interval == 0
            ) or (cl.properties.protocol_version < 5 and cl.properties.clean)
            self.hooks.on_disconnect(cl, err, expire)

            if expire and not cl.is_taken_over:
                cl.clear_inflights()
                self.unsubscribe_client(cl)
                self.clients.delete(cl.id)  # [MQTT-4.1.0-2]
        except Exception as e:
            err = e
        finally:
            if connected:
                with self._conn_lock:
                    self.info.clients_connected -= 1
                if cl.tenant is not None and self._tenancy is not None:
                    self._tenancy.note_disconnect(cl.tenant)
            cl.stop(err)
        if err is not None and not isinstance(
            err, (asyncio.IncompleteReadError, ConnectionError, ConnectionClosedError)
        ):
            self.log.debug("connection ended: %s", err)

    async def read_connection_packet(self, cl: Client) -> Packet:
        """The first packet MUST be CONNECT [MQTT-3.1.0-1]
        (server.go:498-515)."""
        fh = FixedHeader()
        await cl.read_fixed_header(fh)
        if fh.type != pkts.CONNECT:
            raise ERR_PROTOCOL_VIOLATION_REQUIRE_FIRST_CONNECT()
        return await cl.read_packet(fh)

    def receive_packet(self, cl: Client, pk: Packet):
        """Process one inbound packet; a v5 error code disconnects the client
        (server.go:519-534). Returns a coroutine when processing defers to
        the publish staging loop — the caller's read loop awaits it, so the
        publishing client blocks on its own fan-out (the reference's
        per-connection-goroutine semantics) while other clients proceed."""
        try:
            result = self.process_packet(cl, pk)
        except Code as code:
            self._packet_error(cl, code)
            raise
        if asyncio.iscoroutine(result):
            return self._receive_deferred(cl, result)
        return None

    async def _receive_deferred(self, cl: Client, coro) -> None:
        try:
            await coro
        except Code as code:
            self._packet_error(cl, code)
            raise

    def _packet_error(self, cl: Client, code: Code) -> None:
        if cl.properties.protocol_version == 5 and code.code >= ERR_UNSPECIFIED_ERROR.code:
            try:
                self.disconnect_client(cl, code)
            except Exception:  # brokerlint: ok=R4 already on the error path; the warning below records the packet error
                pass
        self.log.warning(
            "error processing packet: error=%s client=%s listener=%s",
            code, cl.id, cl.net.listener,
        )

    def validate_connect(self, cl: Client, pk: Packet) -> Code:
        """Connect compliance checks beyond the codec's (server.go:537-556)."""
        code = pk.connect_validate()
        if code != CODE_SUCCESS:
            return code
        if (
            cl.properties.protocol_version < 5
            and not pk.connect.clean
            and pk.connect.client_identifier == ""
        ):
            return ERR_UNSPECIFIED_ERROR
        caps = self.options.capabilities
        if cl.properties.protocol_version < caps.minimum_protocol_version:
            return ERR_UNSUPPORTED_PROTOCOL_VERSION  # [MQTT-3.1.2-2]
        if cl.properties.will.qos > caps.maximum_qos:
            return ERR_QOS_NOT_SUPPORTED  # [MQTT-3.2.2-12]
        if cl.properties.will.retain and caps.retain_available == 0:
            return ERR_RETAIN_NOT_SUPPORTED  # [MQTT-3.2.2-13]
        return code

    async def _quiesce_takeover(self, existing: Client) -> None:
        """Disconnect a to-be-taken-over client ON its owning shard's
        loop and wait for it: after this, the old owner's loop can no
        longer be mutating the session state the takeover migrates
        (its read loop observes ``closed`` before processing anything
        else). The drain also awaits the old ATTACH HANDLER itself, so
        its disconnect epilogue (the expire branch, registry delete)
        has fully run before the migration reads the registry — for a
        persistent session that epilogue keeps the state (not taken
        over yet, not expiring); for a clean session it discards it,
        which is what a clean takeover does anyway. A dead/stopped
        owner loop degrades to a direct stop — the client was not
        being served."""
        loop = existing.net.loop
        if loop is None or not loop.is_running():
            existing.stop(ERR_SESSION_TAKEN_OVER())
            return

        async def _disconnect_and_drain() -> None:
            try:
                self.disconnect_client(existing, ERR_SESSION_TAKEN_OVER)
            except Code:
                pass
            task = existing._handler_task
            if task is not None and task is not asyncio.current_task():
                try:
                    await asyncio.wait_for(asyncio.shield(task), timeout=4.0)
                except Exception:  # brokerlint: ok=R4 bounded drain; a wedged old handler must not hold the CONNECT hostage
                    pass

        try:
            cfut = asyncio.run_coroutine_threadsafe(
                _disconnect_and_drain(), loop
            )
        except RuntimeError:
            existing.stop(ERR_SESSION_TAKEN_OVER())
            return
        try:
            await asyncio.wait_for(asyncio.wrap_future(cfut), timeout=5.0)
        except asyncio.TimeoutError:
            # a wedged owner loop must not hold the CONNECT hostage;
            # the closed flag still fences its data plane
            existing.stop(ERR_SESSION_TAKEN_OVER())

    def inherit_client_session(self, pk: Packet, cl: Client) -> bool:
        """Session takeover: disconnect the existing client with the same id
        and inherit (or discard) its state (server.go:561-603)."""
        existing = self.clients.get(cl.id)
        if existing is not None:
            try:
                self.disconnect_client(existing, ERR_SESSION_TAKEN_OVER)  # [MQTT-3.1.4-3]
            except Code:
                pass
            if pk.connect.clean or (
                existing.properties.clean and existing.properties.protocol_version < 5
            ):  # [MQTT-3.1.2-4] [MQTT-3.1.4-4]
                self.unsubscribe_client(existing)
                existing.clear_inflights()
                existing.state.is_taken_over = True  # after unsubscribe
                return False  # [MQTT-3.2.2-3]

            existing.state.is_taken_over = True
            if len(existing.state.inflight) > 0:
                cl.state.inflight = existing.state.inflight.clone()  # [MQTT-3.1.2-5]
                if (
                    cl.state.inflight.maximum_receive_quota == 0
                    and self.options.capabilities.receive_maximum != 0
                ):
                    cl.state.inflight.reset_receive_quota(
                        self.options.capabilities.receive_maximum
                    )
                    cl.state.inflight.reset_send_quota(cl.properties.props.receive_maximum)

            for sub in existing.state.subscriptions.get_all().values():
                existed = not self.topics.subscribe(cl.id, sub)  # [MQTT-3.8.4-3]
                if not existed:
                    self.info.subscriptions += 1
                cl.state.subscriptions.add(sub.filter, sub)

            # clean existing state so sequential takeovers don't leak
            self.unsubscribe_client(existing)
            existing.clear_inflights()

            self.log.debug(
                "session taken over: client=%s old_remote=%s new_remote=%s",
                cl.id, existing.net.remote, cl.net.remote,
            )
            return True  # [MQTT-3.2.2-3]

        if self.info.clients_connected > self.info.clients_maximum:
            self.info.clients_maximum += 1
        return False  # [MQTT-3.2.2-2]

    def send_connack(
        self, cl: Client, reason: Code, present: bool, properties: Optional[Properties]
    ) -> None:
        """Issue a CONNACK, translating v5 codes for v3 clients
        (server.go:606-663)."""
        if properties is None:
            properties = Properties()
        properties.receive_maximum = self.options.capabilities.receive_maximum  # 3.2.2.3.3
        if cl.state.server_keepalive:  # set dynamically via the on_connect hook
            properties.server_keep_alive = cl.state.keepalive  # [MQTT-3.1.2-21]
            properties.server_keep_alive_flag = True

        if reason.code >= ERR_UNSPECIFIED_ERROR.code:
            if cl.properties.protocol_version < 5:
                reason = V5_CODES_TO_V3.get(reason, reason)
            properties.reason_string = reason.reason
            ack = Packet(
                fixed_header=FixedHeader(type=pkts.CONNACK),
                session_present=False,  # [MQTT-3.2.2-6]
                reason_code=reason.code,  # [MQTT-3.2.2-8]
                properties=properties,
            )
            cl.write_packet(ack)
            return

        caps = self.options.capabilities
        if caps.maximum_qos < 2:
            properties.maximum_qos = caps.maximum_qos  # [MQTT-3.2.2-9]
            properties.maximum_qos_flag = True
        if cl.properties.props.assigned_client_id:
            properties.assigned_client_id = cl.properties.props.assigned_client_id  # [MQTT-3.1.3-7]
        if cl.properties.props.session_expiry_interval > caps.maximum_session_expiry_interval:
            properties.session_expiry_interval = caps.maximum_session_expiry_interval
            properties.session_expiry_interval_flag = True
            cl.properties.props.session_expiry_interval = properties.session_expiry_interval
            cl.properties.props.session_expiry_interval_flag = True

        ack = Packet(
            fixed_header=FixedHeader(type=pkts.CONNACK),
            session_present=present,
            reason_code=reason.code,  # [MQTT-3.2.2-8]
            properties=properties,
        )
        cl.write_packet(ack)

    # -- packet processing -------------------------------------------------

    def process_packet(self, cl: Client, pk: Packet):
        """Dispatch one inbound packet by type (server.go:667-730); raises a
        Code on protocol errors. A staged PUBLISH returns a coroutine whose
        await completes the fan-out (hook order — on_published before
        on_packet_processed — is preserved inside it)."""
        t = pk.fixed_header.type
        if (
            t == pkts.PUBLISH
            and self._stage is not None
            and not cl.net.inline
        ):
            return self._process_publish_deferred(cl, pk)
        err: Optional[Exception] = None
        try:
            if t == pkts.CONNECT:
                self.process_connect(cl, pk)
            elif t == pkts.DISCONNECT:
                self.process_disconnect(cl, pk)
            elif t == pkts.PINGREQ:
                self.process_pingreq(cl, pk)
            elif t == pkts.PUBLISH:
                self._dispatch_publish(cl, pk)
            elif t == pkts.PUBACK:
                self.process_puback(cl, pk)
            elif t == pkts.PUBREC:
                self.process_pubrec(cl, pk)
            elif t == pkts.PUBREL:
                self.process_pubrel(cl, pk)
            elif t == pkts.PUBCOMP:
                self.process_pubcomp(cl, pk)
            elif t == pkts.SUBSCRIBE:
                code = pk.subscribe_validate()
                if code != CODE_SUCCESS:
                    raise code()
                self.process_subscribe(cl, pk)
            elif t == pkts.UNSUBSCRIBE:
                code = pk.unsubscribe_validate()
                if code != CODE_SUCCESS:
                    raise code()
                self.process_unsubscribe(cl, pk)
            elif t == pkts.AUTH:
                code = pk.auth_validate()
                if code != CODE_SUCCESS:
                    raise code()
                self.process_auth(cl, pk)
            else:
                raise pkts.ERR_NO_VALID_PACKET_AVAILABLE()
        except Exception as e:
            err = e
            raise
        finally:
            self.hooks.on_packet_processed(cl, pk, err)

        self._drain_quota_starved(cl)

    def _dispatch_publish(self, cl: Client, pk: Packet):
        """Validate + process one PUBLISH — the single dispatch point shared
        by the sync and staged paths; returns a coroutine when staged."""
        code = pk.publish_validate(self.options.capabilities.topic_alias_maximum)
        if code != CODE_SUCCESS:
            raise code()
        return self.process_publish(cl, pk)

    async def _process_publish_deferred(self, cl: Client, pk: Packet) -> None:
        """The staged PUBLISH path: identical dispatch semantics to the sync
        path (validate, process, on_packet_processed with the error, quota
        drain) with the fan-out awaited through the staging loop."""
        err: Optional[Exception] = None
        try:
            deferred = self._dispatch_publish(cl, pk)
            if deferred is not None:
                await deferred
        except Exception as e:
            err = e
            raise
        finally:
            self.hooks.on_packet_processed(cl, pk, err)
        self._drain_quota_starved(cl)

    def _drain_quota_starved(self, cl: Client) -> None:
        # post-process: drain one quota-starved inflight if quota freed up
        if len(cl.state.inflight) > 0 and cl.state.inflight.send_quota > 0:
            nxt = cl.state.inflight.next_immediate()
            if nxt is not None:
                try:
                    cl.write_packet(nxt)
                except Exception:  # brokerlint: ok=R4 client mid-teardown; the inflight store still reconciles below
                    pass
                if cl.state.inflight.delete(nxt.packet_id):
                    self.info.inflight -= 1
                cl.state.inflight.decrease_send_quota()

    def process_connect(self, cl: Client, pk: Packet) -> None:
        """A second CONNECT is a protocol violation [MQTT-3.1.0-2]
        (server.go:734-737)."""
        self.send_lwt(cl)
        raise ERR_PROTOCOL_VIOLATION_SECOND_CONNECT()

    def process_pingreq(self, cl: Client, pk: Packet) -> None:
        cl.write_packet(Packet(fixed_header=FixedHeader(type=pkts.PINGRESP)))  # [MQTT-3.12.4-1]

    # -- inline client api -------------------------------------------------

    def publish(self, topic: str, payload: bytes, retain: bool, qos: int) -> None:
        """Inline publish into the broker, bypassing ACL (server.go:752-767)."""
        if not self.options.inline_client:
            raise InlineClientNotEnabledError()
        assert self.inline_client is not None  # built in __init__ with the option on
        self.inject_packet(
            self.inline_client,
            Packet(
                fixed_header=FixedHeader(type=pkts.PUBLISH, qos=qos, retain=retain),
                topic_name=topic,
                payload=payload,
                packet_id=qos,  # unprocessed inbound qos still needs a packet id
            ),
        )

    def subscribe(self, filter: str, subscription_id: int, handler: InlineSubFn) -> None:
        """Inline (in-process) subscription (server.go:771-808)."""
        if not self.options.inline_client:
            raise InlineClientNotEnabledError()
        assert self.inline_client is not None  # built in __init__ with the option on
        if handler is None:
            raise ERR_INLINE_SUBSCRIPTION_HANDLER_INVALID()
        predicates: tuple = ()
        if self._predicates is not None:
            base, pred_suffix = split_predicate_suffix(filter)
            if pred_suffix:
                filter = base
                predicates = (pred_suffix,)
        if not is_valid_filter(filter, False):
            raise ERR_TOPIC_FILTER_INVALID()
        if self._predicates is not None:
            if predicates:
                self._predicates.register(predicates[0])
            # re-subscribing the same (identifier, filter) REPLACES the
            # stored inline subscription: drop the replaced one's rule
            # refs (after registering, like the client SUBSCRIBE path)
            replaced = self.topics.inline_subscription(subscription_id, filter)
            if replaced is not None and replaced.predicates:
                self._predicates.release(replaced.predicates)
        subscription = Subscription(
            identifier=subscription_id, filter=filter, predicates=predicates
        )
        pk = self.hooks.on_subscribe(
            self.inline_client,
            Packet(
                origin=self.inline_client.id,
                fixed_header=FixedHeader(type=pkts.SUBSCRIBE),
                filters=[subscription],
            ),
        )
        inline_sub = InlineSubscription(
            filter=filter,
            identifier=subscription_id,
            handler=handler,
            predicates=predicates,
        )
        self.topics.inline_subscribe(inline_sub)
        self.hooks.on_subscribed(self.inline_client, pk, bytes([CODE_SUCCESS.code]))
        for pkv in self.topics.messages(filter):  # [MQTT-3.8.4-4]
            if self._predicates is not None and not self._predicates.passes_retained(
                subscription, bytes(pkv.payload)
            ):
                continue
            handler(self.inline_client, subscription, pkv)

    def unsubscribe(self, filter: str, subscription_id: int) -> None:
        """Remove an inline subscription (server.go:813-836)."""
        if not self.options.inline_client:
            raise InlineClientNotEnabledError()
        assert self.inline_client is not None  # built in __init__ with the option on
        if self._predicates is not None:
            base, pred_suffix = split_predicate_suffix(filter)
            if pred_suffix:
                filter = base
        if not is_valid_filter(filter, False):
            raise ERR_TOPIC_FILTER_INVALID()
        pk = self.hooks.on_unsubscribe(
            self.inline_client,
            Packet(
                origin=self.inline_client.id,
                fixed_header=FixedHeader(type=pkts.UNSUBSCRIBE),
                filters=[Subscription(identifier=subscription_id, filter=filter)],
            ),
        )
        if self._predicates is not None:
            # release the STORED subscription's rule refs, and only when
            # a subscription is actually removed — an unsubscribe for a
            # (filter, id) that never existed must not underflow a rule
            # other live subscriptions still reference
            stored = self.topics.inline_subscription(subscription_id, filter)
            removed = self.topics.inline_unsubscribe(subscription_id, filter)
            if removed and stored is not None and stored.predicates:
                self._predicates.release(stored.predicates)
        else:
            self.topics.inline_unsubscribe(subscription_id, filter)
        self.hooks.on_unsubscribed(self.inline_client, pk)

    def inject_packet(self, cl: Client, pk: Packet) -> None:
        """Process a packet as if sent by ``cl``, bypassing the network
        (server.go:840-854). A staged PUBLISH completes its fan-out as a
        scheduled task (or synchronously when no loop is running)."""
        pk.protocol_version = cl.properties.protocol_version
        result = self.process_packet(cl, pk)
        if asyncio.iscoroutine(result):
            try:
                # found by brokerlint R13: the fan-out task was
                # fire-and-forget, so asyncio's weak reference was the
                # only thing keeping it alive mid-flight
                task = asyncio.get_running_loop().create_task(result)
                self.listeners.client_tasks.add(task)
                task.add_done_callback(self.listeners.client_tasks.discard)
            except RuntimeError:
                asyncio.run(result)
        self.info.packets_received += 1
        if pk.fixed_header.type == pkts.PUBLISH:
            self.info.messages_received += 1

    # -- publish flow ------------------------------------------------------

    def process_publish(self, cl: Client, pk: Packet):
        """The publish hot path (server.go:857-968). With the staging loop
        active, returns a coroutine completing the fan-out (QoS acks are
        already written synchronously before it is returned)."""
        if not cl.net.inline and not is_valid_filter(pk.topic_name, True):
            return

        if cl.state.inflight.receive_quota == 0:
            self.disconnect_client(cl, ERR_RECEIVE_MAXIMUM)  # ~[MQTT-3.3.4-7/-8]
            return

        if not cl.net.inline and not self.hooks.on_acl_check(cl, pk.topic_name, True):
            if pk.fixed_header.qos == 0:
                return
            if cl.properties.protocol_version != 5:
                self.disconnect_client(cl, ERR_NOT_AUTHORIZED)
                return
            ack_type = pkts.PUBREC if pk.fixed_header.qos == 2 else pkts.PUBACK
            ack = self.build_ack(pk.packet_id, ack_type, 0, pk.properties, ERR_NOT_AUTHORIZED)
            cl.write_packet(ack)
            return

        pk.origin = cl.id
        pk.created = int(time.time())  # brokerlint: ok=R3 packet creation stamp is wall-clock (persists/expires across restarts)
        expiry = _minimum(
            self.options.capabilities.maximum_message_expiry_interval,
            pk.properties.message_expiry_interval,
        )
        if expiry > 0:
            pk.expiry = pk.created + expiry

        if not cl.net.inline:
            pki = cl.state.inflight.get(pk.packet_id)
            if pki is not None:
                if pki.fixed_header.type == pkts.PUBREC:  # [MQTT-4.3.3-10]
                    ack = self.build_ack(
                        pk.packet_id, pkts.PUBREC, 0, pk.properties, ERR_PACKET_IDENTIFIER_IN_USE
                    )
                    cl.write_packet(ack)
                    return
                if cl.state.inflight.delete(pk.packet_id):  # [MQTT-4.3.2-5]
                    self.info.inflight -= 1

        if pk.properties.topic_alias_flag and pk.properties.topic_alias > 0:  # [MQTT-3.3.2-11]
            pk.topic_name = cl.state.topic_aliases.inbound.set(
                pk.properties.topic_alias, pk.topic_name
            )

        if pk.fixed_header.qos > self.options.capabilities.maximum_qos:
            pk.fixed_header.qos = self.options.capabilities.maximum_qos  # [MQTT-3.2.2-9]

        # overload admission (mqtt_tpu.overload): while SHEDDING, traffic
        # past the per-client window budget is refused GRACEFULLY — QoS0
        # drops (counted), QoS1/2 acks 0x97 Quota Exceeded (v5; v3/v4
        # acks carry no reason code, so the excess is simply not fanned
        # out — the reference's drop-on-overload posture). Runs after
        # alias resolution so alias state stays coherent across sheds,
        # and never touches $SYS/LWT/retained housekeeping (those flow
        # through publish_to_subscribers, not here).
        if (
            not cl.net.inline
            and self.overload is not None
            and not self.overload.admit(cl)
        ):
            self.info.messages_dropped += 1
            if cl.tenant is not None:
                # per-tenant shed accounting: quota classes must be
                # visibly shaping who sheds (mqtt_tpu.tenancy)
                cl.tenant.messages_dropped += 1
            if pk.fixed_header.qos == 0:
                return
            ack_type = pkts.PUBREC if pk.fixed_header.qos == 2 else pkts.PUBACK
            cl.write_packet(
                self.build_ack(
                    pk.packet_id, ack_type, 0, pk.properties, ERR_QUOTA_EXCEEDED
                )
            )
            return

        # telemetry stage clock (attached by the read loop on sampled
        # publishes): everything from decode's end to here — validation,
        # quota, alias resolution, the overload admission verdict
        clock = getattr(pk, "_tclock", None)
        tele = self.telemetry
        if (
            tele is not None
            and tele.tracer is not None
            and pk.properties.user
        ):
            # an inbound v5 `trace-id` user property adopts the client's
            # trace id (mqtt_tpu.tracing); off the adopted path this is
            # one empty-list check
            clock = tele.adopt_trace(pk)
        if clock is not None:
            clock.stamp("admission")
            if self.topic_sketch is not None:
                # topic-cardinality sketch rides the sampling verdict:
                # the same 1-in-N publishes that carry a clock feed the
                # top-K/avg-hits estimate (mqtt_tpu.profiling)
                self.topic_sketch.observe(pk.topic_name)
            trace_id = getattr(clock, "trace_id", None)
            if trace_id is not None and self.options.trace_user_property:
                # client-visible traces: subscribers (and peers on the
                # packet leg) see the trace id as a v5 user property
                from .telemetry import TRACE_USER_PROPERTY

                if not any(
                    u.key == TRACE_USER_PROPERTY for u in pk.properties.user
                ):
                    pk.properties.user.append(
                        UserProperty(TRACE_USER_PROPERTY, trace_id)
                    )

        try:
            pk = self.hooks.on_publish(cl, pk)
        except Code as e:
            if e == ERR_REJECT_PACKET:
                return
            if e == CODE_SUCCESS_IGNORE:
                pk.ignore = True
            elif cl.properties.protocol_version == 5 and pk.fixed_header.qos > 0:
                cl.write_packet(self.build_ack(pk.packet_id, pkts.PUBACK, 0, pk.properties, e))
                return
            # other errors: continue with the original packet (reference
            # server.go:912-925 falls through)

        if cl.tenant is not None:
            # tenant namespace (mqtt_tpu.tenancy): validation, the ACL,
            # aliases, admission, and the on_publish hook all saw the
            # LOCAL topic above; matching, retention, staging, and
            # cluster forwarding operate on the scoped key from here
            # (deliveries strip it back off at the fan-out choke point)
            pk.topic_name = ns_scope_topic(cl.tenant.name, pk.topic_name)
            cl.tenant.messages_in += 1
            cl.tenant.bytes_in += len(pk.payload)

        if pk.fixed_header.retain and self._retained_quota_refused(cl, pk):
            # tenant retained COUNT cap (ISSUE 16): refuse the whole
            # publish — accepting the fan-out while silently dropping
            # retention would leave the publisher believing the topic is
            # retained. Same graceful posture as overload: QoS0 drops
            # (counted), QoS1/2 ack 0x97 Quota Exceeded.
            self.info.messages_dropped += 1
            if cl.tenant is not None:
                cl.tenant.messages_dropped += 1
            if pk.fixed_header.qos == 0:
                return
            ack_type = pkts.PUBREC if pk.fixed_header.qos == 2 else pkts.PUBACK
            cl.write_packet(
                self.build_ack(
                    pk.packet_id, ack_type, 0, pk.properties, ERR_QUOTA_EXCEEDED
                )
            )
            return

        if pk.fixed_header.retain:  # [MQTT-3.3.1-5]
            self.retain_message(cl, pk)

        # inline clients can't handle PUBREC/PUBREL: treat as qos 0 inbound
        if pk.fixed_header.qos == 0 or cl.net.inline:
            if self._stage is not None and not cl.net.inline:
                return self._staged_fan_out(cl, pk)
            self.publish_to_subscribers(pk)
            self._finish_publish_clock(pk)
            self.hooks.on_published(cl, pk)
            return None

        cl.state.inflight.decrease_receive_quota()
        ack = self.build_ack(
            pk.packet_id, pkts.PUBACK, 0, pk.properties, QOS_CODES[pk.fixed_header.qos]
        )  # [MQTT-4.3.2-4]
        if pk.fixed_header.qos == 2:
            ack = self.build_ack(
                pk.packet_id, pkts.PUBREC, 0, pk.properties, CODE_SUCCESS
            )  # [MQTT-3.3.4-1] [MQTT-4.3.3-8]

        if cl.state.inflight.set(ack):
            self.info.inflight += 1
            self.hooks.on_qos_publish(cl, ack, ack.created, 0)

        cl.write_packet(ack)

        if pk.fixed_header.qos == 1:
            if cl.state.inflight.delete(ack.packet_id):
                self.info.inflight -= 1
            cl.state.inflight.increase_receive_quota()
            self.hooks.on_qos_complete(cl, ack)

        if self._stage is not None and not cl.net.inline:
            return self._staged_fan_out(cl, pk)
        self.publish_to_subscribers(pk)
        self._finish_publish_clock(pk)
        self.hooks.on_published(cl, pk)
        return None

    def _finish_publish_clock(self, pk: Packet) -> None:
        """Close out a sampled publish's stage clock after fan-out: the
        final stamp is the fanout write leg, then the record lands in
        the per-stage histograms + flight-recorder ring — and the
        arrival->flush total lands in the per-tenant delivery-latency
        SLI (path=local), the number the SLO engine burns against
        (ISSUE 14)."""
        clock = getattr(pk, "_tclock", None)
        if clock is not None:
            setattr(pk, "_tclock", None)  # a clock observes exactly once
            if not any(s in ("encode", "flush") for s, _ in clock.stages):
                # the batched path already split the fan-out leg into
                # encode/flush sub-stamps; telemetry synthesizes the
                # coarse ``fanout`` stage from their sum (continuity
                # with pre-split rounds — exp/stage_gate.py)
                clock.stamp("fanout")
            self.telemetry.observe_publish(
                clock, pk.topic_name, pk.fixed_header.qos
            )
            self._observe_delivery_sli(clock, pk, "local")

    def _observe_delivery_sli(self, clock, pk: Packet, path: str) -> None:
        """Fold one finished clock into the delivery-latency SLI: the
        tenant label comes off the scoped topic, the value is the
        clock's decode->flush total plus (remote path) the origin
        worker's elapsed stamp."""
        tele = self.telemetry
        if tele is None or not tele.delivery_sli:
            return
        topic = pk.topic_name
        tenant = ns_tenant(topic) if topic[:1] == NS_CHAR else ""
        tele.observe_delivery(
            clock.total() + getattr(clock, "remote_base", 0.0),
            tenant,
            pk.fixed_header.qos,
            path,
            trace_id=getattr(clock, "trace_id", None),
        )

    def _finish_remote_clock(self, pk: Packet) -> None:
        """Close a mesh-forwarded publish's receiving-side clock
        (telemetry.RemoteStageClock, attached by cluster delivery): the
        remote-path delivery SLI reads origin-elapsed + local segment.
        Never routed through observe_publish — remote deliveries must
        not skew this worker's pipeline-stage histograms or flight
        ring."""
        clock = getattr(pk, "_tclock", None)
        if clock is None:
            return
        setattr(pk, "_tclock", None)
        if not any(s in ("encode", "flush") for s, _ in clock.stages):
            clock.stamp("fanout")
        self._observe_delivery_sli(clock, pk, "remote")

    async def _staged_fan_out(self, cl: Client, pk: Packet) -> None:
        """Fan out one publish through the staging loop: the device match
        batch resolves off the event loop and this client awaits only its
        own result (SURVEY.md §7 stage 4; seam: server.go:984-1021)."""
        if not pk.ignore:
            self._stamp_publish_expiry(pk)
            # MQTT+ predicate plane: extract the payload features ONCE
            # on the host; the stage batches them to the device beside
            # the tokenized topics and stamps the resolved pass bits
            # back onto this carrier (mqtt_tpu.predicates)
            eng = self._predicates
            feats = (
                eng.features_for(bytes(pk.payload))
                if eng is not None and eng.active
                else None
            )
            # encrypted-namespace publishes carry a decrypt job whose
            # keystream dispatch rides the same staged batch
            # (mqtt_tpu.tenancy.RecryptJob through MatchStage)
            rjob = self._recrypt_job_for(cl, pk)
            subscribers = await self._stage.submit(
                pk.topic_name, getattr(pk, "_tclock", None), feats, rjob
            )
            self._fan_out(pk, subscribers, feats, rjob)
            if self._cluster is not None:
                self._cluster.forward_packet(pk)
            self._finish_publish_clock(pk)
        self.hooks.on_published(cl, pk)

    def _retained_quota_refused(self, cl: Client, pk: Packet) -> bool:
        """Tenant retained COUNT cap (ISSUE 16): True refuses the publish
        with 0x97 before any state grows. Growth only — clearing (empty
        payload) and overwriting an existing retained topic always pass,
        so a capped tenant can still update or free slots. The topic is
        already namespace-scoped here (process_publish scopes first)."""
        t = cl.tenant
        if t is None or not pk.payload:
            return False
        cap = t.max_retained or self.options.tenant_max_retained
        if cap <= 0 or t.retained_count < cap:
            return False
        if self.topics.retained.get(pk.topic_name) is not None:
            return False  # overwrite, not growth
        t.retained_refused += 1
        return True

    def _subscribe_quota_refused(self, cl: Client, sub: Subscription) -> bool:
        """Tenant subscription COUNT cap (ISSUE 16): True refuses the
        filter with 0x97 before any rule or trie registration. Growth
        only — replacing an existing subscription always passes. Sees
        the LOCAL filter (scoping happens in the grant branch); shared
        ($SHARE) filters are uncapped."""
        t = cl.tenant
        if t is None or is_shared_filter(sub.filter):
            return False
        cap = t.max_subscriptions or self.options.tenant_max_subscriptions
        if cap <= 0 or t.subscriptions_count < cap:
            return False
        scoped = ns_scope_filter(t.name, sub.filter)
        if cl.state.subscriptions.get(scoped) is not None:
            return False  # replacement, not growth
        t.subscriptions_refused += 1
        return True

    def retain_message(self, cl: Client, pk: Packet) -> None:
        """(server.go:972-981)"""
        if self.options.capabilities.retain_available == 0 or pk.ignore:
            return
        out = pk.copy(False)
        existed = self.topics.retained.get(out.topic_name) is not None
        r = self.topics.retain_message(out)
        self.hooks.on_retain_message(cl, pk, r)
        self.info.retained = len(self.topics.retained)
        if self._tenancy is not None and out.topic_name[:1] == NS_CHAR:
            t = self._tenancy.tenant_of_topic(out.topic_name)
            if t is not None:
                # durable COUNT quota bookkeeping (ISSUE 16): growth
                # only on a NEW retained topic, shrink on a real clear
                if r == 1 and not existed:
                    t.retained_count += 1
                elif r == -1 and t.retained_count > 0:
                    t.retained_count -= 1
        if self._retained_engine is not None:
            self._retained_engine.note_retained(out.topic_name, r == 1)

    def publish_to_subscribers(self, pk: Packet) -> None:
        """Match subscribers and fan out (server.go:984-1021).

        The synchronous path always walks the host trie: its callers are
        the housekeeping flows ($SYS ticks, LWT, retained delivery, inline
        publishes), which must never pay a device round trip on the event
        loop. Client PUBLISH traffic takes ``_staged_fan_out`` instead when
        the device matcher is active (mqtt_tpu.staging)."""
        if pk.ignore:
            return
        self._stamp_publish_expiry(pk)
        self._fan_out(pk, self.topics.subscribers(pk.topic_name))
        if self._cluster is not None:
            # peer workers with matching subscribers receive the packet
            # once each and fan out locally ($SYS never forwards; retained
            # packets go to all peers) — mqtt_tpu.cluster
            self._cluster.forward_packet(pk)

    def _stamp_publish_expiry(self, pk: Packet) -> None:
        if pk.created == 0:
            pk.created = int(time.time())  # brokerlint: ok=R3 packet creation stamp is wall-clock (persists/expires across restarts)
        if pk.expiry == 0:
            expiry = _minimum(
                self.options.capabilities.maximum_message_expiry_interval,
                pk.properties.message_expiry_interval,
            )
            if expiry > 0:
                pk.expiry = pk.created + expiry

    def fast_publish_eligible(self, cl: Client) -> bool:
        """Session-level gate for the QoS0 passthrough, checked by the
        read loop BEFORE it materializes the frame bytes: v4 network
        client, no staging loop, quota headroom, and no hook that takes
        the packet (the provides() scan is cached per hooks
        generation)."""
        if cl.net.inline or cl.properties.protocol_version != 4:
            return False
        if self._stage is not None or cl.state.inflight.receive_quota == 0:
            return False
        if cl.tenant is not None:
            # tenant publishes need namespace scoping (and possibly the
            # re-encryption leg) — the decode path owns both
            return False
        gen = self.hooks.generation
        if gen != self._fastpub_gate_gen:
            ok = not self.hooks.provides(
                ON_PACKET_READ,
                ON_PUBLISH,
                ON_PACKET_ENCODE,
                ON_PACKET_SENT,
                ON_PUBLISHED,
                ON_PACKET_PROCESSED,
            )
            # only cache when no add_hook raced the scan: Hooks.add bumps
            # the generation on BOTH sides of the list publish, so a scan
            # that saw a mid-add list can never be cached as current (it
            # still decides this one frame — the same one-frame window the
            # reference's lock-free hook swap has, hooks.go:150-170)
            if self.hooks.generation == gen:
                self._fastpub_gate_ok = ok
                self._fastpub_gate_gen = gen
            return ok
        return self._fastpub_gate_ok

    @staticmethod
    def _shared_frame_ok(props: "ClientProperties", sub: Subscription) -> bool:
        """Target eligibility for shared-frame delivery (nothing forces a
        per-subscriber rewrite of the encoded publish): no positive
        subscription identifiers, no outbound aliasing, no size cap.

        Used verbatim by publish_to_client's frame-cache branch and by
        BOTH batched fan-out paths (_fan_out_batched's variant/slow
        split and _fan_out_encrypted_batched's shareable gate).
        try_fast_publish intentionally SPLITS the same predicate: the
        subscription half (identifiers) is precomputed into the cached
        fan-out plan, the session half (alias/size, plus its extra
        version==4 requirement) re-checks at delivery because cids can
        reconnect with different properties under a live plan — that
        split is the ONE remaining site that must track rule changes by
        hand."""
        ids = sub.identifiers
        return (
            props.props.topic_alias_maximum == 0
            and props.props.maximum_packet_size == 0
            and not (ids and any(v > 0 for v in ids.values()))
        )

    def _stamp_outbound(self, tcl: Client) -> None:
        """Sampled outbound queue-wait accounting: every successful
        enqueue bumps the client's sequence; 1-in-N also records the
        enqueue time, and the write loop (clients._write_loop) matches
        the sequence on dequeue to observe the wait."""
        st = tcl.state
        st.out_seq += 1
        tele = self.telemetry
        if tele is not None and tele.sample_outbound():
            st.out_stamps.append((st.out_seq, time.perf_counter()))

    def _enqueue_frame(
        self, tcl: Client, data: bytes, pk_source, count_delivery: bool = True
    ) -> bool:
        """Queue a pre-encoded frame on a target's bounded outbound queue;
        False = dropped (queue full) with the shared drop accounting.
        ``pk_source()`` materializes the Packet for on_publish_dropped.
        ``count_delivery`` keeps $SYS housekeeping fan-out out of the
        amplification accounting (the caller knows the topic; the
        pre-encoded frame does not)."""
        try:
            tcl.state.outbound.put_nowait(data)
            tcl.state.outbound_full_since = None
            self._stamp_outbound(tcl)
            if count_delivery and self.telemetry is not None:
                # shared-frame delivery WITHOUT an encode — exactly what
                # keeps fan-out amplification near 1
                self.telemetry.fanout_deliveries.inc()
            return True
        except asyncio.QueueFull:
            if tcl.state.outbound_full_since is None:
                # slow-consumer eviction clock (overload SHED posture)
                tcl.state.outbound_full_since = time.monotonic()
            self.info.messages_dropped += 1
            self.hooks.on_publish_dropped(tcl, pk_source())
            return False

    def try_fast_publish(self, cl: Client, frame: bytes, body_offset: int) -> bool:
        """QoS0 v4 PUBLISH frame passthrough — the data-plane fast path.

        Delivers an inbound frame without materializing a ``Packet`` when
        nothing can observe the difference (the same shape Go reaches with
        cheap structs, server.go:857-1021). The caller guarantees first
        byte 0x30 (qos/dup/retain all zero) and that
        ``fast_publish_eligible`` held; this method adds the topic gates —
        plain non-``$`` topic, byte rules kept a strict superset of
        ``is_valid_filter``'s publish rejections (see the cross-reference
        there) — and requires no shared/inline subscribers. The v4 QoS0
        frame is version- and property-free, so inbound bytes equal
        outbound bytes for every shared-frame-eligible target.

        Returns True when fully handled (including an ACL-denied silent
        drop); False defers to the decode path, which owns all error and
        edge-case semantics. Stats mirror ``_decode_body`` +
        ``process_publish``.
        """
        body_len = len(frame) - body_offset
        if body_len < 2:
            return False
        # the frame is relayed VERBATIM, so its remaining-length varint
        # must be minimally encoded (a padded varint like 0x85 0x00 is
        # tolerated by the scanner, but the decode path would re-encode
        # it minimally — an observable difference for strict subscribers)
        if body_offset - 1 != (
            1 if body_len < 128 else 2 if body_len < 16384 else 3 if body_len < 2097152 else 4
        ):
            return False
        tl = (frame[body_offset] << 8) | frame[body_offset + 1]
        t0 = body_offset + 2
        end = t0 + tl
        if tl == 0 or len(frame) < end:
            return False  # empty/truncated topic: decode path raises
        raw = frame[t0:end]
        if b"+" in raw or b"#" in raw or b"\x00" in raw or raw[:1] == b"$":
            return False  # wildcard/$-topic rules live in the slow path
        try:
            topic = raw.decode("utf-8")
        except UnicodeDecodeError:
            return False

        plan = self._plan_for_topic(topic)
        if plan is None:
            return False

        # telemetry stage clock for the passthrough leg: its "decode"
        # stage is near-zero BY DESIGN (the whole point of the fast path
        # is skipping packet materialization) — sampled records make that
        # visible next to the decode path's real cost
        clock = None
        if self.telemetry is not None:
            clock = self.telemetry.publish_clock()
            if clock is not None:
                clock.stamp("decode")

        self.info.packets_received += 1
        self.info.messages_received += 1
        if self.overload is not None and not self.overload.admit(cl):
            # overload shed (mqtt_tpu.overload): the passthrough frame is
            # QoS0 by construction, so the shed is a counted silent drop
            self.info.messages_dropped += 1
            return True
        if not self.hooks.on_acl_check(cl, topic, True):
            return True  # QoS0 deny is a silent drop (server.go:879-881)
        if clock is not None:
            clock.stamp("admission")
            if self.topic_sketch is not None:
                self.topic_sketch.observe(topic)

        self._fast_fan_frame(plan, topic, frame, body_offset, cl.id)
        if self._cluster is not None:
            # cluster leg: relay the frame verbatim to peer workers with
            # matching subscribers (mqtt_tpu.cluster); write ACL was
            # enforced above, peers apply per-target read ACL. A traced
            # clock rides along so the forward carries the trace id.
            self._cluster.forward_frame(topic, frame, cl.id, clock)
        if clock is not None:
            clock.stamp("fanout")
            self.telemetry.observe_publish(clock, topic, 0)
            if self.telemetry.delivery_sli:
                # the passthrough leg's delivery SLI: tenants never ride
                # this path (fast_publish_eligible), so the label is the
                # global namespace
                self.telemetry.observe_delivery(
                    clock.total(),
                    "",
                    0,
                    "local",
                    trace_id=getattr(clock, "trace_id", None),
                )
        return True

    def _plan_for_topic(self, topic: str):
        """The fast path's fan-out plan, cached per (topic, trie version):
        the walk and the per-subscription identifier scan re-run only
        after a mutation. None means the topic needs the decode path
        (shared/inline subscribers — negative-cached too). Shared by
        try_fast_publish and the cluster's forwarded-frame delivery: any
        change to the shareability predicate applies to both legs."""
        version = self.topics.version
        cached = self._fastpub_plans.get(topic)
        if cached is not None and cached[0] == version:
            return cached[1]
        subscribers = self.topics.subscribers(topic)
        if (
            subscribers.shared
            or subscribers.inline_subscriptions
            or any(
                sub.predicates
                for sub in subscribers.subscriptions.values()
            )
        ):
            # negative-cache: shared/inline topics — and topics with any
            # PREDICATED subscriber, whose delivery depends on each
            # payload — always take the decode path; don't re-walk here
            # on every publish. Version-keyed, so a predicated subscribe
            # (which bumps the trie version) invalidates stale plans.
            if len(self._fastpub_plans) >= 4096:
                self._fastpub_plans.clear()
            self._fastpub_plans[topic] = (version, None)
            return None
        plan = [
            # frame-shareable iff nothing in the SUBSCRIPTION forces a
            # rewrite; the per-SESSION half (version/alias/size) is
            # re-verified at delivery, since cids can reconnect with
            # different properties under the same plan
            (cid, sub, not (sub.identifiers and any(v > 0 for v in sub.identifiers.values())), sub.no_local)
            for cid, sub in subscribers.subscriptions.items()
        ]
        if len(self._fastpub_plans) >= 4096:
            self._fastpub_plans.clear()
        self._fastpub_plans[topic] = (version, plan)
        return plan

    def _fast_fan_frame(
        self, plan, topic: str, frame: bytes, body_offset: int, origin: str
    ) -> None:
        """The fast path's delivery loop over a cached fan-out plan:
        shareable v4 targets get the frame verbatim, everything else takes
        the full per-subscription path. Shared by try_fast_publish and the
        cluster's forwarded-frame delivery."""
        pk: Optional[Packet] = None  # decoded lazily, once, for slow paths

        def pk_source() -> Packet:
            nonlocal pk
            if pk is None:
                pk = self._decode_fast_frame(origin, frame[body_offset:])
            return pk

        clients_get = self.clients.get
        on_acl = self.hooks.on_acl_check
        for cid, sub, shareable, no_local in plan:
            tcl = clients_get(cid)
            if tcl is None or (no_local and cid == origin):
                continue  # [MQTT-3.8.3-3]
            props = tcl.properties
            if (
                shareable
                and props.protocol_version == 4
                and props.props.topic_alias_maximum == 0
                and props.props.maximum_packet_size == 0
            ):
                if not on_acl(tcl, topic, False):
                    continue
                if tcl.net.writer is None or tcl.closed:
                    continue
                self._enqueue_frame(tcl, frame, pk_source)
                continue
            # v5 target / identifiers / alias / size cap: full per-sub path
            try:
                self._deliver_to_client(tcl, sub, pk_source())
            except Exception as e:
                self.log.debug("failed publishing packet: error=%s client=%s", e, cid)

    def fast_deliver_frame(self, frame: bytes, origin: str) -> bool:
        """Deliver a peer-forwarded v4 QoS0 PUBLISH frame to local
        subscribers through the cached fan-out plans (mqtt_tpu.cluster).
        Returns False when this worker needs the decode path for the topic
        (shared/inline subscribers, or a plan miss class). Write ACL was
        enforced at the origin worker."""
        parsed = publish_frame_topic(frame)
        if parsed is None:
            return True  # origin validated it; nothing deliverable here
        topic, body_offset = parsed
        plan = self._plan_for_topic(topic)
        if plan is None:
            return False
        self._fast_fan_frame(plan, topic, frame, body_offset, origin)
        return True

    def _decode_fast_frame(self, origin: str, body: bytes) -> Packet:
        """Materialize the Packet for a fast-path frame that met a
        per-target slow case, stamped exactly like process_publish."""
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH), protocol_version=4
        )
        pk.publish_decode(body)
        pk.origin = origin
        self._stamp_publish_expiry(pk)
        return pk

    def _fan_out(self, pk: Packet, subscribers, feats=None, rjob=None) -> None:
        """Deliver one matched publish: shared-group selection, inline
        handlers, per-subscriber delivery (server.go:1000-1021).

        MQTT+ predicate filtering happens here — the one choke point
        every delivery path funnels through (staged fan-out, the host
        sync path, cluster-forwarded decode deliveries). ``feats`` is
        the publish's PublishFeatures carrier when the staged pipeline
        evaluated the rule table on device (mqtt_tpu.staging); without
        it the host interpreter decides. With no live rules this is one
        attribute read — the unpredicated path stays bit-identical.

        Tenant-namespace publishes (mqtt_tpu.tenancy) strip their scope
        prefix here — every subscriber of a scoped topic is in the same
        tenant BY CONSTRUCTION, so one copy serves the whole fan-out —
        and encrypted-namespace publishes take the batched
        re-encryption leg instead of the shared-frame path (``rjob`` is
        the staged decrypt carrier when the pipeline generated the
        keystream on device).

        Zero-materialization fan-out (ISSUE 13): a lazy
        ``SubscribersView`` result (the device pair stream as the
        currency — native/accelmod.c) is consumed through its
        ``targets()`` plan without ever building the dicts, as long as
        no dict-semantics consumer is ahead (shared groups, inline
        handlers, live predicate rules). Otherwise it materializes
        here, counted, and the eager path serves bit-identically."""
        emissions = ()
        eng = self._predicates
        targets = None  # the lazy (client_id, Subscription) plan
        vcls = _view_class()
        if vcls is not None and type(subscribers) is vcls:
            if (
                (eng is None or not eng.active)
                and not subscribers.has_shared
                and not subscribers.has_inline
            ):
                targets = subscribers.targets()
            else:
                subscribers = subscribers.materialize()
        if targets is None:
            if eng is not None and eng.active:
                subscribers, emissions = eng.apply(
                    subscribers, bytes(pk.payload), feats
                )
            if subscribers.shared:
                subscribers = self.hooks.on_select_subscribers(
                    subscribers, pk
                )
                if not subscribers.shared_selected:
                    subscribers.select_shared()
                subscribers.merge_shared_selected()

        # tenant namespace: deliveries carry the tenant-LOCAL topic
        # (clients never see the scope prefix); the scoped pk itself
        # stays untouched — the caller still forwards it to the cluster
        dpk = pk
        enc_tenant = None
        if pk.topic_name[:1] == NS_CHAR and self._tenancy is not None:
            dpk = pk.copy(False)
            dpk.topic_name = ns_local(pk.topic_name)
            tenant = self._tenancy.tenant_of_topic(pk.topic_name)
            if (
                self._recrypt is not None
                and tenant is not None
                and tenant.is_encrypted(dpk.topic_name)
            ):
                enc_tenant = tenant

        if enc_tenant is None and targets is None:
            for inline_sub in subscribers.inline_subscriptions.values():
                inline_sub.handler(self.inline_client, inline_sub, dpk)

        if enc_tenant is not None:
            self._fan_out_encrypted(
                enc_tenant, pk, dpk, subscribers, rjob, targets
            )
        else:
            items = (
                targets
                if targets is not None
                else subscribers.subscriptions.items()
            )
            if self._fanout_batch and not self.hooks.provides(
                ON_PACKET_ENCODE, ON_PACKET_SENT
            ):
                # encode-once variant-grouped delivery with the batched
                # GIL-released flush (ISSUE 13 / ROADMAP item 3)
                self._fan_out_batched(pk, dpk, items)
            else:
                # legacy path (hooks that observe encodes/sends, or the
                # batching knob off): QoS0 still shares frames through
                # the per-publish cache; QoS>0 re-encodes per subscriber
                fast = None
                if dpk.fixed_header.qos == 0 and not self.hooks.provides(
                    ON_PACKET_ENCODE, ON_PACKET_SENT
                ):
                    # $SYS housekeeping republishes every interval with no
                    # inbound publish behind it: keep it out of the encode/
                    # delivery amplification accounting (ROADMAP item 3's
                    # metric must measure client fan-out, not the $SYS tick)
                    fast = _FrameCache(
                        dpk,
                        None
                        if dpk.topic_name.startswith("$SYS")
                        else self.telemetry,
                    )

                for id_, subs in items:
                    cl = self.clients.get(id_)
                    if cl is not None:
                        try:
                            delivered = self._deliver_to_client(
                                cl, subs, dpk, fast, account=True
                            )
                        except Exception as e:
                            self.log.debug(
                                "failed publishing packet: error=%s client=%s",
                                e,
                                id_,
                            )
                        else:
                            if delivered and cl.tenant is not None:
                                cl.tenant.messages_out += 1
                                cl.tenant.bytes_out += len(dpk.payload)

        # MQTT+ aggregation windows that completed on this publish emit
        # ONE synthesized publish each (payload = the aggregate), riding
        # the same fan-out tick — no extra timers (mqtt_tpu.predicates)
        for kind, target, sub, agg_payload in emissions:
            out = dpk.copy(False)
            out.payload = agg_payload
            if kind == "inline":
                try:
                    target.handler(self.inline_client, target, out)
                except Exception as e:
                    self.log.debug("inline aggregate handler failed: %s", e)
                continue
            cl = self.clients.get(target)
            if cl is not None:
                try:
                    self._deliver_to_client(cl, sub, out)
                except Exception as e:
                    self.log.debug(
                        "failed publishing aggregate: error=%s client=%s",
                        e,
                        target,
                    )

    def _fan_out_batched(self, pk: Packet, dpk: Packet, items) -> None:
        """Encode-once variant-grouped fan-out (ISSUE 13 / ROADMAP item
        3). Targets are grouped by (protocol version, effective QoS,
        retain) — the complete set of per-target wire differences once
        aliasing/size-caps/positive-identifier sessions are excluded —
        and each variant's frame is encoded ONCE. QoS>0 targets get
        their packet id patched inside the batched native flush (writev
        iovecs, GIL released across the whole delivery batch); targets
        whose session forces a per-subscriber rewrite take the legacy
        path. Per-socket backpressure (bounded outbound queues), the
        slow-consumer eviction clock and every drop/overload counter
        behave exactly as the legacy path — only the encode count and
        the GIL profile change."""
        clock = getattr(pk, "_tclock", None)
        topic = dpk.topic_name
        sys_topic = topic.startswith("$SYS")
        tele = self.telemetry
        amp_tele = None if sys_topic else tele
        caps = self.options.capabilities
        origin = dpk.origin
        clients_get = self.clients.get
        groups: dict[tuple, list] = {}
        slow: list = []
        for cid, sub in items:
            cl = clients_get(cid)
            if cl is None or (sub.no_local and cid == origin):
                continue  # [MQTT-3.8.3-3]
            props = cl.properties
            if not self._shared_frame_ok(props, sub):
                slow.append((cl, sub))
                continue
            eff = dpk.fixed_header.qos
            if eff > sub.qos:
                eff = sub.qos
            if eff > caps.maximum_qos:
                eff = caps.maximum_qos  # [MQTT-3.2.2-9]
            pv = props.protocol_version
            retain = dpk.fixed_header.retain and (
                sub.fwd_retained_flag
                or (pv == 5 and sub.retain_as_published)
            )  # [MQTT-3.3.1-12] / [MQTT-3.3.1-13]
            groups.setdefault((pv, eff, bool(retain)), []).append((cl, sub))

        variants = []
        for (pv, eff, retain), group in groups.items():
            out = dpk.copy(False)
            out.fixed_header.qos = eff
            out.fixed_header.retain = retain
            out.protocol_version = pv
            if eff > 0:
                # nonzero placeholder (the encoder rejects pid 0 on
                # QoS>0); every target's real id is patched at flush
                out.packet_id = 1
            if out.expiry > 0:
                # the send-time expiry rewrite [MQTT-3.3.2-6], once per
                # variant instead of per subscriber
                out.properties.message_expiry_interval = max(
                    1, out.expiry - int(time.time())  # brokerlint: ok=R3 message expiry is an absolute wall-clock stamp
                )
            buf = get_buffer()
            try:
                pkts.ENCODERS[pkts.PUBLISH](out, buf)
                data = bytes(buf)
            finally:
                put_buffer(buf)
            if amp_tele is not None:
                amp_tele.publish_encodes.inc()
                amp_tele.fanout_variants.inc()
            id_off = -1
            if eff > 0:
                # packet id sits right after the topic in the variable
                # header (no aliasing in this path, so the topic is
                # always present)
                id_off = (
                    publish_frame_body_offset(data)
                    + 2
                    + len(topic.encode("utf-8"))
                )
            variants.append((pv, eff, retain, data, id_off, group))
        if clock is not None:
            clock.stamp("encode")

        for pv, eff, retain, data, id_off, group in variants:
            self._flush_variant(dpk, eff, retain, data, id_off, group,
                                sys_topic)
        for cl, sub in slow:
            try:
                delivered = self._deliver_to_client(
                    cl, sub, dpk, account=True
                )
            except Exception as e:
                self.log.debug(
                    "failed publishing packet: error=%s client=%s", e, cl.id
                )
            else:
                if delivered and cl.tenant is not None:
                    cl.tenant.messages_out += 1
                    cl.tenant.bytes_out += len(dpk.payload)
        if clock is not None:
            clock.stamp("flush")

    def _flush_variant(
        self,
        dpk: Packet,
        eff: int,
        retain: bool,
        data: bytes,
        id_off: int,
        group: list,
        sys_topic: bool,
    ) -> None:
        """Deliver one encoded variant to its target group: ready
        sockets (idle transport + empty outbound queue, no TLS) flush
        through ONE GIL-released native call; everything else rides the
        bounded outbound queue with the existing backpressure, eviction
        and drop accounting.

        Under the shard fabric the group is split BY OWNING SHARD
        first: each remote shard receives its whole sub-group as one
        marshaled call of this same method — the encode already
        happened once on the publishing shard, and the remote shard
        runs eligibility, QoS bookkeeping and its own ONE native flush
        loop-locally (ISSUE 15: whole per-shard delivery batches into
        the encode-once write path). ``call_soon_threadsafe`` preserves
        per-publisher FIFO into each shard, so one publisher's
        deliveries to one subscriber stay in order."""
        from .native import fan_flush

        if self._fabric is not None:
            try:
                here: Optional[asyncio.AbstractEventLoop] = (
                    asyncio.get_running_loop()
                )
            except RuntimeError:
                here = None
            local: list = []
            remote: dict = {}
            for cl, sub in group:
                loop = cl.net.loop
                if loop is None or loop is here:
                    local.append((cl, sub))
                else:
                    remote.setdefault(loop, []).append((cl, sub))
            for loop, rgroup in remote.items():
                try:
                    loop.call_soon_threadsafe(
                        self._flush_variant,
                        dpk, eff, retain, data, id_off, rgroup, sys_topic,
                    )
                except RuntimeError:
                    continue  # shard gone; its clients are going away
            if not local:
                return
            group = local

        count_delivery = not sys_topic
        topic = dpk.topic_name
        if topic[:1] == NS_CHAR:
            topic = ns_local(topic)
        on_acl = self.hooks.on_acl_check
        flush: list = []
        for cl, sub in group:
            try:
                if not on_acl(cl, topic, False):
                    continue
                if cl.closed or cl.net.writer is None:
                    continue
                pid = 0
                if eff > 0:
                    pid = self._begin_qos_delivery(cl, dpk, eff, retain)
                    if pid < 0:
                        continue  # quota-refused or parked for resend
                writer = cl.net.writer
                fd = -1
                if (
                    cl.state.outbound_qty == 0
                    and writer.get_extra_info("sslcontext") is None
                    and writer.transport.get_write_buffer_size() == 0
                ):
                    sock = writer.get_extra_info("socket")
                    if sock is not None:
                        try:
                            fd = sock.fileno()
                        except OSError:
                            fd = -1
                if fd >= 0:
                    # tenant accounting deferred to the flush outcome
                    flush.append((cl, fd, pid))
                    continue
                frame = (
                    data if id_off < 0
                    else self._patch_id(data, id_off, pid)
                )
                if not self._enqueue_frame(
                    cl, frame, lambda: dpk,
                    count_delivery=count_delivery,
                ):
                    if eff > 0:
                        self._rollback_qos_delivery(cl, pid)
                    continue
            except Exception as e:
                self.log.debug(
                    "failed publishing packet: error=%s client=%s", e, cl.id
                )
                continue
            self._note_tenant_out(cl, dpk)
        if not flush:
            return
        sent = fan_flush(
            [fd for _, fd, _ in flush],
            data,
            id_off,
            [pid for _, _, pid in flush] if id_off >= 0 else None,
        )
        if self.telemetry is not None:
            self.telemetry.fanout_writev_batches.inc()
        if sent is None:
            # no native library: encode-once still holds, delivery goes
            # through the per-target transport write
            for cl, _fd, pid in flush:
                frame = (
                    data if id_off < 0 else self._patch_id(data, id_off, pid)
                )
                if self._transport_write_frame(cl, frame, count_delivery):
                    self._note_tenant_out(cl, dpk)
            return
        n = len(data)
        for (cl, _fd, pid), wrote in zip(flush, sent.tolist()):
            if wrote == n:
                self._note_direct_write(cl, n, count_delivery)
            elif wrote >= 0:
                # short write (kernel buffer filled mid-frame): finish
                # through the transport — ordering-safe, the transport
                # buffer was empty and we never left the loop thread
                frame = (
                    data if id_off < 0 else self._patch_id(data, id_off, pid)
                )
                try:
                    cl.net.writer.write(frame[wrote:])
                except Exception as e:
                    self.log.debug(
                        "fan-out flush tail failed: error=%s client=%s",
                        e, cl.id,
                    )
                    continue
                self._note_direct_write(cl, n, count_delivery)
            else:
                # -errno (EAGAIN-before-anything, or the connection is
                # going away): the transport path owns delivery + errors
                frame = (
                    data if id_off < 0 else self._patch_id(data, id_off, pid)
                )
                if not self._transport_write_frame(
                    cl, frame, count_delivery
                ):
                    continue
            # accounting only on a delivery that actually went out (the
            # legacy path counts after publish_to_client succeeds)
            self._note_tenant_out(cl, dpk)

    @staticmethod
    def _patch_id(data: bytes, id_off: int, pid: int) -> bytes:
        """A copy of the variant frame with this target's packet id."""
        b = bytearray(data)
        b[id_off] = (pid >> 8) & 0xFF
        b[id_off + 1] = pid & 0xFF
        return bytes(b)

    def _begin_qos_delivery(
        self, cl: Client, dpk: Packet, eff: int, retain: bool
    ) -> int:
        """The QoS>0 per-target bookkeeping of publish_to_client —
        inflight cap, packet-id allocation, inflight store, send quota —
        WITHOUT the per-target encode. Returns the allocated packet id,
        or -1 when nothing must be written now (quota refusal, or the
        send-quota park that resends once quota frees)."""
        caps = self.options.capabilities
        if len(cl.state.inflight) >= caps.maximum_inflight:
            self.info.inflight_dropped += 1
            self.log.warning(
                "client store quota reached: client=%s listener=%s",
                cl.id, cl.net.listener,
            )
            return -1
        try:
            i = cl.next_packet_id()  # [MQTT-4.3.2-1] [MQTT-4.3.3-1]
        except Code:
            self.hooks.on_packet_id_exhausted(cl, dpk)
            self.info.inflight_dropped += 1
            self.log.warning(
                "packet ids exhausted: client=%s listener=%s",
                cl.id, cl.net.listener,
            )
            return -1
        out = dpk.copy(False)
        out.topic_name = (
            ns_local(dpk.topic_name)
            if dpk.topic_name[:1] == NS_CHAR
            else dpk.topic_name
        )
        out.fixed_header.qos = eff
        out.fixed_header.retain = retain
        out.packet_id = i & 0xFFFF  # [MQTT-2.2.1-4]
        sent_quota = cl.state.inflight.send_quota
        if cl.state.inflight.set(out):  # [MQTT-4.3.2-3] [MQTT-4.3.3-3]
            self.info.inflight += 1
            self.hooks.on_qos_publish(cl, out, out.created, 0)
            cl.state.inflight.decrease_send_quota()
        if sent_quota == 0 and cl.state.inflight.maximum_send_quota > 0:
            out.expiry = -1  # mark for immediate resend once quota frees
            cl.state.inflight.set(out)
            return -1
        return out.packet_id

    def _rollback_qos_delivery(self, cl: Client, pid: int) -> None:
        """Undo _begin_qos_delivery after a failed enqueue — the exact
        rollback publish_to_client performs on a full outbound queue."""
        cl.state.inflight.delete(pid)
        cl.state.inflight.increase_send_quota()

    def _note_direct_write(
        self, cl: Client, nbytes: int, count_delivery: bool
    ) -> None:
        """Accounting for one completed direct-socket delivery — the
        union of clients.write_frame's io counters and _enqueue_frame's
        delivery count."""
        self.info.bytes_sent += nbytes
        self.info.packets_sent += 1
        self.info.messages_sent += 1
        st = cl.state
        st.out_bytes += nbytes
        st.out_writes += 1
        tele = self.telemetry
        if tele is not None:
            tele.outbound_bytes.inc(nbytes)
            tele.outbound_writes.inc()
            if count_delivery:
                tele.fanout_deliveries.inc()

    @staticmethod
    def _note_tenant_out(cl: Client, dpk: Packet) -> None:
        """Per-tenant outbound accounting for one completed delivery."""
        if cl.tenant is not None:
            cl.tenant.messages_out += 1
            cl.tenant.bytes_out += len(dpk.payload)

    def _transport_write_frame(
        self, cl: Client, frame: bytes, count_delivery: bool
    ) -> bool:
        """Fallback delivery of a pre-encoded frame through the asyncio
        transport (native flush unavailable or refused the socket);
        False = the write was not accepted."""
        try:
            cl.write_frame(frame)
        except Exception as e:
            self.log.debug(
                "failed publishing packet: error=%s client=%s", e, cl.id
            )
            return False
        if count_delivery and self.telemetry is not None:
            self.telemetry.fanout_deliveries.inc()
        return True

    def _key_idents(self, cid: str, cl: Optional[Client] = None) -> tuple:
        """The key-identity candidates for a client id: the tenant-LOCAL
        client id first, then the connected client's username — whatever
        the operator keyed the tenant's key map on (mqtt_tpu.tenancy)."""
        from .tenancy import local_client_id

        if cl is None:
            cl = self.clients.get(cid)
        uname = ""
        if cl is not None:
            u = cl.properties.username
            uname = (
                u.decode("utf-8", "replace")
                if isinstance(u, (bytes, bytearray))
                else (u or "")
            )
        return (local_client_id(cid), uname)

    def _origin_idents(self, pk: Packet) -> tuple:
        """Key-identity candidates for a publish's ORIGIN: the live
        session's identities plus the username rider cluster forwards
        carry (mqtt_tpu.cluster head["u"]) — a username-keyed publisher
        must resolve on workers where its session does not exist."""
        idents = self._key_idents(pk.origin)
        rider = getattr(pk, "_origin_user", "")
        if rider and rider not in idents:
            idents = idents + (rider,)
        return idents

    def _recrypt_job_for(self, cl: Client, pk: Packet):
        """The staged decrypt carrier for an encrypted-namespace publish
        (None for everything else). Built at submit time so the
        keystream dispatch rides the match batch (mqtt_tpu.staging)."""
        renc = self._recrypt
        tenant = cl.tenant
        if renc is None or tenant is None:
            return None
        local = ns_local(pk.topic_name)
        if not tenant.is_encrypted(local):
            return None
        return renc.decrypt_job(
            tenant, self._key_idents(pk.origin, cl), bytes(pk.payload)
        )

    def _fan_out_encrypted(
        self, tenant, pk: Packet, dpk: Packet, subscribers, rjob,
        targets=None,
    ) -> None:
        """The MQT-TZ re-encryption fan-out (mqtt_tpu.tenancy): decrypt
        the publish once with the publisher's key (the staged keystream
        when the batch rode the device, the host path otherwise),
        re-encrypt per subscriber in ONE batched keystream dispatch, and
        deliver each subscriber its own ``nonce || ciphertext``. Keyless
        subscribers receive nothing (counted) — an encrypted namespace
        never leaks plaintext or someone else's ciphertext.

        ``targets`` is the lazy view's (client_id, Subscription) plan
        when the zero-materialization path resolved this publish — the
        encrypted leg consumes sid pairs directly too (ISSUE 13).
        Shareable-QoS0 targets additionally skip the per-subscriber
        Packet+encode entirely: one shared frame HEAD is encoded per
        (version, retain) variant and the native layer assembles
        ``head || nonce_i || ciphertext_i`` frames from the batched
        keystream XOR in a single pass (PR 12 residual closed for the
        host path)."""
        renc = self._recrypt
        plaintext = renc.open_publish(
            tenant, self._origin_idents(pk), bytes(pk.payload), rjob
        )
        if plaintext is None:
            # keyless publisher / malformed framing: the publish is
            # undeliverable (engine counters carry the reason)
            self.info.messages_dropped += 1
            tenant.messages_dropped += 1
            return
        items = (
            list(targets)
            if targets is not None
            else list(subscribers.subscriptions.items())
        )
        if self._fanout_batch and not self.hooks.provides(
            ON_PACKET_ENCODE, ON_PACKET_SENT
        ):
            if self._fan_out_encrypted_batched(
                tenant, dpk, plaintext, items
            ):
                return
        key_targets = [(cid, self._key_idents(cid)) for cid, _sub in items]
        sealed = renc.seal_fanout(tenant, plaintext, key_targets)
        for id_, subs in items:
            data = sealed.get(id_)
            if data is None:
                continue  # keyless subscriber: withheld, counted
            cl = self.clients.get(id_)
            if cl is None:
                continue
            out = dpk.copy(False)
            out.payload = data
            try:
                delivered = self._deliver_to_client(
                    cl, subs, out, account=True
                )
            except Exception as e:
                self.log.debug(
                    "failed publishing recrypted packet: error=%s "
                    "client=%s",
                    e,
                    id_,
                )
            else:
                if delivered:
                    tenant.messages_out += 1
                    tenant.bytes_out += len(data)

    def _fan_out_encrypted_batched(
        self, tenant, dpk: Packet, plaintext: bytes, items: list
    ) -> bool:
        """The re-encrypt fan-out's encode-once leg (ISSUE 13 satellite,
        PR 12 residual): ONE keystream dispatch for every keyed target,
        then per-subscriber frames assembled in C as ``head || nonce_i
        || (plaintext XOR keystream_i)`` — the frame HEAD is encoded
        once per (version, retain) variant, so encrypted namespaces no
        longer pay a per-subscriber Packet copy + encode. Targets whose
        session forces a per-subscriber rewrite (QoS>0, aliasing, size
        caps, positive identifiers) still ride publish_to_client with
        their sealed payloads — same keystream dispatch, no second one.
        Returns True when delivery was fully handled here."""
        from .native import assemble_frames

        renc = self._recrypt
        caps = self.options.capabilities
        clients_get = self.clients.get
        origin = dpk.origin
        live: list = []  # (cid, cl, sub, eff, pv, retain, shareable)
        for cid, sub in items:
            cl = clients_get(cid)
            if cl is None or (sub.no_local and cid == origin):
                continue
            props = cl.properties
            eff = dpk.fixed_header.qos
            if eff > sub.qos:
                eff = sub.qos
            if eff > caps.maximum_qos:
                eff = caps.maximum_qos
            pv = props.protocol_version
            retain = dpk.fixed_header.retain and (
                sub.fwd_retained_flag
                or (pv == 5 and sub.retain_as_published)
            )
            shareable = eff == 0 and self._shared_frame_ok(props, sub)
            live.append((cid, cl, sub, eff, pv, bool(retain), shareable))
        if not any(s for *_x, s in live):
            return False  # nothing shareable: the legacy path is simpler
        raw = renc.seal_fanout_raw(
            tenant, plaintext,
            [(cid, self._key_idents(cid, cl)) for cid, cl, *_r in live],
        )
        if raw is None:
            # keyless everything: withheld (counted by the engine)
            return True
        keyed, nonces, rows = raw
        kmap = {tkey: i for i, (tkey, _kid) in enumerate(keyed)}
        n_blocks = (len(plaintext) + 15) // 16
        ks2d = (
            rows.reshape(len(keyed), n_blocks * 16)
            if rows is not None
            else None
        )
        payload_len = renc.nonce_bytes + len(plaintext)

        # group shareable targets by head variant; deliver the rest
        # per-subscriber with their sealed payload slices
        groups: dict[tuple, list] = {}
        import numpy as _np

        pt_arr = _np.frombuffer(plaintext, dtype=_np.uint8)
        for cid, cl, sub, eff, pv, retain, shareable in live:
            ki = kmap.get(cid)
            if ki is None:
                continue  # keyless subscriber: withheld, counted
            if shareable:
                groups.setdefault((pv, retain), []).append((cl, ki))
                continue
            data = nonces[ki].tobytes() + (
                (ks2d[ki][: len(plaintext)] ^ pt_arr).tobytes()
                if ks2d is not None
                else b""
            )
            out = dpk.copy(False)
            out.payload = data
            try:
                delivered = self._deliver_to_client(
                    cl, sub, out, account=True
                )
            except Exception as e:
                self.log.debug(
                    "failed publishing recrypted packet: error=%s "
                    "client=%s", e, cid,
                )
            else:
                if delivered:
                    tenant.messages_out += 1
                    tenant.bytes_out += len(data)

        amp_tele = self.telemetry
        # the tenant-LOCAL topic (what the subscriber subscribed to):
        # the ACL below must judge what the client sees on the wire
        topic = dpk.topic_name
        if topic[:1] == NS_CHAR:
            topic = ns_local(topic)
        for (pv, retain), group in groups.items():
            out = dpk.copy(False)
            out.fixed_header.qos = 0
            out.fixed_header.retain = retain
            out.protocol_version = pv
            out.payload = b"\x00" * payload_len  # placeholder bytes only
            if out.expiry > 0:
                out.properties.message_expiry_interval = max(
                    1, out.expiry - int(time.time())  # brokerlint: ok=R3 message expiry is an absolute wall-clock stamp
                )
            buf = get_buffer()
            try:
                pkts.ENCODERS[pkts.PUBLISH](out, buf)
                frame = bytes(buf)
            finally:
                put_buffer(buf)
            head = frame[: len(frame) - payload_len]
            if amp_tele is not None:
                amp_tele.publish_encodes.inc()
                amp_tele.fanout_variants.inc()
            idxs = [ki for _cl, ki in group]
            frames = None
            if ks2d is not None:
                frames = assemble_frames(
                    head, nonces[idxs], ks2d[idxs], plaintext
                )
            if frames is None:
                # no native library (or empty plaintext): numpy assembly,
                # still encode-once
                ct = (
                    (ks2d[idxs][:, : len(plaintext)] ^ pt_arr[None, :])
                    if ks2d is not None
                    else _np.zeros((len(idxs), 0), dtype=_np.uint8)
                )
                rows_bytes = [
                    head + nonces[ki].tobytes() + ct[i].tobytes()
                    for i, ki in enumerate(idxs)
                ]
            else:
                rows_bytes = [f.tobytes() for f in frames]
            for (cl, _ki), fbytes in zip(group, rows_bytes):
                try:
                    # the per-target read ACL every delivery path
                    # enforces (publish_to_client raises on the slow
                    # legs; here denial withholds the frame)
                    if not self.hooks.on_acl_check(cl, topic, False):
                        continue
                    if cl.closed or cl.net.writer is None:
                        continue
                    if self._enqueue_frame(cl, fbytes, lambda: dpk):
                        tenant.messages_out += 1
                        tenant.bytes_out += payload_len
                except Exception as e:
                    self.log.debug(
                        "failed publishing recrypted packet: error=%s "
                        "client=%s", e, cl.id,
                    )
        return True

    def _client_loop_local(self, cl: Client) -> bool:
        """True when the calling thread may touch this client's
        loop-affine state directly (its owning loop, or no loop)."""
        loop = cl.net.loop
        if loop is None:
            return True
        try:
            return loop is asyncio.get_running_loop()
        except RuntimeError:
            return False

    def _deliver_to_client(
        self,
        cl: Client,
        sub: Subscription,
        pk: Packet,
        fast: Optional["_FrameCache"] = None,
        account: bool = False,
    ) -> bool:
        """``publish_to_client`` with shard-loop affinity (mqtt_tpu.shards):
        a delivery that mutates per-client loop-affine state (QoS>0
        packet-id/inflight bookkeeping, outbound topic aliasing) for a
        client ANOTHER shard owns is marshaled onto that shard's loop;
        everything else — the shared-frame and plain QoS0 paths, whose
        only cross-thread touch is the thread-safe outbound queue —
        runs inline. No fabric = always inline = today's path.

        Returns True when the delivery ran inline (exceptions propagate
        and the caller does its own accounting); False when marshaled
        (the owner-loop callback logs failures and, with ``account``,
        performs the tenant accounting itself)."""
        if self._fabric is None or self._client_loop_local(cl):
            self.publish_to_client(cl, sub, pk, fast)
            return True
        eff = pk.fixed_header.qos
        if eff > sub.qos:
            eff = sub.qos
        if eff == 0 and cl.properties.props.topic_alias_maximum == 0:
            self.publish_to_client(cl, sub, pk, fast)
            return True
        loop = cl.net.loop
        try:
            loop.call_soon_threadsafe(  # type: ignore[union-attr]
                self._deliver_remote, cl, sub, pk, fast, account
            )
        except RuntimeError:
            pass  # owner shard gone; the client is going away with it
        return False

    def _deliver_remote(
        self,
        cl: Client,
        sub: Subscription,
        pk: Packet,
        fast: Optional["_FrameCache"],
        account: bool,
    ) -> None:
        """The owner-shard half of a marshaled delivery."""
        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                # call_soon_threadsafe landed us on the owner's loop;
                # anything else is a marshal-routing bug
                w.check_owner(
                    "client_state", "deliver_marshal", cl.net.loop,
                    detail=cl.id,
                )
        try:
            self.publish_to_client(cl, sub, pk, fast)
        except Exception as e:
            self.log.debug(
                "failed publishing packet: error=%s client=%s", e, cl.id
            )
        else:
            if account:
                self._note_tenant_out(cl, pk)

    def publish_to_client(
        self,
        cl: Client,
        sub: Subscription,
        pk: Packet,
        fast: Optional["_FrameCache"] = None,
    ) -> Packet:
        """Deliver one publish to one subscriber (server.go:1023-1113).

        A namespace-scoped ``pk`` (retained deliveries walk the trie
        directly, so their packets still carry the tenant prefix —
        mqtt_tpu.tenancy) is delivered under its tenant-LOCAL topic:
        the ACL, aliasing, and the wire all see what the client
        subscribed to."""
        if sub.no_local and pk.origin == cl.id:
            return pk  # [MQTT-3.8.3-3]

        if _LOOP_PLANE.active:
            w = _LOOP_PLANE.witness
            if w is not None:
                eff = pk.fixed_header.qos
                if eff > sub.qos:
                    eff = sub.qos
                if eff > 0 or cl.properties.props.topic_alias_maximum > 0:
                    # this delivery mutates loop-affine per-client state
                    # (packet ids / inflight / outbound aliases): the
                    # _deliver_to_client contract marshals it here
                    w.check_owner(
                        "client_state", "owner_touch", cl.net.loop,
                        detail=cl.id,
                    )
        topic = pk.topic_name
        if topic[:1] == NS_CHAR:
            topic = ns_local(topic)

        # zero-valued identifiers never reach the wire (properties.py
        # encodes only v > 0), so they don't disqualify the shared frame
        if fast is not None and self._shared_frame_ok(cl.properties, sub):
            if not self.hooks.on_acl_check(cl, topic, False):
                raise ERR_NOT_AUTHORIZED()
            retain = pk.fixed_header.retain and (
                sub.fwd_retained_flag
                or (cl.properties.protocol_version == 5 and sub.retain_as_published)
            )
            data = fast.get(cl.properties.protocol_version, retain)
            if cl.net.writer is None or cl.closed:
                raise CODE_DISCONNECT()
            if not self._enqueue_frame(
                cl,
                data,
                lambda: pk,
                count_delivery=not topic.startswith("$SYS"),
            ):
                raise ERR_PENDING_CLIENT_WRITES_EXCEEDED()
            return pk

        out = pk.copy(False)
        out.topic_name = topic
        if not self.hooks.on_acl_check(cl, topic, False):
            raise ERR_NOT_AUTHORIZED()
        if not sub.fwd_retained_flag and (
            (cl.properties.protocol_version == 5 and not sub.retain_as_published)
            or cl.properties.protocol_version < 5
        ):  # ![MQTT-3.3.1-13] [v3 MQTT-3.3.1-9]
            out.fixed_header.retain = False  # [MQTT-3.3.1-12]

        if sub.identifiers:  # [MQTT-3.3.4-3]
            out.properties.subscription_identifier = sorted(
                sub.identifiers.values()
            )  # [MQTT-3.3.4-4] ![MQTT-3.3.4-5]

        if out.fixed_header.qos > sub.qos:
            out.fixed_header.qos = sub.qos
        if out.fixed_header.qos > self.options.capabilities.maximum_qos:
            out.fixed_header.qos = self.options.capabilities.maximum_qos  # [MQTT-3.2.2-9]

        if cl.properties.props.topic_alias_maximum > 0:
            alias, alias_exists = cl.state.topic_aliases.outbound.set(topic)
            out.properties.topic_alias = alias
            if alias > 0:
                out.properties.topic_alias_flag = True
                if alias_exists:
                    out.topic_name = ""

        if out.fixed_header.qos > 0:
            caps = self.options.capabilities
            if len(cl.state.inflight) >= caps.maximum_inflight:
                self.info.inflight_dropped += 1
                self.log.warning(
                    "client store quota reached: client=%s listener=%s", cl.id, cl.net.listener
                )
                raise ERR_QUOTA_EXCEEDED()
            try:
                i = cl.next_packet_id()  # [MQTT-4.3.2-1] [MQTT-4.3.3-1]
            except Code:
                self.hooks.on_packet_id_exhausted(cl, pk)
                self.info.inflight_dropped += 1
                self.log.warning(
                    "packet ids exhausted: client=%s listener=%s", cl.id, cl.net.listener
                )
                raise ERR_QUOTA_EXCEEDED() from None

            out.packet_id = i & 0xFFFF  # [MQTT-2.2.1-4]
            sent_quota = cl.state.inflight.send_quota

            if cl.state.inflight.set(out):  # [MQTT-4.3.2-3] [MQTT-4.3.3-3]
                self.info.inflight += 1
                self.hooks.on_qos_publish(cl, out, out.created, 0)
                cl.state.inflight.decrease_send_quota()

            if sent_quota == 0 and cl.state.inflight.maximum_send_quota > 0:
                out.expiry = -1  # mark for immediate resend once quota frees
                cl.state.inflight.set(out)
                return out

        if cl.net.writer is None or cl.closed:
            raise CODE_DISCONNECT()

        try:
            cl.state.outbound.put_nowait(out)
            cl.state.outbound_full_since = None
            self._stamp_outbound(cl)
        except asyncio.QueueFull:
            if cl.state.outbound_full_since is None:
                # slow-consumer eviction clock (overload SHED posture)
                cl.state.outbound_full_since = time.monotonic()
            self.info.messages_dropped += 1
            self.hooks.on_publish_dropped(cl, pk)
            if out.fixed_header.qos > 0:
                cl.state.inflight.delete(out.packet_id)  # rollback inflight
                cl.state.inflight.increase_send_quota()
            raise ERR_PENDING_CLIENT_WRITES_EXCEEDED() from None

        return out

    def publish_retained_to_client(self, cl: Client, sub: Subscription, existed: bool) -> None:
        """Send matching retained messages after a subscribe
        (server.go:1115-1133)."""
        if is_shared_filter(sub.filter):
            return  # 4.8.2 Non-normative: no retained on shared subscribe
        if (sub.retain_handling == 1 and existed) or sub.retain_handling == 2:
            return  # [MQTT-3.3.1-10] [MQTT-3.3.1-11]
        # value-copy: the reference ranges over Subscription values, so the
        # trie-stored subscription never carries fwd_retained_flag
        sub = replace(sub, fwd_retained_flag=True)
        # device-resident retained matching (ISSUE 16): the flat publish
        # kernel run in reverse answers wildcard filters against the
        # retained corpus; None (non-wildcard, $SHARE, fallback class,
        # open breaker) = host trie walk, the differential oracle
        retained_msgs: list = []
        if self._retained_engine is not None:
            names = self._retained_engine.match(sub.filter)
            if names is not None:
                retained_msgs = [
                    m
                    for m in (self.topics.retained.get(n) for n in names)
                    if m is not None
                ]
            else:
                retained_msgs = self.topics.messages(sub.filter)
        else:
            retained_msgs = self.topics.messages(sub.filter)
        for pkv in retained_msgs:  # [MQTT-3.8.4-4]
            # MQTT+ predicates apply to retained payloads too: the
            # sub.filter here is already the BASE filter, so the walk is
            # unchanged and only the delivery gate consults the rules
            if self._predicates is not None and not self._predicates.passes_retained(
                sub, bytes(pkv.payload)
            ):
                continue
            if (
                self._recrypt is not None
                and pkv.topic_name[:1] == NS_CHAR
            ):
                # an encrypted-namespace retained message is stored as
                # the PUBLISHER's ciphertext; deliver it re-keyed to
                # this subscriber (or not at all — mqtt_tpu.tenancy)
                pkv2 = self._recrypt_retained(cl, pkv)
                if pkv2 is None:
                    continue
                pkv = pkv2
            try:
                self.publish_to_client(cl, sub, pkv)
            except Exception as e:
                self.log.debug(
                    "failed to publish retained message: error=%s client=%s", e, cl.id
                )
                continue
            self.hooks.on_retain_published(cl, pkv)

    def _recrypt_retained(self, cl: Client, pkv: Packet) -> Optional[Packet]:
        """Re-key one retained encrypted-namespace message for a fresh
        subscriber (mqtt_tpu.tenancy): the store holds the publisher's
        ciphertext, the wire carries this subscriber's. None = withhold
        (keyless publisher or subscriber, malformed framing — counted by
        the engine). Scoped-but-unencrypted topics pass through."""
        tenant = (
            self._tenancy.tenant_of_topic(pkv.topic_name)
            if self._tenancy is not None
            else None
        )
        if tenant is None or not tenant.is_encrypted(ns_local(pkv.topic_name)):
            return pkv
        renc = self._recrypt
        plaintext = renc.open_publish(
            tenant, self._origin_idents(pkv), bytes(pkv.payload)
        )
        if plaintext is None:
            return None
        sealed = renc.seal_fanout(
            tenant, plaintext, [(cl.id, self._key_idents(cl.id, cl))]
        )
        data = sealed.get(cl.id)
        if data is None:
            return None
        out = pkv.copy(False)
        out.payload = data
        return out

    # -- live tenant re-key (ISSUE 20, the MQT-TZ rotation residual) -------

    def _publish_rekey_notice(
        self, tenant: str, state: str, epoch: int, extra: Optional[dict] = None
    ) -> None:
        """The $SYS half of the epoch protocol: a retained
        ``$SYS/broker/tenant/rekey`` message in the tenant's OWN
        namespace (its clients subscribe there to learn the new epoch)
        plus the global operator mirror, published on every state edge
        (distributing -> active -> retired)."""
        payload = {"tenant": tenant, "epoch": epoch, "state": state}
        if extra:
            payload.update(extra)
        data = json.dumps(payload).encode()
        now = int(time.time())  # brokerlint: ok=R3 $SYS rekey notice stamps are wall-clock (operator-correlatable)
        for topic in (
            ns_scope_topic(tenant, SYS_PREFIX + "/broker/tenant/rekey"),
            SYS_PREFIX + f"/broker/tenants/{tenant}/rekey",
        ):
            pk = Packet(
                fixed_header=FixedHeader(type=pkts.PUBLISH, retain=True),
                topic_name=topic,
                payload=data,
                created=now,
            )
            self.topics.retain_message(pk.copy(False))
            if self._retained_engine is not None:
                self._retained_engine.note_retained(topic, True)
            self.publish_to_subscribers(pk)

    def rekey_tenant(
        self, name: str, new_keys: dict, reseal_retained: bool = True
    ) -> dict:
        """Rotate a tenant's encryption keys LIVE (ISSUE 20): stage the
        next epoch's keys (``ident -> raw 16-byte key``), announce the
        distributing epoch on ``$SYS/broker/tenant/rekey``, re-seal the
        tenant's retained encrypted payloads across the rotation in
        batched device dispatches, then activate — new fan-out ticks
        seal under the new generation while in-flight ticks drain on
        their old-table snapshots. The OLD epoch stays decryptable
        (epoch-tagged nonces) until :meth:`retire_tenant_epoch`.

        Returns ``{"epoch", "old_epoch", "resealed"}``; raises
        ValueError when tenancy/recrypt is off or the tenant is
        unknown."""
        if self._tenancy is None or self._recrypt is None:
            raise ValueError("rekey requires tenancy + recrypt enabled")
        t = self._tenancy.get(name)
        if t is None:
            raise ValueError(f"unknown tenant {name!r}")
        renc = self._recrypt
        keys = self._tenancy.keys
        old_epoch = keys.current_epoch(name)
        epoch = keys.stage_epoch(name, new_keys)
        self._publish_rekey_notice(name, "distributing", epoch)
        resealed = 0
        if reseal_retained:
            resealed = self._reseal_tenant_retained(t, epoch)
        keys.activate_epoch(name)
        renc.note_rekey(name)
        self._publish_rekey_notice(
            name, "active", epoch, {"resealed": resealed}
        )
        self.log.info(
            "tenant %s re-keyed: epoch %d -> %d, %d retained re-sealed",
            name, old_epoch, epoch, resealed,
        )
        return {"epoch": epoch, "old_epoch": old_epoch, "resealed": resealed}

    def retire_tenant_epoch(self, name: str, epoch: int) -> int:
        """Retire a drained epoch: tagged publishes under it now drop
        (counted as stale), its round-key rows are scrubbed, and the
        retirement is announced on the rekey $SYS topic. Returns how
        many key rows were scrubbed."""
        if self._tenancy is None:
            raise ValueError("rekey requires tenancy enabled")
        scrubbed = self._tenancy.keys.retire_epoch(name, epoch)
        self._publish_rekey_notice(
            name, "retired", epoch, {"scrubbed": scrubbed}
        )
        return scrubbed

    def _reseal_tenant_retained(self, t, epoch: int) -> int:
        """Re-seal every retained encrypted-namespace payload of one
        tenant from its CURRENT generation to the staged ``epoch`` in
        ONE batched keystream dispatch (decrypt + seal blocks share the
        call — tenancy.RecryptEngine.reseal_batch). The rewritten
        payloads ride retain_message, so durable persistence and the
        retained-match engine see the new ciphertext."""
        renc = self._recrypt
        keys = self._tenancy.keys
        prefix = NS_CHAR + t.name + "/"
        victims: list = []
        items: list = []
        for topic, pkv in self.topics.retained.get_all().items():
            if not topic.startswith(prefix) or not pkv.payload:
                continue
            local = ns_local(topic)
            if local.startswith("$SYS") or not t.is_encrypted(local):
                continue
            idents = self._origin_idents(pkv)
            old_kid = new_kid = -1
            for ident in idents:
                if not ident:
                    continue
                old_kid = keys.key_id(t.name, ident)
                new_kid = keys.kid_for_epoch(t.name, ident, epoch)
                if old_kid >= 0 and new_kid >= 0:
                    break
            victims.append((topic, pkv))
            items.append((bytes(pkv.payload), old_kid, new_kid))
        if not items:
            return 0
        resealed = renc.reseal_batch(t, items, epoch)
        n = 0
        for (topic, pkv), data in zip(victims, resealed):
            if data is None:
                continue  # keyless origin: the old ciphertext stands
            out = pkv.copy(False)
            out.payload = data
            out.fixed_header.retain = True
            self.retain_message(self.clients.get(out.origin), out)
            n += 1
        return n

    def build_ack(
        self, packet_id: int, pkt: int, qos: int, properties: Properties, reason: Code
    ) -> Packet:
        """A standardized ack for puback/pubrec/pubrel/pubcomp
        (server.go:1136-1157)."""
        if self.options.capabilities.compatibilities.no_inherited_properties_on_ack:
            properties = Properties()
        if reason.code >= ERR_UNSPECIFIED_ERROR.code:
            properties.reason_string = reason.reason
        now = int(time.time())  # brokerlint: ok=R3 ack created/expiry stamps are wall-clock (message-expiry contract)
        return Packet(
            fixed_header=FixedHeader(type=pkt, qos=qos),
            packet_id=packet_id,  # [MQTT-2.2.1-5]
            reason_code=reason.code,  # [MQTT-3.4.2-1]
            properties=properties,
            created=now,
            expiry=now + self.options.capabilities.maximum_message_expiry_interval,
        )

    # -- qos acks ----------------------------------------------------------

    def process_puback(self, cl: Client, pk: Packet) -> None:
        """(server.go:1160-1172)"""
        if cl.state.inflight.get(pk.packet_id) is None:
            return  # omit ErrPacketIdentifierNotFound
        if cl.state.inflight.delete(pk.packet_id):  # [MQTT-4.3.2-5]
            cl.state.inflight.increase_send_quota()
            self.info.inflight -= 1
            self.hooks.on_qos_complete(cl, pk)

    def process_pubrec(self, cl: Client, pk: Packet) -> None:
        """(server.go:1175-1192)"""
        if cl.state.inflight.get(pk.packet_id) is None:  # [MQTT-4.3.3-7/-13]
            cl.write_packet(
                self.build_ack(
                    pk.packet_id, pkts.PUBREL, 1, pk.properties, ERR_PACKET_IDENTIFIER_NOT_FOUND
                )
            )
            return
        if pk.reason_code >= ERR_UNSPECIFIED_ERROR.code or not pk.reason_code_valid():
            if cl.state.inflight.delete(pk.packet_id):
                self.info.inflight -= 1
            self.hooks.on_qos_dropped(cl, pk)
            return  # MQTT5 section 4.13.2 paragraph 2
        ack = self.build_ack(pk.packet_id, pkts.PUBREL, 1, pk.properties, CODE_SUCCESS)
        cl.state.inflight.decrease_receive_quota()
        cl.state.inflight.set(ack)  # [MQTT-4.3.3-5]
        # persist the PUBLISH -> PUBREL window transition (ISSUE 20):
        # the durable record must flip with the in-memory window, or a
        # crash-restore re-inflates the window as an unacked PUBLISH and
        # re-delivers a message the receiver already PUBREC'd — the
        # exactly-once violation the qos2_fanout scenario's kill -9 leg
        # caught ([MQTT-4.3.3-6]: no PUBLISH re-send once PUBREC is in)
        self.hooks.on_qos_publish(cl, ack, ack.created, 0)
        cl.write_packet(ack)

    def process_pubrel(self, cl: Client, pk: Packet) -> None:
        """(server.go:1195-1224)"""
        if cl.state.inflight.get(pk.packet_id) is None:  # [MQTT-4.3.3-7/-13]
            cl.write_packet(
                self.build_ack(
                    pk.packet_id, pkts.PUBCOMP, 0, pk.properties, ERR_PACKET_IDENTIFIER_NOT_FOUND
                )
            )
            return
        if pk.reason_code >= ERR_UNSPECIFIED_ERROR.code or not pk.reason_code_valid():
            if cl.state.inflight.delete(pk.packet_id):
                self.info.inflight -= 1
            self.hooks.on_qos_dropped(cl, pk)
            return
        ack = self.build_ack(pk.packet_id, pkts.PUBCOMP, 0, pk.properties, CODE_SUCCESS)
        cl.state.inflight.set(ack)
        cl.write_packet(ack)
        cl.state.inflight.increase_receive_quota()
        cl.state.inflight.increase_send_quota()
        if cl.state.inflight.delete(pk.packet_id):  # [MQTT-4.3.3-12]
            self.info.inflight -= 1
            self.hooks.on_qos_complete(cl, pk)

    def process_pubcomp(self, cl: Client, pk: Packet) -> None:
        """(server.go:1227-1237)"""
        cl.state.inflight.increase_receive_quota()
        cl.state.inflight.increase_send_quota()
        if cl.state.inflight.delete(pk.packet_id):
            self.info.inflight -= 1
            self.hooks.on_qos_complete(cl, pk)

    # -- subscribe / unsubscribe -------------------------------------------

    def process_subscribe(self, cl: Client, pk: Packet) -> None:
        """(server.go:1240-1312)"""
        pk = self.hooks.on_subscribe(cl, pk)
        code = CODE_SUCCESS
        if cl.state.inflight.get(pk.packet_id) is not None:
            code = ERR_PACKET_IDENTIFIER_IN_USE

        caps = self.options.capabilities
        filter_existed = [False] * len(pk.filters)
        reason_codes = bytearray(len(pk.filters))
        for i, sub in enumerate(pk.filters):
            if code != CODE_SUCCESS:
                reason_codes[i] = code.code  # NB 3.9.3 Non-normative 0x91
                continue
            # MQTT+ predicate suffix (mqtt_tpu.predicates): split BEFORE
            # validation so the SUBACK reason, the ACL check, $SHARE
            # parsing, and the trie all see the BASE filter — the suffix
            # never leaks past this point. Registration waits for the
            # success branch so a refused filter leaks no rule.
            pred_suffix = ""
            if self._predicates is not None:
                base, pred_suffix = split_predicate_suffix(sub.filter)
                if pred_suffix:
                    sub.filter = base
            if not is_valid_filter(sub.filter, False):
                reason_codes[i] = ERR_TOPIC_FILTER_INVALID.code
            elif sub.no_local and is_shared_filter(sub.filter):
                reason_codes[i] = ERR_PROTOCOL_VIOLATION_INVALID_SHARED_NO_LOCAL.code  # [MQTT-3.8.3-4]
            elif not self.hooks.on_acl_check(cl, sub.filter, False):
                reason_codes[i] = ERR_NOT_AUTHORIZED.code
                if caps.compatibilities.obscure_not_authorized:
                    reason_codes[i] = ERR_UNSPECIFIED_ERROR.code
            elif self._subscribe_quota_refused(cl, sub):
                # tenant subscription COUNT cap (ISSUE 16): 0x97 before
                # any rule/trie registration (the v3 clamp below turns
                # it into 0x80 for pre-v5 clients)
                reason_codes[i] = ERR_QUOTA_EXCEEDED.code
            else:
                if cl.tenant is not None:
                    # tenant namespace (mqtt_tpu.tenancy): validation,
                    # $SHARE parsing, and the ACL all saw the LOCAL
                    # filter above; everything stored or matched from
                    # here — trie, client state, retained walk,
                    # persistence, cluster presence — carries the
                    # scoped key, so two tenants' identical filter
                    # strings live on disjoint subtrees
                    sub.filter = ns_scope_filter(cl.tenant.name, sub.filter)
                if pred_suffix:
                    self._predicates.register(pred_suffix)
                    sub.predicates = (pred_suffix,)
                if self._predicates is not None:
                    # [MQTT-3.8.4-3] a re-subscribe REPLACES the stored
                    # subscription: drop the replaced one's rule refs
                    # (after registering, so a same-suffix replace never
                    # drops the rule to zero in between)
                    old = cl.state.subscriptions.get(sub.filter)
                    if old is not None and old.predicates:
                        self._predicates.release(old.predicates)
                is_new = self.topics.subscribe(cl.id, sub)  # [MQTT-3.8.4-3]
                if is_new:
                    self.info.subscriptions += 1
                    if cl.tenant is not None and sub.filter[:1] == NS_CHAR:
                        cl.tenant.subscriptions_count += 1
                cl.state.subscriptions.add(sub.filter, sub)  # [MQTT-3.2.2-10]
                # granted qos caps at server max [MQTT-3.2.2-9] without
                # mutating the trie-stored subscription (the reference caps a
                # value copy, server.go:1269-1274)
                filter_existed[i] = not is_new
                reason_codes[i] = min(sub.qos, caps.maximum_qos)  # [MQTT-3.9.3-1]

            if reason_codes[i] > 2 and cl.properties.protocol_version < 5:  # MQTT3
                reason_codes[i] = ERR_UNSPECIFIED_ERROR.code

        ack = Packet(  # [MQTT-3.8.4-1] [MQTT-3.8.4-5]
            fixed_header=FixedHeader(type=pkts.SUBACK),
            packet_id=pk.packet_id,  # [MQTT-2.2.1-6] [MQTT-3.8.4-2]
            reason_codes=bytes(reason_codes),  # [MQTT-3.8.4-6]
            properties=Properties(user=pk.properties.user),
        )
        if code.code >= ERR_UNSPECIFIED_ERROR.code:
            ack.properties.reason_string = code.reason

        self.hooks.on_subscribed(cl, pk, bytes(reason_codes))
        cl.write_packet(ack)

        for i, sub in enumerate(pk.filters):  # [MQTT-3.3.1-9]
            if reason_codes[i] >= ERR_UNSPECIFIED_ERROR.code:
                continue
            self.publish_retained_to_client(cl, sub, filter_existed[i])

    def process_unsubscribe(self, cl: Client, pk: Packet) -> None:
        """(server.go:1315-1356)"""
        code = CODE_SUCCESS
        if cl.state.inflight.get(pk.packet_id) is not None:
            code = ERR_PACKET_IDENTIFIER_IN_USE
        pk = self.hooks.on_unsubscribe(cl, pk)
        reason_codes = bytearray(len(pk.filters))
        for i, sub in enumerate(pk.filters):  # [MQTT-3.10.4-6] [MQTT-3.11.3-1]
            if code != CODE_SUCCESS:
                reason_codes[i] = code.code
                continue
            if self._predicates is not None:
                # an UNSUBSCRIBE naming the original predicated filter
                # must remove the subscription stored under its base
                base, pred_suffix = split_predicate_suffix(sub.filter)
                if pred_suffix:
                    sub.filter = base
            if cl.tenant is not None:
                # the stored key is namespace-scoped (process_subscribe)
                sub.filter = ns_scope_filter(cl.tenant.name, sub.filter)
            if self._predicates is not None:
                old = cl.state.subscriptions.get(sub.filter)
                if old is not None and old.predicates:
                    self._predicates.release(old.predicates)
            if self.topics.unsubscribe(sub.filter, cl.id):
                self.info.subscriptions -= 1
                if (
                    cl.tenant is not None
                    and sub.filter[:1] == NS_CHAR
                    and cl.tenant.subscriptions_count > 0
                ):
                    cl.tenant.subscriptions_count -= 1
                reason_codes[i] = CODE_SUCCESS.code
            else:
                reason_codes[i] = pkts.CODE_NO_SUBSCRIPTION_EXISTED.code
            cl.state.subscriptions.delete(sub.filter)  # [MQTT-3.10.4-2]

        ack = Packet(  # [MQTT-3.10.4-4]
            fixed_header=FixedHeader(type=pkts.UNSUBACK),
            packet_id=pk.packet_id,  # [MQTT-2.2.1-6] [MQTT-3.10.4-5]
            reason_codes=bytes(reason_codes),  # [MQTT-3.11.3-2]
            properties=Properties(user=pk.properties.user),
        )
        if code.code >= ERR_UNSPECIFIED_ERROR.code:
            ack.properties.reason_string = code.reason

        self.hooks.on_unsubscribed(cl, pk)
        cl.write_packet(ack)

    def unsubscribe_client(self, cl: Client) -> None:
        """Remove all of a client's subscriptions (server.go:1359-1379)."""
        filter_map = cl.state.subscriptions.get_all()
        for k in filter_map:
            cl.state.subscriptions.delete(k)
        if cl.is_taken_over:
            return  # the inheriting session keeps the rules referenced
        for k, sub in filter_map.items():
            if self._predicates is not None and sub.predicates:
                self._predicates.release(sub.predicates)
            if self.topics.unsubscribe(k, cl.id):
                self.info.subscriptions -= 1
                if self._tenancy is not None and k[:1] == NS_CHAR:
                    # restored clients may not carry cl.tenant — resolve
                    # the owner off the scoped filter itself
                    t = self._tenancy.tenant_of_topic(k)
                    if t is not None and t.subscriptions_count > 0:
                        t.subscriptions_count -= 1
        self.hooks.on_unsubscribed(
            cl,
            Packet(
                fixed_header=FixedHeader(type=pkts.UNSUBSCRIBE),
                filters=list(filter_map.values()),
            ),
        )

    # -- auth / disconnect -------------------------------------------------

    def process_auth(self, cl: Client, pk: Packet) -> None:
        """(server.go:1382-1389)"""
        self.hooks.on_auth_packet(cl, pk)

    def process_disconnect(self, cl: Client, pk: Packet) -> None:
        """(server.go:1392-1410)"""
        if pk.properties.session_expiry_interval_flag:
            if (
                pk.properties.session_expiry_interval > 0
                and cl.properties.props.session_expiry_interval == 0
            ):
                raise ERR_PROTOCOL_VIOLATION_ZERO_NON_ZERO_EXPIRY()
            cl.properties.props.session_expiry_interval = pk.properties.session_expiry_interval
            cl.properties.props.session_expiry_interval_flag = True

        if pk.reason_code == CODE_DISCONNECT_WILL_MESSAGE.code:  # [MQTT-3.1.2.5]
            raise CODE_DISCONNECT_WILL_MESSAGE()

        self.will_delayed.delete(cl.id)  # [MQTT-3.1.3-9] [MQTT-3.1.2-8]
        # discard the will STRUCT too, not just a pending delayed entry
        # [MQTT-3.14.4-3] (ISSUE 20 will fixes): the read loop usually
        # returns cleanly after stop() and clears it, but a transport
        # already racing its own teardown can surface the close as a
        # ConnectionError first — and that path fires send_lwt
        cl.properties.will = Will()
        cl.stop(CODE_DISCONNECT())  # [MQTT-3.14.4-2]

    def disconnect_client(self, cl: Client, code: Code) -> None:
        """Send DISCONNECT and close (server.go:1413-1437). Raises the code
        for error-class disconnects (mirrors the reference's error return).

        Under the shard fabric a disconnect targeting a client ANOTHER
        shard owns (cross-shard takeover, the main loop's eviction/drain
        paths) is marshaled onto the owning loop — the DISCONNECT write
        and the transport close are loop-affine. The marshaled form
        cannot raise; its callers already treat the raise as advisory
        (every call site catches Code)."""
        if self._fabric is not None and not self._client_loop_local(cl):
            loop = cl.net.loop
            if loop is not None and loop.is_running():
                try:
                    loop.call_soon_threadsafe(
                        self._disconnect_client_remote, cl, code
                    )
                    return
                except RuntimeError:
                    pass  # owner loop gone; close directly below
        out = Packet(
            fixed_header=FixedHeader(type=pkts.DISCONNECT),
            reason_code=code.code,
            properties=Properties(),
        )
        if code.code >= ERR_UNSPECIFIED_ERROR.code:
            out.properties.reason_string = code.reason  # [MQTT-3.14.2-1]
        try:
            cl.write_packet(out)
        except Exception:  # brokerlint: ok=R4 we're already disconnecting; write errors don't matter
            pass
        if not self.options.capabilities.compatibilities.passive_client_disconnect:
            cl.stop(code)
            if code.code >= ERR_UNSPECIFIED_ERROR.code:
                raise code()

    def _disconnect_client_remote(self, cl: Client, code: Code) -> None:
        """The owner-shard half of a marshaled disconnect."""
        try:
            self.disconnect_client(cl, code)
        except Code:
            pass

    # -- $SYS / housekeeping -----------------------------------------------

    def publish_sys_topics(self) -> None:
        """Publish retained $SYS values (server.go:1442-1492)."""
        now = int(time.time())  # brokerlint: ok=R3 $SYS/broker/time is wall-clock by definition
        self.info.memory_alloc = rss_bytes()
        self.info.threads = threading.active_count()
        self.info.time = now
        # monotonic anchor, not `now - started`: a wall-clock step (NTP,
        # suspend) must not bend $SYS/broker/uptime (system.Info)
        self.info.uptime = self.info.uptime_now()
        self.info.clients_total = len(self.clients)
        self.info.clients_disconnected = self.info.clients_total - self.info.clients_connected

        info = self.info.clone()
        topics = {
            SYS_PREFIX + "/broker/version": info.version,
            SYS_PREFIX + "/broker/time": str(info.time),
            SYS_PREFIX + "/broker/uptime": str(info.uptime),
            SYS_PREFIX + "/broker/started": str(info.started),
            SYS_PREFIX + "/broker/load/bytes/received": str(info.bytes_received),
            SYS_PREFIX + "/broker/load/bytes/sent": str(info.bytes_sent),
            SYS_PREFIX + "/broker/clients/connected": str(info.clients_connected),
            SYS_PREFIX + "/broker/clients/disconnected": str(info.clients_disconnected),
            SYS_PREFIX + "/broker/clients/maximum": str(info.clients_maximum),
            SYS_PREFIX + "/broker/clients/total": str(info.clients_total),
            SYS_PREFIX + "/broker/packets/received": str(info.packets_received),
            SYS_PREFIX + "/broker/packets/sent": str(info.packets_sent),
            SYS_PREFIX + "/broker/messages/received": str(info.messages_received),
            SYS_PREFIX + "/broker/messages/sent": str(info.messages_sent),
            SYS_PREFIX + "/broker/messages/dropped": str(info.messages_dropped),
            SYS_PREFIX + "/broker/messages/inflight": str(info.inflight),
            SYS_PREFIX + "/broker/retained": str(info.retained),
            SYS_PREFIX + "/broker/subscriptions": str(info.subscriptions),
            SYS_PREFIX + "/broker/system/memory": str(info.memory_alloc),
            SYS_PREFIX + "/broker/system/threads": str(info.threads),
        }
        if self.matcher is not None:
            # device-matcher observability (MatcherStats.as_dict): batches,
            # topics, host_fallbacks, overflows, rebuilds, fallback_ratio
            for key, val in self.matcher.stats.as_dict().items():
                topics[SYS_PREFIX + "/broker/matcher/" + key] = str(val)
            gauges = getattr(self.matcher, "breaker_gauges", None)
            if callable(gauges):
                # degradation-manager observability (mqtt_tpu.resilience):
                # breaker state/trips, fallback rates, probe counters
                for key, val in gauges().items():
                    topics[
                        SYS_PREFIX + "/broker/matcher/breaker/" + key
                    ] = str(val)
        if self._predicates is not None:
            # MQTT+ predicate plane (mqtt_tpu.predicates): rule counts,
            # device vs host eval split, filter selectivity, aggregation
            # emissions, oracle verdicts, breaker posture
            for key, val in self._predicates.gauges().items():
                topics[SYS_PREFIX + "/broker/predicates/" + key] = str(val)
        if self._recrypt is not None:
            # re-encryption observability (mqtt_tpu.tenancy): batch/block
            # split, oracle verdicts, key count, breaker posture
            for key, val in self._recrypt.gauges().items():
                topics[SYS_PREFIX + "/broker/recrypt/" + key] = str(val)
        if self._tenancy is not None:
            # per-tenant $SYS scoping: each ACTIVE tenant's counters
            # publish INTO its own namespace (a tenant subscribing
            # $SYS/broker/tenant/# sees only its own broker stats —
            # structurally, like everything else) plus a global
            # operator mirror under $SYS/broker/tenants/<name>/
            for t in self._tenancy.active_tenants():
                for key, val in t.sys_rows().items():
                    topics[
                        ns_scope_topic(
                            t.name, SYS_PREFIX + "/broker/tenant/" + key
                        )
                    ] = str(val)
                    topics[
                        SYS_PREFIX + f"/broker/tenants/{t.name}/" + key
                    ] = str(val)
        if self.overload is not None:
            # overload-governor observability (mqtt_tpu.overload): state,
            # transition/shed/eviction/throttle counters, per-signal
            # pressures (signal/*) and their high-water marks (peak/*)
            for key, val in self.overload.gauges().items():
                topics[SYS_PREFIX + "/broker/overload/" + key] = str(val)
            topics[SYS_PREFIX + "/broker/overload/outbound_backlog"] = str(
                self._outbound_backlog
            )
            if self._stage is not None:
                st = self._stage
                topics[SYS_PREFIX + "/broker/overload/stage_pending"] = str(
                    st.pending_depth
                )
                topics[
                    SYS_PREFIX + "/broker/overload/stage_peak_pending"
                ] = str(st.peak_pending)
                topics[
                    SYS_PREFIX + "/broker/overload/stage_admission_fallbacks"
                ] = str(st.admission_fallbacks)
        if self.telemetry is not None:
            # telemetry-plane observability (mqtt_tpu.telemetry): stage
            # histogram percentiles, batch occupancy, fallback classes,
            # queue-wait, flight-recorder state
            for key, val in self.telemetry.sys_tree().items():
                topics[SYS_PREFIX + "/broker/telemetry/" + key] = str(val)
        if self.device_stats is not None:
            # per-device observability (ISSUE 18, ops/devicestats): HBM,
            # duty cycles, skew, and the compile ledger as retained rows
            for key, val in self.device_stats.sys_tree().items():
                topics[SYS_PREFIX + "/broker/devices/" + key] = str(val)
        if self._cluster is not None:
            # worker-mesh observability (mqtt_tpu.cluster)
            c = self._cluster
            topics[SYS_PREFIX + "/broker/cluster/worker"] = str(c.worker_id)
            topics[SYS_PREFIX + "/broker/cluster/peers"] = str(c.peer_count)
            topics[SYS_PREFIX + "/broker/cluster/dropped_forwards"] = str(
                c.dropped_forwards
            )
            # backpressure + link-health gauges (mqtt_tpu.cluster known
            # limits: QoS>0 forwards DROP at the peer-buffer cap — the
            # drop is counted here, never silent)
            topics[SYS_PREFIX + "/broker/cluster/dropped_qos_forwards"] = str(
                c.dropped_qos_forwards
            )
            topics[SYS_PREFIX + "/broker/cluster/reconnects"] = str(
                c.reconnects_total
            )
            # overload tier: QoS0 forwards shed at the governor's reduced
            # peer-buffer cap (subset of dropped_forwards, never silent)
            topics[SYS_PREFIX + "/broker/cluster/shed_qos0_forwards"] = str(
                c.shed_qos0_forwards
            )
            # partition-tolerance gauges (ISSUE 5): the drop-class split
            # (partition-time vs backlog), the park buffer, and replays
            topics[SYS_PREFIX + "/broker/cluster/peer_drops_partition"] = str(
                c.dropped_partition
            )
            topics[SYS_PREFIX + "/broker/cluster/peer_drops_backlog"] = str(
                c.dropped_backlog
            )
            topics[SYS_PREFIX + "/broker/cluster/parked_forwards"] = str(
                c.parked_forwards
            )
            topics[SYS_PREFIX + "/broker/cluster/replayed_forwards"] = str(
                c.replayed_forwards
            )
            # control-plane byte volume (the drill's O(degree) gossip
            # assertion reads it per worker)
            topics[SYS_PREFIX + "/broker/cluster/control_bytes"] = str(
                c.control_bytes
            )
            for peer, n in sorted(c.dropped_by_peer.items()):
                topics[
                    SYS_PREFIX + f"/broker/cluster/peer/{peer}/dropped_forwards"
                ] = str(n)
            for peer, ph in sorted(c._health.items()):
                topics[
                    SYS_PREFIX + f"/broker/cluster/peer/{peer}/health"
                ] = ph.state
            if c.topo is not None:
                # spanning-tree gauges (ISSUE 9): epoch, live edge
                # count, the loop/duplicate guards, and the summary
                # routing split — everything the partition-storm drill
                # asserts from the outside
                t = c.topo
                topics[SYS_PREFIX + "/broker/cluster/tree/epoch"] = str(
                    t.epoch_num()
                )
                topics[SYS_PREFIX + "/broker/cluster/tree/neighbors"] = str(
                    len(t.neighbors())
                )
                topics[SYS_PREFIX + "/broker/cluster/tree/links"] = str(
                    sum(1 for p in t.neighbors() if p in c._writers)
                )
                topics[SYS_PREFIX + "/broker/cluster/tree/re_elections"] = str(
                    t.re_elections
                )
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/duplicates_suppressed"
                ] = str(c.duplicates_suppressed)
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/stale_epoch_frames"
                ] = str(c.stale_epoch_frames)
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/summary_filtered"
                ] = str(c.summary_filtered_forwards)
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/summary_passthrough"
                ] = str(c.summary_passthrough_forwards)
                # predicate push-down + root-failover gauges (ISSUE 17):
                # the WAN drill asserts both from the outside
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/predicate_filtered"
                ] = str(c.summary_predicate_filtered_forwards)
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/root_failovers"
                ] = str(c.root_failovers)
                topics[
                    SYS_PREFIX + "/broker/cluster/tree/root_failover_last_s"
                ] = "%.6f" % c.root_failover_last_s
                topics[SYS_PREFIX + "/broker/cluster/tree/root"] = str(
                    t.root()
                )
                topics[SYS_PREFIX + "/broker/cluster/tree/successor"] = str(
                    t.successor()
                )
        pk = Packet(
            fixed_header=FixedHeader(type=pkts.PUBLISH, retain=True),
            created=now,
        )
        for topic, payload in topics.items():
            pk.topic_name = topic
            pk.payload = payload.encode()
            self.topics.retain_message(pk.copy(False))
            if self._retained_engine is not None:
                self._retained_engine.note_retained(topic, True)
            self.publish_to_subscribers(pk)
        if (
            self._durable["recovering"]
            or self._durable["replayed_keys"]
            or self._durable["restore_batches"]
        ):
            # keep the recovery tree fresh on the $SYS cadence (only
            # once a durable restore has actually happened — brokers
            # with no storage hook never grow the subtree)
            self.publish_durable_sys()
        self.hooks.on_sys_info_tick(info)

    async def close(self) -> None:
        """Gracefully stop the server, listeners, clients, and hooks
        (server.go:1495-1504)."""
        self._draining = True  # late CONNECTs now refuse with 0x89
        self.done.set()
        self.log.info("gracefully stopping server")
        await self.listeners.close_all(self._close_listener_clients)
        if self._fabric is not None:
            # after the listeners: the drain disconnects were marshaled
            # onto the shard loops, which must still be alive to run
            # them; stop() then drains the establish tasks and joins
            # the shard threads (mqtt_tpu.shards)
            await self._fabric.stop()
            self._fabric = None
        # stage first (parked publishes resolve via the host walk), then
        # the matcher; shutdown LWT publishes and clean-session
        # unsubscribes must still flow through the live delta overlay
        if self._stage is not None:
            await self._stage.stop()
            self._stage = None
        if self._jax_trace_active:
            self._jax_trace_active = False
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception:  # brokerlint: ok=R4 teardown; a failed profiler stop must not abort the drain
                self.log.exception("jax.profiler trace failed to stop")
        if self.matcher is not None:
            self.matcher.close()
        if self.host_profiler is not None:
            self.host_profiler.stop()
        if self._lock_plane_armed:
            self._lock_plane_armed = False
            self.telemetry.lock_plane.disarm()
        self.hooks.on_stopped()
        self.hooks.stop()
        if self._event_loop_task is not None:
            self._event_loop_task.cancel()
        self.log.info("mqtt_tpu server stopped")

    def _close_listener_clients(self, listener: str) -> None:
        """(server.go:1507-1512)"""
        for cl in self.clients.get_by_listener(listener):
            try:
                self.disconnect_client(cl, ERR_SERVER_SHUTTING_DOWN)
            except Code:
                pass

    def send_lwt(self, cl: Client) -> None:
        """Issue (or delay) a client's will message (server.go:1515-1551)."""
        if cl.properties.will.flag == 0:
            return
        if cl.is_taken_over:
            # session takeover is not an ungraceful disconnect: the
            # inheriting connection IS the client, so the old
            # connection's will must not fire (ISSUE 20 will fixes —
            # the read loop's teardown path lands here after
            # disconnect_client(ERR_SESSION_TAKEN_OVER) aborts it)
            cl.properties.will = Will()
            return
        if self.overload is not None and not self.overload.admit(cl):
            # wills ride the same shed accounting as live publishes
            # (ISSUE 20): a mass-disconnect will storm against a broker
            # already in SHED must not bypass the governor — the will is
            # dropped AND counted, exactly like an admitted-path shed
            self.info.messages_dropped += 1
            if cl.tenant is not None:
                cl.tenant.messages_dropped += 1
            cl.properties.will = Will()
            return
        modified = self.hooks.on_will(cl, cl.properties.will)
        now = int(time.time())  # brokerlint: ok=R3 will-message created/expiry stamps are wall-clock
        pk = Packet(
            fixed_header=FixedHeader(
                type=pkts.PUBLISH,
                retain=modified.retain,  # [MQTT-3.1.2-14/-15]
                qos=modified.qos,
            ),
            topic_name=modified.topic_name,
            payload=modified.payload,
            properties=Properties(user=modified.user),
            origin=cl.id,
            created=now,
        )
        if cl.tenant is not None:
            # a tenant's will fires into its own namespace — exactly
            # like its live publishes (mqtt_tpu.tenancy)
            pk.topic_name = ns_scope_topic(cl.tenant.name, pk.topic_name)
        if cl.properties.will.will_delay_interval > 0:
            pk.connect.will_properties.will_delay_interval = (
                cl.properties.will.will_delay_interval
            )
            pk.expiry = now + pk.connect.will_properties.will_delay_interval
            self.will_delayed.add(cl.id, pk)
            return
        if pk.fixed_header.retain:
            self.retain_message(cl, pk)
        self.publish_to_subscribers(pk)  # [MQTT-3.1.2-8]
        cl.properties.will.flag = 0  # [MQTT-3.1.2-10]
        self.hooks.on_will_sent(cl, pk)

    # -- persistence restore (server.go:1554-1692) -------------------------

    def read_store(self) -> None:
        # durable recovery window (ISSUE 16): healthz answers 503
        # `recovering` from the first restored byte until serve() has
        # the maps actually being served (after hooks.on_started()).
        # Restore failures propagate — serving a silently-partial
        # session map would be worse than refusing to start.
        self._durable["recovering"] = True
        t0 = time.perf_counter()
        try:
            if self.hooks.provides(STORED_CLIENTS):
                clients = self.hooks.stored_clients()
                self.load_clients(clients)
                self.log.debug("loaded clients from store: len=%d", len(clients))
            if self.hooks.provides(STORED_SUBSCRIPTIONS):
                subs = self.hooks.stored_subscriptions()
                self.load_subscriptions(subs)
                self.log.debug("loaded subscriptions from store: len=%d", len(subs))
            if self.hooks.provides(STORED_INFLIGHT_MESSAGES):
                inflight = self.hooks.stored_inflight_messages()
                self.load_inflight(inflight)
                self.log.debug("loaded inflights from store: len=%d", len(inflight))
            if self.hooks.provides(STORED_RETAINED_MESSAGES):
                retained = self.hooks.stored_retained_messages()
                self.load_retained(retained)
                self.log.debug("loaded retained messages from store: len=%d", len(retained))
            if self.hooks.provides(STORED_SYS_INFO):
                sys_info = self.hooks.stored_sys_info()
                if sys_info is not None:
                    self.load_server_info(sys_info.info)
                    self.log.debug("loaded $SYS info from store")
        finally:
            self._durable["recovery_seconds"] = time.perf_counter() - t0
            self._durable["replayed_keys"] = int(
                self._durable_store_stats().get("replayed_keys", 0)
            )

    def load_server_info(self, v: Info) -> None:
        if self.options.capabilities.compatibilities.restore_sys_info_on_restart:
            self.info.bytes_received = v.bytes_received
            self.info.bytes_sent = v.bytes_sent
            self.info.clients_maximum = v.clients_maximum
            self.info.clients_total = v.clients_total
            self.info.clients_disconnected = v.clients_disconnected
            self.info.messages_received = v.messages_received
            self.info.messages_sent = v.messages_sent
            self.info.messages_dropped = v.messages_dropped
            self.info.packets_received = v.packets_received
            self.info.packets_sent = v.packets_sent
            self.info.inflight_dropped = v.inflight_dropped
        self.info.retained = v.retained
        self.info.inflight = v.inflight
        self.info.subscriptions = v.subscriptions

    def load_subscriptions(self, v: list) -> None:
        entries: list[tuple[str, Subscription]] = []
        for sub in v:
            predicates = tuple(getattr(sub, "predicates", ()) or ())
            if predicates and self._predicates is not None:
                # re-intern persisted MQTT+ rules (a restart must keep
                # filtering; with the plane disabled the subscription
                # restores as its base filter and fails open)
                for suffix in predicates:
                    try:
                        self._predicates.register(suffix)
                    except ValueError:
                        predicates = ()
                        break
            sb = Subscription(
                filter=sub.filter,
                retain_handling=sub.retain_handling,
                qos=sub.qos,
                retain_as_published=sub.retain_as_published,
                no_local=sub.no_local,
                identifier=sub.identifier,
                predicates=predicates,
            )
            entries.append((sub.client, sb))
        # batched re-registration (ISSUE 16): a million-session restart
        # must not pay a trie lock round-trip per subscription — chunks
        # flow through the trie's bulk-insert path
        from .staging import bulk_register

        new, batches = bulk_register(
            self.topics, entries, batch=self.options.durable_restore_batch
        )
        self._durable["restored_subscriptions"] += new
        self._durable["restore_batches"] += batches
        for client, sb in entries:
            cl = self.clients.get(client)
            if cl is not None:
                cl.state.subscriptions.add(sb.filter, sb)
            if self._tenancy is not None and sb.filter[:1] == NS_CHAR:
                t = self._tenancy.tenant_of_topic(sb.filter)
                if t is not None:
                    # seed the durable COUNT quota from restored state:
                    # a tenant over cap after restart keeps its
                    # subscriptions but cannot grow further
                    t.subscriptions_count += 1

    def load_clients(self, v: list) -> None:
        for c in v:
            cl = self.new_client(None, None, c.listener, c.id, False)
            cl.properties.username = c.username
            cl.properties.clean = c.clean
            cl.properties.protocol_version = c.protocol_version
            cl.properties.props = Properties(
                session_expiry_interval=c.properties.session_expiry_interval,
                session_expiry_interval_flag=c.properties.session_expiry_interval_flag,
                authentication_method=c.properties.authentication_method,
                authentication_data=c.properties.authentication_data,
                request_problem_info_flag=c.properties.request_problem_info_flag,
                request_problem_info=c.properties.request_problem_info,
                request_response_info=c.properties.request_response_info,
                receive_maximum=c.properties.receive_maximum,
                topic_alias_maximum=c.properties.topic_alias_maximum,
                user=list(c.properties.user),
                maximum_packet_size=c.properties.maximum_packet_size,
            )
            cl.properties.will = Will(
                payload=c.will.payload,
                user=list(c.will.user),
                topic_name=c.will.topic_name,
                flag=c.will.flag,
                will_delay_interval=c.will.will_delay_interval,
                qos=c.will.qos,
                retain=c.will.retain,
            )
            # restored clients are disconnected and expire normally
            cl.stop(ERR_SERVER_SHUTTING_DOWN())
            expire = (
                cl.properties.protocol_version == 5
                and cl.properties.props.session_expiry_interval == 0
            ) or (cl.properties.protocol_version < 5 and cl.properties.clean)
            self.hooks.on_disconnect(cl, ERR_SERVER_SHUTTING_DOWN(), expire)
            if expire:
                cl.clear_inflights()
                self.unsubscribe_client(cl)
            else:
                self.clients.add_client(cl)

    def load_inflight(self, v: list) -> None:
        # batched restore (ISSUE 17 satellite): the unacked QoS1/QoS2
        # window rides the same chunked bulk path as subscriptions and
        # retained — one inflight-lock acquisition per chunk, and the
        # restore counters prove it was batched
        from .staging import bulk_inflight

        restored, batches = bulk_inflight(
            self.clients, v, batch=self.options.durable_restore_batch
        )
        self._durable["restored_inflight"] += restored
        self._durable["restore_batches"] += batches

    def load_retained(self, v: list) -> None:
        from .staging import bulk_retain

        packets = [msg.to_packet() for msg in v]
        retained, batches = bulk_retain(
            self.topics, packets, batch=self.options.durable_restore_batch
        )
        self._durable["restored_retained"] += retained
        self._durable["restore_batches"] += batches
        self.info.retained = len(self.topics.retained)
        if self._tenancy is not None:
            for pk in packets:
                if pk.payload and pk.topic_name[:1] == NS_CHAR:
                    t = self._tenancy.tenant_of_topic(pk.topic_name)
                    if t is not None:
                        t.retained_count += 1
        if self._retained_engine is not None:
            # one corpus rebuild beats a million note_retained calls
            self._retained_engine.reseed()

    # -- expiry loops (server.go:1696-1758) --------------------------------

    def clear_expired_clients(self, dt: int) -> None:
        for id_, client in self.clients.get_all().items():
            disconnected = client.stop_time
            if disconnected == 0:
                continue
            expire = self.options.capabilities.maximum_session_expiry_interval
            if (
                client.properties.protocol_version == 5
                and client.properties.props.session_expiry_interval_flag
            ):
                expire = client.properties.props.session_expiry_interval
            if disconnected + expire < dt:
                # a pending delayed will fires when the session ends,
                # even if its delay interval has not elapsed
                # [MQTT-3.1.2-8] (ISSUE 20 will fixes): expiry must not
                # orphan the entry — and its retain flag must still be
                # honored after the session object is gone
                pending = self.will_delayed.get(id_)
                if pending is not None:
                    self.will_delayed.delete(id_)
                    if pending.fixed_header.retain:
                        self.topics.retain_message(pending.copy(False))
                        self.info.retained = len(self.topics.retained)
                        if self._retained_engine is not None:
                            self._retained_engine.note_retained(
                                pending.topic_name, True
                            )
                    self.publish_to_subscribers(pending)
                    self.hooks.on_will_sent(client, pending)
                self.hooks.on_client_expired(client)
                self.clients.delete(id_)  # [MQTT-4.1.0-2]

    def clear_expired_retained_messages(self, now: int) -> None:
        for filter_, pk in self.topics.retained.get_all().items():
            expired = pk.protocol_version == 5 and 0 < pk.expiry < now  # [MQTT-3.3.2-5]
            enforced = (
                self.options.capabilities.maximum_message_expiry_interval > 0
                and now - pk.created > self.options.capabilities.maximum_message_expiry_interval
            )
            if expired or enforced:
                self.topics.retained.delete(filter_)
                self.hooks.on_retained_expired(filter_)
                if self._tenancy is not None and filter_[:1] == NS_CHAR:
                    t = self._tenancy.tenant_of_topic(filter_)
                    if t is not None and t.retained_count > 0:
                        t.retained_count -= 1
                if self._retained_engine is not None:
                    self._retained_engine.note_retained(filter_, False)

    def clear_expired_inflights(self, now: int) -> None:
        for client in self.clients.get_all().values():
            deleted = client.clear_expired_inflights(
                now, self.options.capabilities.maximum_message_expiry_interval
            )
            for id_ in deleted:
                self.hooks.on_qos_dropped(client, Packet(packet_id=id_))

    def send_delayed_lwt(self, dt: int) -> None:
        for id_, pk in self.will_delayed.get_all().items():
            if dt > pk.expiry:
                cl = self.clients.get(id_)
                if (
                    cl is not None
                    and self.overload is not None
                    and not self.overload.admit(cl)
                ):
                    # delayed wills obey the shed accounting too
                    # (ISSUE 20): counted and dropped, never a governor
                    # bypass
                    self.info.messages_dropped += 1
                    if cl.tenant is not None:
                        cl.tenant.messages_dropped += 1
                    cl.properties.will = Will()
                    self.will_delayed.delete(id_)
                    continue
                self.publish_to_subscribers(pk)  # [MQTT-3.1.2-8]
                if pk.fixed_header.retain:
                    if cl is not None:
                        self.retain_message(cl, pk)
                    else:
                        # the retain flag holds even when the session
                        # is already gone (ISSUE 20 will fixes)
                        self.topics.retain_message(pk.copy(False))
                        self.info.retained = len(self.topics.retained)
                        if self._retained_engine is not None:
                            self._retained_engine.note_retained(
                                pk.topic_name, True
                            )
                if cl is not None:
                    cl.properties.will = Will()  # [MQTT-3.1.2-10]
                    self.hooks.on_will_sent(cl, pk)
                self.will_delayed.delete(id_)


def _minimum(a: int, b: int) -> int:
    """Minimum of the non-zero values of a and b; 0 when both are zero
    (server.go:1767-1780)."""
    if a != 0:
        if b != 0 and b < a:
            return b
        return a
    return b
