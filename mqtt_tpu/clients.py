"""Per-client connection state machine: buffered async reads, a single
writer task draining a bounded outbound queue, packet-id allocation,
keepalive deadlines, topic aliases, and session state.

Behavioral parity with reference ``clients.go``. The reference's
goroutine-per-connection becomes one asyncio reader task plus one writer
task per client; the bounded ``outbound`` channel becomes an
``asyncio.Queue`` whose ``put_nowait``-full path reproduces the reference's
drop-on-slow-consumer semantics (server.go:1099-1110).
"""

from __future__ import annotations

import asyncio
import collections
import logging
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Awaitable, Callable, Optional

from . import packets as pkts
from .inflight import Inflight
from .packets import (
    ERR_PACKET_TOO_LARGE,
    ERR_QUOTA_EXCEEDED,
    Code,
    FixedHeader,
    Packet,
    Properties,
    UserProperty,
)
from .topics import OutboundTopicAliases, Subscriptions, TopicAliases
from .utils import LockedMap
from .utils.loopwitness import DEFAULT_LOOP_PLANE as _LOOP_PLANE
from .utils.mempool import get_buffer, put_buffer

DEFAULT_KEEPALIVE = 10  # default connection keepalive seconds (clients.go:25)
DEFAULT_CLIENT_PROTOCOL_VERSION = 4  # (clients.go:26)
MINIMUM_KEEPALIVE = 5  # below this a warning is logged (clients.go:27)


class ConnectionClosedError(Exception):
    """The client connection is not open (reference ErrConnectionClosed)."""


class OutboundQueue:
    """A thread-safe bounded outbound queue with asyncio.Queue's
    data-plane surface (``put_nowait``/``QueueFull``, awaitable
    ``get``, ``full``/``qsize``/``empty``).

    asyncio.Queue is loop-affine: ``put_nowait`` wakes waiters with a
    plain ``call_soon``, which is illegal from any other thread. Under
    the event-loop shard fabric (mqtt_tpu.shards) a publisher's fan-out
    runs on ITS shard's loop and enqueues onto subscribers owned by
    OTHER shards — so the queue itself goes thread-safe: a lock-guarded
    deque plus a single-consumer wakeup future that cross-thread
    producers resolve via ``call_soon_threadsafe`` on the consumer's
    loop. Single-loop brokers pay one uncontended lock acquire per
    enqueue/dequeue and keep identical semantics.
    """

    __slots__ = ("maxsize", "_items", "_lock", "_waiter", "_witness_loop")

    def __init__(self, maxsize: int = 0) -> None:
        self.maxsize = maxsize
        self._items: collections.deque = collections.deque()
        self._lock = threading.Lock()
        # the single consumer's parked (loop, future), or None; the
        # write loop is the only get() caller, so one slot suffices
        self._waiter: Optional[tuple] = None
        # owning-loop identity stamped by the first witnessed get()
        # (mqtt_tpu.utils.loopwitness); None while unobserved/disarmed
        self._witness_loop: Optional[asyncio.AbstractEventLoop] = None

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    @staticmethod
    def _wake(fut: "asyncio.Future") -> None:
        if not fut.done():
            fut.set_result(None)

    def put_nowait(self, item: Any) -> None:
        """Enqueue from ANY thread; raises ``asyncio.QueueFull`` past
        the bound (the drop-on-slow-consumer contract is unchanged)."""
        plane = _LOOP_PLANE
        if plane.active:
            w = plane.witness
            if w is not None:
                w.note_crossing(
                    "outbound_queue", "put_local", "put_cross",
                    self._witness_loop,
                )
        wake = None
        with self._lock:
            if 0 < self.maxsize <= len(self._items):
                raise asyncio.QueueFull()
            self._items.append(item)
            if self._waiter is not None:
                wake, self._waiter = self._waiter, None
        if wake is not None:
            loop, fut = wake
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if loop is running:
                self._wake(fut)
            else:
                try:
                    loop.call_soon_threadsafe(self._wake, fut)
                except RuntimeError:
                    pass  # consumer loop closed; the writer task is gone

    async def get(self) -> Any:
        """Dequeue (single consumer: the client's write loop)."""
        plane = _LOOP_PLANE
        if plane.active:
            w = plane.witness
            if w is not None:
                if self._witness_loop is None:
                    self._witness_loop = asyncio.get_running_loop()
                w.check_owner(
                    "outbound_queue", "get_owner", self._witness_loop
                )
        while True:
            with self._lock:
                if self._items:
                    return self._items.popleft()
                loop = asyncio.get_running_loop()
                fut: asyncio.Future = loop.create_future()
                self._waiter = (loop, fut)
            try:
                await fut
            except asyncio.CancelledError:
                with self._lock:
                    if self._waiter is not None and self._waiter[1] is fut:
                        self._waiter = None
                raise


class ScanGate:
    """Coalesce frame scans from read loops that wake in the same
    event-loop tick into ONE native multi-buffer call (ISSUE 13's
    read-side decode batching — mqtt_native.mqtt_frame_scan_multi).

    Read loops register their buffer and await a future; a
    ``call_soon`` flush runs after every currently-ready callback (i.e.
    after every read loop that woke this tick has registered), scans
    all buffers in one GIL-released pass, and resolves the futures.
    Single-scanner ticks pay one loop-callback hop and nothing else;
    without the native library the flush falls back to per-buffer
    scans. Opt-in via ``Options.scan_coalesce``."""

    def __init__(self) -> None:
        self._pending: list = []
        self._scheduled = False
        self.batches = 0  # flush calls issued (observability)
        self.scans = 0  # buffers scanned through the gate

    def scan(
        self, buf: bytearray, max_frames: int, max_packet_size: int
    ) -> "asyncio.Future":
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending.append((buf, fut))
        self._max_frames = max_frames
        self._max_packet_size = max_packet_size
        if not self._scheduled:
            self._scheduled = True
            loop.call_soon(self._flush)
        return fut

    def _flush(self) -> None:
        from .native import frame_scan, frame_scan_multi

        self._scheduled = False
        pending, self._pending = self._pending, []
        if not pending:
            return
        self.batches += 1
        self.scans += len(pending)
        results = None
        try:
            results = frame_scan_multi(
                [buf for buf, _ in pending],
                max_frames=self._max_frames,
                max_packet_size=self._max_packet_size,
            )
        except Exception as e:
            for _buf, fut in pending:
                if not fut.done():
                    fut.set_exception(e)
            return
        if results is None:
            # no native library: per-buffer scans, same contract
            for buf, fut in pending:
                if fut.done():
                    continue
                try:
                    fut.set_result(
                        frame_scan(
                            buf, max_frames=self._max_frames,
                            max_packet_size=self._max_packet_size,
                        )
                    )
                except Exception as e:
                    fut.set_exception(e)
            return
        for (_buf, fut), res in zip(pending, results):
            if not fut.done():
                fut.set_result(res)


@dataclass
class Will:
    """Last will and testament details (clients.go:132-140)."""

    payload: bytes = b""
    user: list[UserProperty] = field(default_factory=list)
    topic_name: str = ""
    flag: int = 0  # 0/1; cleared once the will is sent
    will_delay_interval: int = 0
    qos: int = 0
    retain: bool = False


class ClientConnection:
    """Transport state for one client (clients.go:113-120)."""

    def __init__(
        self,
        reader: Optional[asyncio.StreamReader] = None,
        writer: Optional[asyncio.StreamWriter] = None,
    ) -> None:
        self.reader = reader
        self.writer = writer
        self.remote = ""
        self.listener = ""
        self.inline = False
        # the asyncio loop OWNING this transport (set at attach): under
        # the shard fabric every transport write/close must happen on
        # it; None (inline clients, unattached tests) means loop-local
        self.loop: Optional[asyncio.AbstractEventLoop] = None
        if writer is not None:
            peer = writer.get_extra_info("peername")
            if peer:
                self.remote = f"{peer[0]}:{peer[1]}" if isinstance(peer, tuple) else str(peer)


class ClientProperties:
    """Properties defining client behaviour (clients.go:123-129)."""

    def __init__(self) -> None:
        self.props = Properties()
        self.will = Will()
        self.username = b""
        self.protocol_version = DEFAULT_CLIENT_PROTOCOL_VERSION
        self.clean = False


class ClientState:
    """Operational state of one client (clients.go:143-158)."""

    def __init__(self, topic_alias_maximum: int, max_writes_pending: int) -> None:
        self.topic_aliases = TopicAliases(topic_alias_maximum)
        self.inflight = Inflight()
        self.subscriptions = Subscriptions()  # filter -> Subscription (client mirror)
        self.disconnected = 0  # unix ts of disconnect, for expiry
        # Packet on the per-subscriber path, raw bytes on the shared
        # QoS0 frame fast path (clients._write_loop dispatches on type);
        # thread-safe so cross-shard fan-out can enqueue directly
        # (mqtt_tpu.shards)
        self.outbound: OutboundQueue = OutboundQueue(
            maxsize=max_writes_pending
        )
        self.keepalive = DEFAULT_KEEPALIVE
        self.server_keepalive = False
        self.packet_id = 0  # current highest allocated packet id
        self.stop_cause: Optional[Exception] = None
        self.is_taken_over = False
        self.open = True
        # monotonic ts the outbound queue was first found full (None =
        # not full); the overload governor's slow-consumer eviction
        # sweep compares it against the grace window (mqtt_tpu.overload)
        self.outbound_full_since: Optional[float] = None
        # monotonic ts the client's backlog (transport write buffer past
        # its limit, or a still-full outbound queue) was first observed
        # by the overload sweep; cleared the moment it drains
        self.backlog_over_since: Optional[float] = None
        # transport buffer size at the last overload sweep: a consumer
        # whose buffer SHRANK since then is draining (slow, not stalled)
        # and must not accumulate eviction grace
        self.sweep_buffered = 0
        # outbound queue-wait sampling (mqtt_tpu.telemetry): every
        # successful enqueue bumps out_seq (server._stamp_outbound);
        # sampled enqueues park (seq, t) here and the write loop matches
        # out_deq against the head to observe the wait. Bounded: evicted
        # stamps are just lost samples.
        self.out_seq = 0
        self.out_deq = 0
        self.out_stamps: collections.deque = collections.deque(maxlen=64)
        # write-path accounting (mqtt_tpu.profiling / ROADMAP item 3):
        # bytes and socket-write calls this client's outbound legs have
        # issued — the per-client face of the aggregate
        # mqtt_tpu_outbound_{bytes,writes}_total counters
        self.out_bytes = 0
        self.out_writes = 0

    @property
    def outbound_qty(self) -> int:
        """Queued outbound publishes — delegated to the thread-safe
        queue's own count. A bare ``+=`` mirror would lose updates when
        shard threads enqueue concurrently (mqtt_tpu.shards), and this
        count gates the direct-socket flush eligibility
        (server._flush_variant), where an undercount could reorder
        frames past still-queued ones."""
        return self.outbound.qsize()


class Client:
    """A client known by the broker (clients.go:103-110)."""

    def __init__(self, reader, writer, ops) -> None:
        self.ops = ops
        self.id = ""
        self.properties = ClientProperties()
        self.state = ClientState(
            ops.options.capabilities.topic_alias_maximum,
            ops.options.capabilities.maximum_client_writes_pending,
        )
        self.net = ClientConnection(reader, writer)
        self._deadline: Optional[float] = None  # monotonic keepalive deadline
        self._writer_task: Optional[asyncio.Task] = None
        # per-evaluation-window publish counter for the overload
        # governor's THROTTLE read-delay verdict (mqtt_tpu.overload);
        # the read loop counts, read_delay() resets on window roll
        self._pub_epoch = -1
        self._pub_count = 0
        # priority-weighted shedding (mqtt_tpu.overload): the class and
        # its shed/publish-quota multiplier, resolved at CONNECT from
        # Options.overload_priority_users / overload_priority_classes
        # (server._assign_priority_class); 1.0 = the flat default. The
        # governor reads the weight on every admit/read_delay verdict,
        # so it lives here as a plain attribute, not a config lookup.
        self.priority_class = ""
        self.priority_weight = 1.0
        # the tenant this client resolved to at CONNECT
        # (mqtt_tpu.tenancy.Tenant) or None for the global namespace;
        # set once by server._resolve_tenant, read on every publish /
        # subscribe to decide namespace scoping
        self.tenant: Optional[Any] = None
        # the owning shard's read-side ScanGate (mqtt_tpu.shards): set
        # at attach when the fabric is on; None falls back to the
        # server-wide gate (Options.scan_coalesce) or per-socket scans
        self.scan_gate: Optional[ScanGate] = None
        # the attach-handler task serving this connection (set by
        # server.attach_client): the cross-shard takeover quiesce
        # awaits it on the owning loop so the old session's disconnect
        # epilogue fully runs before state migrates (mqtt_tpu.shards)
        self._handler_task: Optional[asyncio.Task] = None

    # -- lifecycle ---------------------------------------------------------

    def start_write_loop(self) -> None:
        """Spawn the single writer task draining the outbound queue
        (clients.go:192-205)."""
        self._writer_task = asyncio.get_running_loop().create_task(self._write_loop())

    async def _write_loop(self) -> None:
        st = self.state
        while True:
            pk = await st.outbound.get()
            st.out_deq += 1
            stamps = st.out_stamps
            if stamps:
                # resync past stamps evicted by the deque bound, then
                # observe the matching sampled enqueue's queue wait
                while stamps and stamps[0][0] < st.out_deq:
                    stamps.popleft()
                if stamps and stamps[0][0] == st.out_deq:
                    _, t0 = stamps.popleft()
                    tele = getattr(self.ops, "telemetry", None)
                    if tele is not None:
                        tele.outbound_wait.observe(time.perf_counter() - t0)
            try:
                if type(pk) is bytes:  # pre-encoded qos0 fan-out frame
                    self.write_frame(pk)
                else:
                    self.write_packet(pk)
            except Exception as e:
                self.ops.log.debug("failed publishing packet to %s: %s", self.id, e)

    def write_frame(self, data: bytes) -> None:
        """Write a pre-encoded PUBLISH frame (the server's qos0 fan-out
        fast path — shared bytes, one encode per publish). The fast path
        is disabled whenever on_packet_encode/on_packet_sent hooks are
        attached, so skipping them here never hides a hook call."""
        if self.closed:
            raise ConnectionClosedError()
        if self.net.writer is None:
            return
        self.net.writer.write(data)
        self.ops.info.bytes_sent += len(data)
        self.ops.info.packets_sent += 1
        self.ops.info.messages_sent += 1
        st = self.state
        st.out_bytes += len(data)
        st.out_writes += 1
        tele = getattr(self.ops, "telemetry", None)
        if tele is not None:
            # io accounting only here: the DELIVERY count for a shared
            # frame is stamped by server._enqueue_frame, which still
            # knows the topic (this pre-encoded frame does not) and so
            # can keep $SYS housekeeping out of the amplification math
            tele.outbound_bytes.inc(len(data))
            tele.outbound_writes.inc()

    def parse_connect(self, lid: str, pk: Packet) -> None:
        """Absorb CONNECT parameters into client state (clients.go:208-257)."""
        self.net.listener = lid
        self.properties.protocol_version = pk.protocol_version
        self.properties.username = pk.connect.username
        self.properties.clean = pk.connect.clean
        self.properties.props = pk.properties.copy(False)

        caps = self.ops.options.capabilities
        if self.properties.props.receive_maximum > caps.maximum_inflight:  # 3.3.4 Non-normative
            self.properties.props.receive_maximum = caps.maximum_inflight

        if 0 < pk.connect.keepalive <= MINIMUM_KEEPALIVE:
            # keepalive 0 DISABLES the mechanism [MQTT-3.1.2-22] — a
            # deliberate choice (mostly-idle device fleets), not a
            # too-small value worth one warning per ramped connection
            self.ops.log.warning(
                "client keepalive is below minimum recommended value: client=%s keepalive=%d recommended=%d",
                self.id,
                pk.connect.keepalive,
                MINIMUM_KEEPALIVE,
            )

        self.state.keepalive = pk.connect.keepalive  # [MQTT-3.2.2-22]
        self.state.inflight.reset_receive_quota(caps.receive_maximum)  # server per-client max
        self.state.inflight.reset_send_quota(self.properties.props.receive_maximum)  # client max
        self.state.topic_aliases.outbound = OutboundTopicAliases(
            self.properties.props.topic_alias_maximum
        )

        self.id = pk.connect.client_identifier
        if self.id == "":
            self.id = uuid.uuid4().hex[:20]  # [MQTT-3.1.3-6] [MQTT-3.1.3-7]
            self.properties.props.assigned_client_id = self.id

        if pk.connect.will_flag:
            self.properties.will = Will(
                qos=pk.connect.will_qos,
                retain=pk.connect.will_retain,
                payload=pk.connect.will_payload,
                topic_name=pk.connect.will_topic,
                will_delay_interval=pk.connect.will_properties.will_delay_interval,
                user=pk.connect.will_properties.user,
                flag=1,
            )
            if (
                pk.properties.session_expiry_interval_flag
                and pk.properties.session_expiry_interval
                < pk.connect.will_properties.will_delay_interval
            ):
                self.properties.will.will_delay_interval = pk.properties.session_expiry_interval

    def refresh_deadline(self, keepalive: int) -> None:
        """Arm the read deadline at keepalive x 1.5 [MQTT-3.1.2-22]
        (clients.go:260-269); 0 disables it."""
        self._deadline = time.monotonic() + keepalive * 1.5 if keepalive > 0 else None

    def next_packet_id(self) -> int:
        """The next unused packet id; raises ERR_QUOTA_EXCEEDED when all ids
        are inflight (clients.go:274-299)."""
        i = self.state.packet_id
        started = i
        overflowed = False
        maximum = self.ops.options.capabilities.maximum_packet_id
        while True:
            if overflowed and i == started:
                raise ERR_QUOTA_EXCEEDED()
            if i >= maximum:
                overflowed = True
                i = 0
                continue
            i += 1
            if self.state.inflight.get(i & 0xFFFF) is None:
                self.state.packet_id = i
                return i

    def resend_inflight_messages(self, force: bool) -> None:
        """Resend pending inflight messages with DUP [MQTT-3.3.1-1/-3]
        (clients.go:302-327)."""
        if len(self.state.inflight) == 0:
            return
        for tk in self.state.inflight.get_all(False):
            if tk.fixed_header.type == pkts.PUBLISH:
                tk.fixed_header.dup = True
            self.ops.hooks.on_qos_publish(self, tk, tk.created, 0)
            self.write_packet(tk)
            if tk.fixed_header.type in (pkts.PUBACK, pkts.PUBCOMP):
                if self.state.inflight.delete(tk.packet_id):
                    self.ops.hooks.on_qos_complete(self, tk)
                    self.ops.info.inflight -= 1

    def clear_inflights(self) -> None:
        """Drop all inflight messages, e.g. clean-session disconnect
        (clients.go:330-337)."""
        for tk in self.state.inflight.get_all(False):
            if self.state.inflight.delete(tk.packet_id):
                self.ops.hooks.on_qos_dropped(self, tk)
                self.ops.info.inflight -= 1

    def clear_expired_inflights(self, now: int, maximum_expiry: int) -> list[int]:
        """Drop expired inflight messages [MQTT-3.3.2-5] (clients.go:340-359)."""
        deleted = []
        for tk in self.state.inflight.get_all(False):
            expired = tk.protocol_version == 5 and 0 < tk.expiry < now
            enforced = maximum_expiry > 0 and now - tk.created > maximum_expiry
            if expired or enforced:
                if self.state.inflight.delete(tk.packet_id):
                    self.ops.hooks.on_qos_dropped(self, tk)
                    self.ops.info.inflight -= 1
                    deleted.append(tk.packet_id)
        return deleted

    async def read(self, packet_handler: Callable[["Client", Packet], Optional[Awaitable]]) -> None:
        """The blocking per-packet read loop (clients.go:363-388); raises on
        connection error, keepalive timeout, or a handler error.

        Packets are framed in bulk: each socket read drains everything
        available, the native frame scanner (mqtt_tpu/native) splits it
        into complete packets, and each is decoded straight from the
        buffer — one await per socket read instead of one per header byte,
        which is what keeps the asyncio data plane within reach of the
        reference's goroutine throughput (SURVEY.md §7 hard-part #5).
        """
        from .native import MAX_FRAMES_PER_SCAN, frame_scan, varint_decode

        caps = self.ops.options.capabilities
        fast_eligible = self.ops.fast_publish_eligible
        fast_publish = self.ops.fast_publish
        telemetry = getattr(self.ops, "telemetry", None)
        # the shard's own gate wins (per-shard decode batching is
        # default-on inside the fabric); the server-wide gate serves the
        # single-loop opt-in (Options.scan_coalesce)
        scan_gate = self.scan_gate or getattr(self.ops, "scan_gate", None)
        rbuf = bytearray()
        deferred: Optional[list] = None
        self.refresh_deadline(self.state.keepalive)
        while True:
            if self.closed:
                return
            if scan_gate is not None:
                # read-side decode batching (ISSUE 13): every read loop
                # that woke this tick lands in ONE native scan call
                frames, consumed, err = await scan_gate.scan(
                    rbuf, MAX_FRAMES_PER_SCAN, caps.maximum_packet_size
                )
            else:
                frames, consumed, err = frame_scan(
                    rbuf, max_frames=MAX_FRAMES_PER_SCAN,
                    max_packet_size=caps.maximum_packet_size,
                )
            # account for and process every complete packet
            start = 0
            for f in frames:
                fstart = start
                fend = f.body_offset + f.remaining
                self.ops.info.bytes_received += (f.body_offset - start) + f.remaining
                start = fend
                if (f.first_byte >> 4) == pkts.PUBLISH:
                    # overload-governor accounting: publishes this window
                    # (both the fast-path and decode legs land here)
                    self._pub_count += 1
                # QoS0 v4 PUBLISH passthrough (flags all zero): deliver the
                # frame bytes without materializing a Packet when the
                # server proves nothing can observe the difference. The
                # session gate runs BEFORE any bytes are copied.
                if (
                    f.first_byte == 0x30
                    and fast_publish is not None
                    and fast_eligible(self)
                ):
                    frame = bytes(rbuf[fstart:fend])
                    if fast_publish(self, frame, f.body_offset - fstart):
                        continue
                    body = frame[f.body_offset - fstart :]
                else:
                    body = bytes(rbuf[f.body_offset : fend])
                # telemetry stage clock: 1-in-N publishes get stamped
                # through decode -> admission -> staging -> fanout
                # (mqtt_tpu.telemetry); the clock rides on the packet
                clock = None
                if telemetry is not None and (f.first_byte >> 4) == pkts.PUBLISH:
                    clock = telemetry.publish_clock()
                fh = FixedHeader()
                fh.decode(f.first_byte)
                fh.remaining = f.remaining
                pk = self._decode_body(fh, body)
                if clock is not None:
                    clock.stamp("decode")
                    # dynamic rider, not a Packet field: the clock never
                    # touches the wire or dataclass equality
                    setattr(pk, "_tclock", clock)
                result = packet_handler(self, pk)
                if asyncio.iscoroutine(result):
                    # deferred (staged-publish) completions: schedule now,
                    # await after the whole scan — every publish in this
                    # socket read reaches the staging batch before we block
                    # on any of them, so one pipelining client still fills
                    # device batches instead of paying a round trip each
                    if deferred is None:
                        deferred = []
                    deferred.append(asyncio.get_running_loop().create_task(result))
                if self.closed:
                    break
            if deferred is not None:
                err0: Optional[BaseException] = None
                for t in deferred:
                    try:
                        await t
                    except BaseException as e:
                        err0 = err0 or e
                deferred = None
                if err0 is not None:
                    raise err0
            if self.closed:
                return
            del rbuf[:consumed]
            if err == -2:
                raise ERR_PACKET_TOO_LARGE()  # [MQTT-3.2.2-15]
            if err == -1:
                # replay the per-byte path for the precise reason code
                FixedHeader().decode(rbuf[0])  # raises for bad header bytes
                raise pkts.ERR_MALFORMED_VARIABLE_BYTE_INTEGER()
            if len(frames) == MAX_FRAMES_PER_SCAN:
                continue  # more complete packets may still be buffered
            if frames:
                # progress made — extend the keepalive deadline. A trickle
                # of partial-packet bytes deliberately does NOT extend it.
                self.refresh_deadline(self.state.keepalive)
            overload = self.ops.overload
            if overload is not None and not self.net.inline:
                # THROTTLE lever: an over-quota publisher's next socket
                # read is delayed, so the kernel's TCP window pushes
                # back on it — the QoS0 analog of v5 receive-maximum
                delay = overload.read_delay(self)
                if delay > 0:
                    await asyncio.sleep(delay)
            data = await self._read_more(self._missing_bytes(rbuf, varint_decode))
            if not data:
                raise ConnectionClosedError()
            rbuf += data

    @staticmethod
    def _missing_bytes(rbuf: bytearray, varint_decode) -> int:
        """How many more bytes complete the partial packet at the head of
        the buffer (0 = unknown): lets a huge body arrive in one readexactly
        instead of 64 KiB nibbles that would rescan the buffer each time."""
        if len(rbuf) < 2:
            return 0
        try:
            remaining, vb = varint_decode(bytes(rbuf[1:5]))
        except ValueError:
            return 0
        if vb == 0:
            return 0
        return max(0, 1 + vb + remaining - len(rbuf))

    def _decode_body(self, fh: FixedHeader, body: bytes) -> Packet:
        """Decode one framed packet body and run the on_packet_read chain
        (the bulk-path core of read_packet, clients.go:462-520)."""
        self.ops.info.packets_received += 1
        pk = Packet(fixed_header=fh, protocol_version=self.properties.protocol_version)
        decoder = pkts.DECODERS.get(fh.type)
        if decoder is None:
            raise pkts.ERR_NO_VALID_PACKET_AVAILABLE()
        decoder(pk, body)
        if fh.type == pkts.PUBLISH:
            self.ops.info.messages_received += 1
        return self.ops.hooks.on_packet_read(self, pk)

    async def _read_more(self, need: int = 0) -> bytes:
        """One bulk socket read honoring the keepalive deadline. ``need``>0
        waits for exactly that many bytes (completing a known partial
        packet); otherwise reads whatever is available up to 64 KiB."""
        if self.net.reader is None:
            raise ConnectionClosedError()
        if need > 0:
            coro = self.net.reader.readexactly(need)
        else:
            coro = self.net.reader.read(65536)
        if self._deadline is None:
            return await coro
        timeout = self._deadline - time.monotonic()
        if timeout <= 0:
            coro.close()
            raise asyncio.TimeoutError()
        return await asyncio.wait_for(coro, timeout)

    def stop(self, err: Optional[Exception] = None) -> None:
        """Idempotently end the client: close the transport, cancel the
        writer task, record the stop cause and time (clients.go:391-407).

        Task.cancel and transport.close are loop-affine: when another
        shard's loop owns this connection (cross-shard takeover, the
        main loop's drain) the teardown is marshaled to the owner via
        ``call_soon_threadsafe``; the closed flag flips immediately
        either way, so every data-plane gate sees the stop at once."""
        if not self.state.open:
            return
        self.state.open = False
        if err is not None:
            self.state.stop_cause = err
        loop = self.net.loop
        marshaled = False
        if loop is not None and loop.is_running():
            try:
                running = asyncio.get_running_loop()
            except RuntimeError:
                running = None
            if loop is not running:
                try:
                    loop.call_soon_threadsafe(self._stop_teardown)
                    marshaled = True
                except RuntimeError:
                    marshaled = False  # owner loop died first
        if not marshaled:
            self._stop_teardown()
        # brokerlint: ok=R3 session-expiry bookkeeping is wall-clock (persists across restarts)
        self.state.disconnected = int(time.time())

    def _stop_teardown(self) -> None:
        """The loop-affine half of stop(): cancel the writer task and
        close the transport on the loop that owns them."""
        if self._writer_task is not None:
            self._writer_task.cancel()
        if self.net.writer is not None:
            try:
                self.net.writer.close()
            except Exception:  # brokerlint: ok=R4 teardown; the transport is already dead and close() has no one to report to
                pass

    @property
    def stop_cause(self) -> Optional[Exception]:
        return self.state.stop_cause

    @property
    def stop_time(self) -> int:
        return self.state.disconnected

    @property
    def closed(self) -> bool:
        return not self.state.open

    @property
    def is_taken_over(self) -> bool:
        return self.state.is_taken_over

    # -- wire io -----------------------------------------------------------

    async def _read_exactly(self, n: int) -> bytes:
        if self.net.reader is None:
            raise ConnectionClosedError()
        if self._deadline is None:
            return await self.net.reader.readexactly(n)
        timeout = self._deadline - time.monotonic()
        if timeout <= 0:
            raise asyncio.TimeoutError()
        return await asyncio.wait_for(self.net.reader.readexactly(n), timeout)

    async def read_fixed_header(self, fh: FixedHeader) -> None:
        """Read and validate the next packet's fixed header, enforcing the
        maximum packet size [MQTT-3.2.2-15] (clients.go:432-459)."""
        b = await self._read_exactly(1)
        fh.decode(b[0])
        remaining = 0
        multiplier = 0
        bu = 1
        while True:
            eb = (await self._read_exactly(1))[0]
            bu += 1
            remaining |= (eb & 127) << multiplier
            if remaining > pkts.MAX_VARINT:
                raise pkts.ERR_MALFORMED_VARIABLE_BYTE_INTEGER()
            if (eb & 128) == 0:
                break
            multiplier += 7
        fh.remaining = remaining
        caps = self.ops.options.capabilities
        if caps.maximum_packet_size > 0 and remaining + 1 > caps.maximum_packet_size:
            raise ERR_PACKET_TOO_LARGE()  # [MQTT-3.2.2-15]
        self.ops.info.bytes_received += bu

    async def read_packet(self, fh: FixedHeader) -> Packet:
        """Read and decode a packet body, then run the on_packet_read
        modifier chain (clients.go:462-520)."""
        self.ops.info.packets_received += 1
        pk = Packet(fixed_header=fh, protocol_version=self.properties.protocol_version)
        body = await self._read_exactly(fh.remaining) if fh.remaining else b""
        self.ops.info.bytes_received += len(body)
        decoder = pkts.DECODERS.get(fh.type)
        if decoder is None:
            raise pkts.ERR_NO_VALID_PACKET_AVAILABLE()
        decoder(pk, body)
        if fh.type == pkts.PUBLISH:
            self.ops.info.messages_received += 1
        return self.ops.hooks.on_packet_read(self, pk)

    def write_packet(self, pk: Packet) -> None:
        """Encode and write a packet to the client transport
        (clients.go:523-642)."""
        if self.closed:
            raise ConnectionClosedError()
        if self.net.writer is None:
            return
        if pk.expiry > 0:
            expiry = pk.expiry - int(time.time())  # brokerlint: ok=R3 message expiry is an absolute wall-clock stamp
            if expiry < 1:
                expiry = 1
            pk.properties.message_expiry_interval = expiry  # [MQTT-3.3.2-6]

        pk.protocol_version = self.properties.protocol_version
        if pk.mods.max_size == 0:  # NB used to embed client packet sizes in tests
            pk.mods.max_size = self.properties.props.maximum_packet_size

        if (
            self.properties.props.request_problem_info_flag
            and self.properties.props.request_problem_info == 0
        ):
            pk.mods.disallow_problem_info = True  # [MQTT-3.1.2-29]

        if (
            pk.fixed_header.type != pkts.CONNACK
            or self.properties.props.request_response_info == 1
            or self.ops.options.capabilities.compatibilities.always_return_response_info
        ):
            pk.mods.allow_response_info = True  # [MQTT-3.1.2-28]

        pk = self.ops.hooks.on_packet_encode(self, pk)

        buf = get_buffer()
        try:
            pkts.ENCODERS[pk.fixed_header.type](pk, buf)
            if pk.mods.max_size > 0 and len(buf) > pk.mods.max_size:
                raise ERR_PACKET_TOO_LARGE()  # [MQTT-3.1.2-24] [MQTT-3.1.2-25]
            data = bytes(buf)
        finally:
            put_buffer(buf)

        self.net.writer.write(data)

        self.ops.info.bytes_sent += len(data)
        self.ops.info.packets_sent += 1
        st = self.state
        st.out_bytes += len(data)
        st.out_writes += 1
        tele = getattr(self.ops, "telemetry", None)
        if tele is not None:
            tele.outbound_bytes.inc(len(data))
            tele.outbound_writes.inc()
        if pk.fixed_header.type == pkts.PUBLISH:
            self.ops.info.messages_sent += 1
            if tele is not None and not pk.topic_name.startswith("$SYS"):
                # a per-subscriber encode: the amplification numerator
                # (ROADMAP item 3's encode-once rewrite drives this to
                # ~1 per inbound publish). $SYS housekeeping fan-out is
                # excluded — it recurs every interval with no inbound
                # publish behind it and would inflate the ratio without
                # bound; retained deliveries and QoS retransmits DO
                # count (they are real write-path encode work).
                tele.publish_encodes.inc()
                tele.fanout_deliveries.inc()
        self.ops.hooks.on_packet_sent(self, pk, data)


class Clients(LockedMap[str, Client]):
    """Clients known by the broker, keyed on client id (clients.go:36-100).

    Lock-plane adopted (mqtt_tpu.utils.locked): every fan-out delivery
    does a ``get`` per subscriber, so this is the hottest single lock in
    the broker."""

    def __init__(self) -> None:
        super().__init__(name="clients")

    def add_client(self, cl: Client) -> None:
        self.add(cl.id, cl)

    def get_by_listener(self, id_: str) -> list[Client]:
        with self._lock:
            return [
                c for c in self.internal.values() if c.net.listener == id_ and not c.closed
            ]
