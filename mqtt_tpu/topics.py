"""Topic trie: subscriptions, shared subscriptions, inline subscriptions,
retained messages, wildcard match walks, and topic aliases.

Behavioral parity with reference ``topics.go`` — this host implementation is
the bit-identical oracle (and fallback path) for the device matcher in
``mqtt_tpu.ops``. The corner cases that define "bit-identical":

- ``zen/#`` matches ``zen`` (spec 4.7.1.2), via the child-``#`` gather at the
  terminal level (topics.go:612-616).
- ``a/b`` must NOT match ``a/b/c`` (no prefix inheritance).
- ``$``-prefixed topics are not matched by TOP-LEVEL ``+``/``#`` filters
  [MQTT-4.7.1-1/2]; the check is on the subscription's original filter string
  (topics.go:637).
- Empty levels are real levels: ``/a/`` is ``["", "a", ""]``.
- ``#`` is gathered at every walk level; ``+`` forks the frontier.
- Shared subscriptions (``$SHARE/<group>/<filter>``) root their subtree at
  depth 2 (topics.go:407-411).

Quirk replicated on purpose (topics.go:615): in the terminal child-``#``
branch, the reference gathers the *parent* particle's inline subscriptions
again instead of the wild child's — so an inline subscription on ``a/#``
does not match topic ``a``.
"""

from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Callable, Optional

from .packets import Packet, PacketStore, Subscription
from .utils import LockedMap

SHARE_PREFIX = "$SHARE"  # prefix indicating a shared-subscription filter
SYS_PREFIX = "$SYS"  # prefix indicating a system info topic

# -- tenant namespaces (mqtt_tpu.tenancy) -----------------------------------
#
# A tenant's topic space is a structurally enforced namespace: every key
# the broker stores or matches for a tenant client — trie filters,
# retained topics, $SHARE inner filters, cluster interest summaries —
# is prefixed with one extra level ``NS_CHAR + tenant`` before it
# reaches this module. NS_CHAR is U+0000, which no client-supplied
# topic or filter may contain ([MQTT-4.7.3-2], enforced by
# ``is_valid_filter``), so a scoped key can never be forged from the
# wire and two tenants' identical topic strings land on disjoint trie
# subtrees. Cross-tenant delivery is therefore impossible by
# construction; the only cross-namespace reach a wildcard has is a
# GLOBAL (untenanted) top-level ``+``/``#`` filter, which the gather
# guards below exclude from namespace subtrees the same way the
# [MQTT-4.7.1-1/2] rule excludes ``$``-topics.

NS_CHAR = "\x00"


def ns_scope_topic(tenant: str, topic: str) -> str:
    """Prefix a tenant-local topic NAME into its namespace."""
    return NS_CHAR + tenant + "/" + topic


def ns_scope_filter(tenant: str, filter: str) -> str:
    """Prefix a tenant-local FILTER into its namespace. A shared
    subscription scopes its inner filter (the group is a delivery
    policy, not an address): ``$SHARE/g/f`` -> ``$SHARE/g/<ns>/f`` —
    the trie roots shared subtrees at depth 2, so two tenants' identical
    groups+filters still land on disjoint particles."""
    if is_shared_filter(filter):
        parts = filter.split("/", 2)
        inner = parts[2] if len(parts) > 2 else ""
        return f"{parts[0]}/{parts[1]}/{NS_CHAR}{tenant}/{inner}"
    return NS_CHAR + tenant + "/" + filter


def ns_tenant(key: str) -> str:
    """The tenant a scoped key belongs to ("" for global keys)."""
    if key[:1] != NS_CHAR:
        return ""
    i = key.find("/")
    return key[1:i] if i > 0 else key[1:]


def ns_local(key: str) -> str:
    """Strip the namespace level off a scoped key (identity for global
    keys) — the tenant-local topic/filter the client sees on the wire."""
    if key[:1] != NS_CHAR:
        return key
    i = key.find("/")
    return key[i + 1 :] if i >= 0 else ""


def _ns_local0(key: str) -> str:
    """First character of the tenant-local portion of a (possibly
    scoped) key — the character the [MQTT-4.7.1-1/2] ``$``-rules apply
    to inside a namespace."""
    if key[:1] != NS_CHAR:
        return key[:1]
    i = key.find("/")
    return key[i + 1 : i + 2] if i >= 0 else ""

# -- MQTT+ predicate suffixes (mqtt_tpu.predicates) -------------------------
#
# An MQTT+ subscription rides a standard SUBSCRIBE filter with a payload
# predicate appended: ``sensors/+/temp$GT{25.0}``. The trie only ever sees
# the BASE filter — the suffix is split off at SUBSCRIBE time so the walk,
# retained matching, and $SHARE parsing are byte-identical to a plain
# subscription. The split is defined here (string surgery is the topic
# layer's business); compilation/evaluation live in mqtt_tpu.predicates.

#: ops that compare a numeric payload feature against a threshold
PREDICATE_NUMERIC_OPS = ("GT", "GTE", "LT", "LTE", "EQ", "NE")
#: ops that aggregate a numeric payload feature over a message window
PREDICATE_AGG_OPS = ("MEAN", "MAX", "MIN")
#: every recognized simple predicate op (CONTAINS and EQS are the
#: payload-bytes/string ops; compounds AND/OR are parsed separately)
PREDICATE_OPS = (
    PREDICATE_NUMERIC_OPS + ("CONTAINS", "EQS") + PREDICATE_AGG_OPS
)
#: compound ops combining SIMPLE predicates: ``$AND{$GT{t:20}$LT{t:30}}``
PREDICATE_COMPOUND_OPS = ("AND", "OR")

_PREDICATE_RE = re.compile(
    r"^(?P<base>.*?)\$(?P<op>" + "|".join(PREDICATE_OPS) + r")\{(?P<arg>[^{}]*)\}$",
    re.DOTALL,
)
# one SIMPLE predicate token, anchored at the string start — the unit
# the compound-argument scanner consumes
_PREDICATE_TOKEN_RE = re.compile(
    r"^\$(?P<op>" + "|".join(PREDICATE_OPS) + r")\{(?P<arg>[^{}]*)\}",
    re.DOTALL,
)
_COMPOUND_RE = re.compile(
    r"^(?P<base>.*?)\$(?P<op>AND|OR)\{(?P<arg>.*)\}$", re.DOTALL
)


def _predicate_arg_ok(op: str, arg: str) -> bool:
    """Validate a predicate argument for ``op`` — an invalid argument means
    the whole token is NOT a predicate (the filter stays literal, so the
    extension can never reject a filter plain MQTT would accept)."""
    if op == "CONTAINS":
        return len(arg) > 0
    if op == "EQS":
        # string equality ``field:literal``; an empty field means "the
        # whole payload as the string"
        _field, sep, _literal = arg.partition(":")
        return bool(sep)
    field_part, _, num = arg.rpartition(":")
    if op in PREDICATE_AGG_OPS:
        try:
            return int(num) >= 1
        except ValueError:
            return False
    try:
        value = float(num)
    except ValueError:
        return False
    return value == value  # reject an explicit nan threshold
    # (field_part may be empty: "whole payload as the number")


def split_predicate_tokens(arg: str) -> tuple:
    """Scan a compound argument into its simple ``$OP{...}`` member
    tokens. Returns the token tuple, or () when the argument is not a
    well-formed run of >= 2 valid simple predicates (compounds of one
    are just that predicate; spell it plainly)."""
    tokens = []
    rest = arg
    while rest:
        m = _PREDICATE_TOKEN_RE.match(rest)
        if m is None or not _predicate_arg_ok(m.group("op"), m.group("arg")):
            return ()
        if m.group("op") in PREDICATE_AGG_OPS:
            # stateful windows have no boolean verdict to combine
            return ()
        tokens.append(m.group(0))
        rest = rest[len(m.group(0)):]
    return tuple(tokens) if len(tokens) >= 2 else ()


def split_predicate_suffix(filter: str) -> tuple[str, str]:
    """Split a trailing MQTT+ predicate off a subscription filter.

    Returns ``(base_filter, suffix)`` where ``suffix`` is the literal
    ``$OP{arg}`` text ("" when the filter carries no well-formed
    predicate). Only a syntactically valid suffix is split — anything
    else is a literal filter, so pre-MQTT+ behavior is bit-identical. A
    bare predicate (``$CONTAINS{alarm}``) means "every topic": the base
    widens to ``#``.

    Compounds (``$AND{...}``/``$OR{...}`` over simple predicates) are
    matched FIRST — their argument contains nested braces, which the
    simple-token grammar deliberately excludes."""
    m = _COMPOUND_RE.match(filter)
    if m is not None and split_predicate_tokens(m.group("arg")):
        base = m.group("base") or "#"
        return base, filter[len(m.group("base")):]
    m = _PREDICATE_RE.match(filter)
    if m is None:
        return filter, ""
    if not _predicate_arg_ok(m.group("op"), m.group("arg")):
        return filter, ""
    base = m.group("base")
    if base == "":
        base = "#"  # payload-only subscription: predicate over all topics
    return base, filter[len(m.group("base")):]


def summary_base(filter: str) -> str:
    """The filter as PUBLISHES match it — the key the mesh interest
    summaries index (mqtt_tpu.mesh_topology): a ``$SHARE/<group>/...``
    subscription strips to the inner filter (publishes arrive on the
    inner topic space, the group is a delivery policy), and a trailing
    MQTT+ predicate strips to its base filter (the predicate gates
    delivery at the subscriber's worker, not routability — a remote
    ``sensors/+/temp$GT{25}`` subscriber still needs the publish
    forwarded before it can evaluate anything)."""
    if is_shared_filter(filter):
        parts = filter.split("/", 2)
        filter = parts[2] if len(parts) > 2 else ""
    base, _suffix = split_predicate_suffix(filter)
    return base


@dataclass(frozen=True)
class Mutation:
    """One subscription mutation, delivered to trie observers.

    Device-index consumers (``mqtt_tpu.ops.delta``, ``mqtt_tpu.parallel``)
    use it to maintain delta overlays and per-shard subscription replicas
    without re-walking the trie.
    """

    filter: str
    kind: str  # "sub" (client/shared subscription) or "inline"
    op: str  # "add" or "del"
    client: str = ""  # client id for kind="sub"; "" for inline
    subscription: Optional[object] = None  # the added Subscription / InlineSubscription
    identifier: int = 0  # inline subscription identifier (kind="inline")


def isolate_particle(filter: str, d: int) -> tuple[str, bool]:
    """Extract the topic level at depth ``d`` and whether more levels follow.

    Depths past the last level clamp to the last level (reference
    topics.go:679-698) — the retained-message ``#`` walk relies on this.
    """
    parts = filter.split("/")
    if d >= len(parts):
        return parts[-1], False
    return parts[d], d < len(parts) - 1


def is_shared_filter(filter: str) -> bool:
    prefix, _ = isolate_particle(filter, 0)
    return prefix.upper() == SHARE_PREFIX


def is_valid_filter(filter: str, for_publish: bool = False) -> bool:
    """Validate a topic filter (or topic name when ``for_publish``);
    reference topics.go:707-745.

    COUPLING NOTE: ``Server.try_fast_publish`` (server.py) short-circuits
    QoS0 v4 publishes using raw-byte gates that must remain a strict
    SUPERSET of this function's ``for_publish`` rejections (it defers all
    ``$``-prefixed, wildcard, NUL, and empty topics to the decode path).
    If a new publish-topic rejection is added here whose topics would
    still pass those byte gates, extend the fast-path gates too."""
    if not for_publish and len(filter) == 0:
        return False  # [MQTT-4.7.3-1]
    if NS_CHAR in filter:
        # [MQTT-4.7.3-2]: topic names and filters must not include
        # U+0000 — and NS_CHAR doubles as the tenant-namespace marker
        # (mqtt_tpu.tenancy), so a wire topic can never alias into (or
        # out of) another tenant's scoped key space
        return False
    if for_publish:
        # 4.7.2: the server prevents clients using $SYS topic names to
        # exchange messages with other clients.
        if len(filter) >= len(SYS_PREFIX) and filter[: len(SYS_PREFIX)].upper() == SYS_PREFIX:
            return False
        if "+" in filter or "#" in filter:
            return False  # [MQTT-3.3.2-2]
    wildhash = filter.find("#")
    if wildhash >= 0 and wildhash != len(filter) - 1:
        return False  # [MQTT-4.7.1-2]
    prefix, has_next = isolate_particle(filter, 0)
    if prefix.upper() == SHARE_PREFIX:
        if not has_next:
            return False  # [MQTT-4.8.2-1]
        group, has_next = isolate_particle(filter, 1)
        if not has_next:
            return False  # [MQTT-4.8.2-1]
        if "+" in group or "#" in group:
            return False  # [MQTT-4.8.2-2]
    return True


# -- topic aliases ---------------------------------------------------------


class InboundTopicAliases:
    """Aliases received from the client (topics.go:43-64)."""

    def __init__(self, maximum: int) -> None:
        self.maximum = maximum
        self.internal: dict[int, str] = {}
        self._lock = threading.Lock()

    def set(self, id_: int, topic: str) -> str:
        with self._lock:
            if self.maximum == 0:
                return topic
            if topic == "" and id_ in self.internal:
                return self.internal[id_]
            self.internal[id_] = topic
            return topic


class OutboundTopicAliases:
    """Aliases assigned by the broker for messages to the client; ids are
    cursor-allocated 1..maximum (topics.go:67-105)."""

    def __init__(self, maximum: int) -> None:
        self.maximum = maximum
        self.internal: dict[str, int] = {}
        self.cursor = 0
        self._lock = threading.Lock()

    def set(self, topic: str) -> tuple[int, bool]:
        """Returns ``(alias, already_existed)``; ``(0, False)`` when aliases
        are disabled or exhausted."""
        with self._lock:
            if self.maximum == 0:
                return 0, False
            if topic in self.internal:
                return self.internal[topic], True
            if self.cursor + 1 > self.maximum:
                return 0, False
            self.cursor += 1
            self.internal[topic] = self.cursor
            return self.cursor, False


class TopicAliases:
    """Inbound and outbound alias registries for one client (topics.go:21)."""

    def __init__(self, topic_alias_maximum: int) -> None:
        self.inbound = InboundTopicAliases(topic_alias_maximum)
        self.outbound = OutboundTopicAliases(topic_alias_maximum)


# -- subscription containers -----------------------------------------------


class Subscriptions(LockedMap[str, Subscription]):
    """A map of subscriptions, keyed by client id (trie state) or by filter
    (client state) (topics.go:249-301)."""


class SharedSubscriptions:
    """Shared subscriptions for one filter: group -> client id -> sub
    (topics.go:109-187)."""

    def __init__(self) -> None:
        self.internal: dict[str, dict[str, Subscription]] = {}
        self._lock = threading.RLock()

    def add(self, group: str, id_: str, val: Subscription) -> None:
        with self._lock:
            self.internal.setdefault(group, {})[id_] = val

    def delete(self, group: str, id_: str) -> None:
        with self._lock:
            subs = self.internal.get(group)
            if subs is None:
                return
            subs.pop(id_, None)
            if not subs:
                del self.internal[group]

    def get(self, group: str, id_: str) -> Optional[Subscription]:
        with self._lock:
            return self.internal.get(group, {}).get(id_)

    def get_all(self) -> dict[str, dict[str, Subscription]]:
        with self._lock:
            return {group: dict(subs) for group, subs in self.internal.items()}

    def group_len(self) -> int:
        with self._lock:
            return len(self.internal)

    def __len__(self) -> int:
        with self._lock:
            return sum(len(subs) for subs in self.internal.values())


# Signature of an inline (in-process) subscription callback: receives the
# local client, the matched subscription, and the publish packet.
InlineSubFn = Callable[["object", Subscription, Packet], None]


@dataclass(slots=True)
class InlineSubscription(Subscription):
    """An in-process subscription: a Subscription plus a handler callback,
    keyed on the subscription identifier (topics.go:306-309)."""

    handler: InlineSubFn | None = None


class InlineSubscriptions(LockedMap[int, "InlineSubscription"]):
    """Inline subscriptions for one particle, keyed on identifier
    (topics.go:195-246)."""

    def add_inline(self, val: "InlineSubscription") -> None:
        self.add(val.identifier, val)


# Aggregated subscriptions for one client, keyed on filter.
ClientSubscriptions = dict


class Subscribers:
    """The result set of a subscriber scan (topics.go:312-347).

    ``__slots__`` keeps the result object dict-free so the C materializer
    (native/accelmod.c) can build one per matched topic at tp_alloc + four
    dict stores."""

    __slots__ = ("shared", "shared_selected", "subscriptions", "inline_subscriptions")

    def __init__(self) -> None:
        self.shared: dict[str, dict[str, Subscription]] = {}
        self.shared_selected: dict[str, Subscription] = {}
        self.subscriptions: dict[str, Subscription] = {}
        self.inline_subscriptions: dict[int, InlineSubscription] = {}

    def select_shared(self) -> None:
        """Pick one subscriber per shared group. The reference picks the
        first map-iteration entry (nondeterministic in Go, insertion-ordered
        here); selection stays host-side and pluggable via the
        on_select_subscribers hook."""
        self.shared_selected = {}
        for subs in self.shared.values():
            for client, sub in subs.items():
                cls = self.shared_selected.get(client, sub)
                self.shared_selected[client] = cls.merge(sub)
                break

    def merge_shared_selected(self) -> None:
        """Fold selected shared subscribers into the non-shared set so no
        client receives duplicates (topics.go:338-347)."""
        for client, sub in self.shared_selected.items():
            cls = self.subscriptions.get(client, sub)
            self.subscriptions[client] = cls.merge(sub)


# -- the trie --------------------------------------------------------------


class _Particle:
    """One trie node (reference 'particle', topics.go:748-769)."""

    __slots__ = (
        "key",
        "parent",
        "particles",
        "subscriptions",
        "shared",
        "inline_subscriptions",
        "retain_path",
    )

    def __init__(self, key: str, parent: "_Particle | None") -> None:
        self.key = key
        self.parent = parent
        self.particles: dict[str, _Particle] = {}
        self.subscriptions = Subscriptions()
        self.shared = SharedSubscriptions()
        self.inline_subscriptions = InlineSubscriptions()
        self.retain_path = ""


class TopicsIndex:
    """A trie of topic filters with subscriber scan and retained-message
    walks (reference TopicsIndex, topics.go:350+)."""

    def __init__(self, lock_name: str = "topics_trie") -> None:
        # lock-plane adoption (mqtt_tpu.utils.locked): every host-walk
        # fallback, subscribe/unsubscribe, and retained-store mutation
        # serializes here — the prime suspect for ROADMAP item 3's
        # per-client collapse, now measured. The cluster's remote-
        # interest index passes its own name so the two tries' numbers
        # stay separable.
        from .utils.locked import InstrumentedLock

        self.retained = PacketStore(name="retained")
        self.root = _Particle("", None)
        self._lock = InstrumentedLock(lock_name, rlock=True)
        # bumped on every subscription mutation; device indexes (mqtt_tpu.ops)
        # compare against it to detect staleness
        self.version = 0
        # mutation observers: called with a Mutation under the trie lock,
        # after the version bump. The delta-staged device matcher
        # (mqtt_tpu.ops.delta) uses this to route affected topics to the
        # host walk while a stale device snapshot keeps serving everything
        # else; the mesh-sharded matcher (mqtt_tpu.parallel) additionally
        # applies the mutation to the owning shard's replica trie.
        self._observers: list[Callable[[Mutation], None]] = []

    def add_observer(self, fn: Callable[[Mutation], None]) -> None:
        """Register a subscription-mutation observer (delta stream consumer)."""
        with self._lock:
            self._observers.append(fn)

    def remove_observer(self, fn: Callable[[Mutation], None]) -> None:
        with self._lock:
            if fn in self._observers:
                self._observers.remove(fn)

    def _notify(self, mutation: Mutation) -> None:
        for fn in self._observers:
            # brokerlint: ok=R5 intentional in-lock delivery: the delta overlay must observe the mutation atomically with the version bump (a gap would let a stale device snapshot serve the mutated filter); the lock is an RLock, so same-thread re-registration cannot deadlock, and observers are contract-bound to be O(1) appends
            fn(mutation)

    # -- mutation ----------------------------------------------------------

    def subscribe(self, client: str, subscription: Subscription) -> bool:
        """Add a subscription; returns True if it was new (topics.go:401-419).
        ``$SHARE/<group>/<filter>`` roots the subtree at depth 2."""
        with self._lock:
            self.version += 1
            prefix, _ = isolate_particle(subscription.filter, 0)
            if prefix.upper() == SHARE_PREFIX:
                group, _ = isolate_particle(subscription.filter, 1)
                n = self._set(subscription.filter, 2)
                existed = n.shared.get(group, client) is not None
                n.shared.add(group, client, subscription)
            else:
                n = self._set(subscription.filter, 0)
                existed = n.subscriptions.get(client) is not None
                n.subscriptions.add(client, subscription)
            self._notify(
                Mutation(subscription.filter, "sub", "add", client, subscription)
            )
            return not existed

    def subscribe_bulk(self, entries: list[tuple[str, Subscription]]) -> int:
        """Batched :meth:`subscribe`: one lock acquisition inserts a whole
        batch of ``(client, subscription)`` pairs — the restart
        re-registration path (ISSUE 16), where a million persisted
        subscriptions must not pay a lock round-trip (and an observer
        wake) each. Returns how many were NEW. Per-entry semantics are
        identical to :meth:`subscribe`: the version bumps and the delta
        observers fire for every entry, so device-matcher overlays see
        the same mutation stream either way."""
        added = 0
        with self._lock:
            for client, subscription in entries:
                self.version += 1
                prefix, _ = isolate_particle(subscription.filter, 0)
                if prefix.upper() == SHARE_PREFIX:
                    group, _ = isolate_particle(subscription.filter, 1)
                    n = self._set(subscription.filter, 2)
                    existed = n.shared.get(group, client) is not None
                    n.shared.add(group, client, subscription)
                else:
                    n = self._set(subscription.filter, 0)
                    existed = n.subscriptions.get(client) is not None
                    n.subscriptions.add(client, subscription)
                self._notify(
                    Mutation(subscription.filter, "sub", "add", client, subscription)
                )
                if not existed:
                    added += 1
        return added

    def unsubscribe(self, filter: str, client: str) -> bool:
        """Remove a client's subscription; returns True if it existed
        (topics.go:423-448)."""
        with self._lock:
            d = 0
            prefix, _ = isolate_particle(filter, 0)
            share_sub = prefix.upper() == SHARE_PREFIX
            if share_sub:
                d = 2
            particle = self._seek(filter, d)
            if particle is None:
                return False
            self.version += 1
            if share_sub:
                group, _ = isolate_particle(filter, 1)
                particle.shared.delete(group, client)
            else:
                particle.subscriptions.delete(client)
            self._trim(particle)
            self._notify(Mutation(filter, "sub", "del", client))
            return True

    def inline_subscribe(self, subscription: InlineSubscription) -> bool:
        """Add an in-process subscription keyed on its identifier; returns
        True if new (topics.go:368-378)."""
        with self._lock:
            self.version += 1
            n = self._set(subscription.filter, 0)
            existed = n.inline_subscriptions.get(subscription.identifier) is not None
            n.inline_subscriptions.add_inline(subscription)
            self._notify(
                Mutation(
                    subscription.filter,
                    "inline",
                    "add",
                    subscription=subscription,
                    identifier=subscription.identifier,
                )
            )
            return not existed

    def inline_subscription(self, id_: int, filter: str) -> Optional[InlineSubscription]:
        """The stored inline subscription at (identifier, filter), or
        None. The predicate plane consults it on replace/unsubscribe so
        rule refcounts track the subscription actually stored."""
        with self._lock:
            particle = self._seek(filter, 0)
            if particle is None:
                return None
            return particle.inline_subscriptions.get(id_)

    def inline_unsubscribe(self, id_: int, filter: str) -> bool:
        with self._lock:
            particle = self._seek(filter, 0)
            if particle is None:
                return False
            self.version += 1
            particle.inline_subscriptions.delete(id_)
            if len(particle.inline_subscriptions) == 0:
                self._trim(particle)
            self._notify(Mutation(filter, "inline", "del", identifier=id_))
            return True

    def retain_message(self, pk: Packet) -> int:
        """Store/clear the retained message for a topic. Returns 1 when a
        message was retained, -1 when an existing one was cleared, 0 for a
        clear with nothing to clear (topics.go:453-476)."""
        with self._lock:
            n = self._set(pk.topic_name, 0)
            if pk.payload:
                n.retain_path = pk.topic_name
                self.retained.add(pk.topic_name, pk)
                return 1
            out = 0
            pke = self.retained.get(pk.topic_name)
            if pke is not None and pke.payload and pke.fixed_header.retain:
                out = -1
            n.retain_path = ""
            self.retained.delete(pk.topic_name)  # [MQTT-3.3.1-6] [MQTT-3.3.1-7]
            self._trim(n)
            return out

    def retain_bulk(self, packets: list[Packet]) -> int:
        """Batched :meth:`retain_message` for restart restore: one lock
        acquisition re-seats a whole batch of retained messages. Returns
        how many were retained (clears count like the scalar path but are
        not summed). Per-packet semantics match :meth:`retain_message`."""
        retained = 0
        with self._lock:
            for pk in packets:
                n = self._set(pk.topic_name, 0)
                if pk.payload:
                    n.retain_path = pk.topic_name
                    self.retained.add(pk.topic_name, pk)
                    retained += 1
                else:
                    n.retain_path = ""
                    self.retained.delete(pk.topic_name)
                    self._trim(n)
        return retained

    def _set(self, topic: str, d: int) -> _Particle:
        """Create (or find) the particle at a topic address (topics.go:479)."""
        parts = topic.split("/")
        n = self.root
        for key in parts[d:] if d < len(parts) else [parts[-1]]:
            p = n.particles.get(key)
            if p is None:
                p = _Particle(key, n)
                n.particles[key] = p
            n = p
        return n

    def _seek(self, filter: str, d: int) -> _Particle | None:
        parts = filter.split("/")
        n = self.root
        for key in parts[d:] if d < len(parts) else [parts[-1]]:
            n = n.particles.get(key)
            if n is None:
                return None
        return n

    def _trim(self, n: _Particle) -> None:
        """Prune empty particles up the parent chain (topics.go:516-522)."""
        while (
            n.parent is not None
            and n.retain_path == ""
            and len(n.particles) + len(n.subscriptions) + len(n.shared) + len(n.inline_subscriptions) == 0
        ):
            key = n.key
            n = n.parent
            n.particles.pop(key, None)

    # -- scans -------------------------------------------------------------

    def subscribers(self, topic: str) -> Subscribers:
        """All clients subscribed to filters matching ``topic`` — THE hot
        walk the TPU matcher accelerates (topics.go:583-628). Iterative
        frontier walk (explicit stack) so deep topics cannot overflow the
        interpreter's recursion limit."""
        subs = Subscribers()
        if len(topic) == 0:
            return subs
        parts = topic.split("/")
        last = len(parts) - 1
        stack: list[tuple[_Particle, int]] = [(self.root, 0)]
        while stack:
            n, d = stack.pop()
            key = parts[d] if d < len(parts) else parts[-1]
            has_next = d < last
            for part_key in (key, "+"):
                particle = n.particles.get(part_key)
                if particle is not None:  # [MQTT-3.3.2-3]
                    if has_next:
                        stack.append((particle, d + 1))
                    else:
                        self._gather_subscriptions(topic, particle, subs)
                        self._gather_shared(topic, particle, subs)
                        self._gather_inline(topic, particle, subs)
                        wild = particle.particles.get("#")
                        if wild is not None and part_key != "+":
                            # filter/# matches filter itself, per spec 4.7.1.2
                            self._gather_subscriptions(topic, wild, subs)
                            self._gather_shared(topic, wild, subs)
                            # reference quirk (topics.go:615): gathers the
                            # parent particle's inline subs, not the wild
                            # child's
                            self._gather_inline(topic, particle, subs)
            particle = n.particles.get("#")
            if particle is not None:
                self._gather_subscriptions(topic, particle, subs)
                self._gather_shared(topic, particle, subs)
                self._gather_inline(topic, particle, subs)
        return subs

    @staticmethod
    def _ns_excluded(topic: str, filter: str) -> bool:
        """The namespace gather guards (mqtt_tpu.tenancy): a GLOBAL
        top-level-wildcard filter never reaches into a tenant namespace,
        and inside a namespace the [MQTT-4.7.1-1/2] ``$``-rule applies
        to the tenant-LOCAL first level. Zero-cost for global topics
        (one char compare)."""
        if topic[:1] != NS_CHAR or not filter:
            return False
        if filter[0] in "+#":
            return True  # global wildcard vs scoped topic
        return _ns_local0(topic) == "$" and _ns_local0(filter) in "+#"

    def _gather_subscriptions(self, topic: str, particle: _Particle, subs: Subscribers) -> None:
        """Merge a particle's subscriptions into the result set, excluding
        top-level-wildcard filters for $-topics [MQTT-4.7.1-1/2]
        (topics.go:631-648)."""
        for client, sub in particle.subscriptions.get_all().items():
            if sub.filter and topic[0] == "$" and sub.filter[0] in "+#":
                continue
            if self._ns_excluded(topic, sub.filter):
                continue
            cls = subs.subscriptions.get(client, sub)
            subs.subscriptions[client] = cls.merge(sub)

    def _gather_shared(self, topic: str, particle: _Particle, subs: Subscribers) -> None:
        for shares in particle.shared.get_all().values():
            for client, sub in shares.items():
                if topic[:1] == NS_CHAR:
                    # the namespace guard applies to the INNER filter
                    # (publishes match the inner topic space)
                    parts = sub.filter.split("/", 2)
                    inner = parts[2] if len(parts) > 2 else ""
                    if self._ns_excluded(topic, inner):
                        continue
                subs.shared.setdefault(sub.filter, {})[client] = sub

    def _gather_inline(self, topic: str, particle: _Particle, subs: Subscribers) -> None:
        if topic[:1] == NS_CHAR:
            for iid, isub in particle.inline_subscriptions.get_all().items():
                if not self._ns_excluded(topic, isub.filter):
                    subs.inline_subscriptions[iid] = isub
            return
        subs.inline_subscriptions.update(particle.inline_subscriptions.get_all())

    def messages(self, filter: str) -> list[Packet]:
        """All retained messages matching ``filter`` (topics.go:525-579).
        Iterative walk — see :meth:`subscribers`."""
        pks: list[Packet] = []
        if len(filter) == 0 or len(self.retained) == 0:
            return pks
        if "#" not in filter and "+" not in filter:
            pk = self.retained.get(filter)
            if pk is not None:
                pks.append(pk)
            return pks
        parts = filter.split("/")
        last = len(parts) - 1
        # a namespace-scoped filter's local top level sits at depth 1;
        # the $SYS wildcard exclusion applies there (mqtt_tpu.tenancy)
        sys_d = 1 if parts[0][:1] == NS_CHAR else 0
        stack: list[tuple[_Particle, int]] = [(self.root, 0)]
        while stack:
            n, d = stack.pop()
            key = parts[d] if d < len(parts) else parts[-1]
            has_next = d < last
            if key in ("+", "#"):
                for adjacent in list(n.particles.values()):
                    if d == sys_d and adjacent.key == SYS_PREFIX:
                        continue
                    if d == 0 and adjacent.key[:1] == NS_CHAR:
                        # a GLOBAL wildcard never descends into a
                        # tenant namespace (scoped filters address it
                        # by its literal level instead)
                        continue
                    if not has_next and adjacent.retain_path:
                        pk = self.retained.get(adjacent.retain_path)
                        if pk is not None:
                            pks.append(pk)
                    if has_next or key == "#":
                        stack.append((adjacent, d + 1))
            else:
                particle = n.particles.get(key)
                if particle is not None:
                    if has_next:
                        stack.append((particle, d + 1))
                    elif particle.retain_path:
                        pk = self.retained.get(particle.retain_path)
                        if pk is not None:
                            pks.append(pk)
        return pks
