"""Host-side topic tokenization and hashing for the device matcher.

Strings never reach the TPU: topic levels are tokenized and hashed on the
host (SURVEY.md §7 hard-part #3). Each token gets two independent 32-bit
hashes — hash1 keys the sorted literal-edge binary search, hash2 verifies
the hit — so a false device match requires a simultaneous 64-bit collision
(~2^-64 per lookup). The builder additionally guarantees hash1 uniqueness
within each node's edge list (see csr.py), keeping the search well-defined.

The batch path delegates to the native core (mqtt_tpu/native) when a C
toolchain is available; ``tokenize_topics_py`` is the always-available
pure-Python reference, and tests/test_native.py enforces that the two are
bit-identical.
"""

from __future__ import annotations

import hashlib
from functools import lru_cache

import numpy as np


@lru_cache(maxsize=1 << 20)
def hash_token(token: str, salt: int = 0) -> tuple[int, int]:
    """Two independent u32 hashes of one topic level token."""
    d = hashlib.blake2b(
        token.encode("utf-8"), digest_size=8, salt=salt.to_bytes(8, "little")
    ).digest()
    return int.from_bytes(d[:4], "little"), int.from_bytes(d[4:], "little")


def tokenize_topics_py(
    topics: list[str], max_levels: int, salt: int = 0
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pure-Python reference tokenizer (see ``tokenize_topics``)."""
    b = len(topics)
    tok1 = np.zeros((b, max_levels), dtype=np.uint32)
    tok2 = np.zeros((b, max_levels), dtype=np.uint32)
    lengths = np.zeros(b, dtype=np.int32)
    is_dollar = np.zeros(b, dtype=bool)
    overflow = np.zeros(b, dtype=bool)
    for i, topic in enumerate(topics):
        parts = topic.split("/")
        n = len(parts)
        if n > max_levels:
            overflow[i] = True
            n = max_levels
        lengths[i] = n
        is_dollar[i] = topic.startswith("$")
        for d in range(n):
            h1, h2 = hash_token(parts[d], salt)
            tok1[i, d] = h1
            tok2[i, d] = h2
    return tok1, tok2, lengths, is_dollar, overflow


def tokenize_topics(
    topics: list[str], max_levels: int, salt: int = 0
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Tokenize and hash a batch of PUBLISH topics.

    Returns ``(tok1[B,L], tok2[B,L], lengths[B], is_dollar[B], overflow[B])``
    — hashes padded with zeros past each topic's level count; ``overflow``
    marks topics with more than ``max_levels`` levels (routed to the host
    trie fallback).
    """
    from ..native import tokenize_topics_native

    native = tokenize_topics_native(topics, max_levels, salt)
    if native is not None:
        return native
    return tokenize_topics_py(topics, max_levels, salt)
