"""Per-device observability plane: HBM gauges, a compile-event ledger,
and shard-skew instruments for the multi-chip frontier (ISSUE 18).

Three cooperating pieces, all host-side (no new kernels):

* ``CompileLedger`` / ``KernelWatch`` — every jitted entry point funnels
  through ``_LazyJit`` (ops/flat.py) or the sharded step caches
  (parallel/sharded.py); both wrap the built callable in a
  ``KernelWatch`` that detects the FIRST call per (shapes, dtypes,
  statics) signature and notes its wall duration into the module-level
  ``LEDGER``. jax.jit compiles synchronously on that first call, so the
  note is a faithful compile event without touching XLA internals —
  and a *steady-state* note is exactly the PR 11 capacity-hysteresis
  incident (one recompile per step, a silent 3x e2e loss), now a
  watched quantity: ``mqtt_tpu_matcher_recompiles_total{kernel}`` plus
  a compile-seconds histogram, with a bounded event ring carrying
  kernel/shape attribution for test failure messages.

* ``DeviceStatsPlane`` — per-device HBM gauges (live/peak/limit via
  ``jax.Device.memory_stats()``; backends without it report the -1
  sentinel on /metrics and ``null`` in JSON), the ``device_skew_ratio``
  gauge and per-tile hit/fill families (fed by ``ShardedTpuMatcher``),
  and the JSON snapshot behind ``GET /devices``, the
  ``$SYS/broker/devices/#`` tree, and the ``devices_*.json`` trigger
  dump sibling. Per-device duty/overlap/idle-gap windows live in
  ``tracing.DeviceProfiler`` (per-device generalization); the plane
  only *reads* them for the snapshot.

The ledger lock is ``device_stats`` (LOCK_NAMES/LOCK_ORDER blessed); it
is a leaf — registry child registration happens OUTSIDE it so no
device_stats -> metrics_registry edge exists.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Optional

import numpy as np

from ..telemetry import Histogram

# compile wall-times: ~1ms trace-cache hits up to minute-scale XLA runs
COMPILE_BOUNDS = (
    0.001, 0.003, 0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 60.0,
)

# one attribution ring, not per kernel: recent-first is what a failing
# steady-state assert wants to print
_EVENT_RING = 256

# HBM gauge value when the backend cannot answer (CPU-jax has no
# memory_stats); /metrics carries the sentinel, JSON carries null
HBM_UNKNOWN = -1.0


def _sig_of(args: tuple, kwargs: dict) -> tuple:
    """The jit-signature key for one call: array args by (shape, dtype),
    hashable non-array args (the statics) by value. Mirrors what jax.jit
    keys its compile cache on closely enough that a NEW key here is a
    new traced/compiled program for our kernels (all statics pass by
    keyword, all arrays positionally)."""
    key: list = []
    for a in args:
        shp = getattr(a, "shape", None)
        if shp is not None:
            # the dtype OBJECT, not str(dtype): numpy/jax dtypes hash and
            # compare by identity semantics, and their __str__ costs ~4us
            # per array — 20x the rest of the probe (bench cfg 2's
            # sig_probe_ns_per_dispatch watches this)
            key.append((tuple(shp), getattr(a, "dtype", None)))
        else:
            key.append(a if isinstance(a, (int, float, bool, str, type(None))) else type(a).__name__)
    for k in sorted(kwargs):
        v = kwargs[k]
        shp = getattr(v, "shape", None)
        if shp is not None:
            key.append((k, tuple(shp), getattr(v, "dtype", None)))
        else:
            key.append((k, v if isinstance(v, (int, float, bool, str, type(None))) else type(v).__name__))
    return tuple(key)


def _shape_bucket(args: tuple, kwargs: dict) -> str:
    """Human-readable signature for the attribution ring: array shapes
    plus the static kwargs, e.g. ``"64x8,64x8,capacity=512"``."""
    parts: list[str] = []
    for a in args:
        shp = getattr(a, "shape", None)
        if shp is not None:
            parts.append("x".join(str(d) for d in shp) or "scalar")
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, (int, float, bool, str)):
            parts.append(f"{k}={v}")
    return ",".join(parts)[:160]


class CompileLedger:
    """Bounded record of compile events with per-kernel counts. One
    module-level instance (``LEDGER``) serves every kernel in the
    process; broker instances bind their registries to it so the
    labeled counter family and the compile-seconds histogram appear on
    each broker's /metrics without the ledger holding them alive."""

    def __init__(self) -> None:
        # lazy import: telemetry <- locked <- telemetry is already a
        # settled cycle; devicestats itself is imported lazily from the
        # kernel modules so `import mqtt_tpu.ops` stays light
        from ..utils.locked import InstrumentedLock

        self._lock = InstrumentedLock("device_stats")
        self._counts: dict[str, int] = {}
        self._events: deque = deque(maxlen=_EVENT_RING)
        self._total = 0
        self.compile_hist = Histogram(bounds=COMPILE_BOUNDS)
        self._registries: "weakref.WeakSet" = weakref.WeakSet()

    # -- registry binding --------------------------------------------------

    def bind_registry(self, registry) -> None:
        """Expose this ledger on one broker's /metrics: the
        compile-seconds histogram plus a labeled recompiles counter per
        already-seen kernel (later first-seen kernels register their
        child on the fly). Idempotent; holds no ledger lock while
        talking to the registry."""
        with self._lock:
            kernels = list(self._counts)
        self._registries.add(registry)
        registry.histogram(
            "mqtt_tpu_matcher_compile_seconds",
            "Wall seconds of each jit compile (first call per signature)",
            bounds=COMPILE_BOUNDS,
            fn=lambda: self.compile_hist,
        )
        for kernel in kernels:
            self._register_kernel(registry, kernel)

    def _register_kernel(self, registry, kernel: str) -> None:
        registry.counter(
            "mqtt_tpu_matcher_recompiles_total",
            "jit compile events per kernel (a NONZERO steady-state rate "
            "is the PR 11 recompile-churn failure mode)",
            fn=lambda k=kernel: self.count(k),
            kernel=kernel,
        )

    # -- event intake ------------------------------------------------------

    def note_compile(self, kernel: str, shape_bucket: str, seconds: float) -> None:
        """Record one compile event; the single seam every jit entry
        point funnels through."""
        with self._lock:
            first = kernel not in self._counts
            self._counts[kernel] = self._counts.get(kernel, 0) + 1
            self._total += 1
            self.compile_hist.observe(seconds)
            self._events.append(
                {
                    "kernel": kernel,
                    "shape_bucket": shape_bucket,
                    "seconds": round(seconds, 6),
                    "time_unix": time.time(),  # brokerlint: ok=R3 wall-clock event timestamp for the attribution ring, not an interval
                }
            )
        if first:
            # child registration outside the ledger lock: device_stats
            # stays a leaf in the lock-order graph
            for registry in list(self._registries):
                self._register_kernel(registry, kernel)

    # -- reads -------------------------------------------------------------

    def count(self, kernel: str) -> int:
        with self._lock:
            return self._counts.get(kernel, 0)

    def counts(self) -> dict:
        with self._lock:
            return dict(self._counts)

    def total(self) -> int:
        with self._lock:
            return self._total

    def events(self, n: Optional[int] = None) -> list:
        """Most-recent-last compile events (the attribution ring)."""
        with self._lock:
            evs = list(self._events)
        return evs if n is None else evs[-n:]

    def attribution(self, since_total: int = 0) -> str:
        """Human-readable blame for compile events past ``since_total``
        — what a failed steady-state-recompile assert prints."""
        evs = self.events()
        new = max(0, self.total() - since_total)
        tail = evs[-new:] if new else []
        if not tail:
            return "no compile events recorded"
        lines = [
            f"  {e['kernel']}[{e['shape_bucket']}] {e['seconds'] * 1e3:.1f}ms"
            for e in tail
        ]
        return f"{new} compile event(s):\n" + "\n".join(lines)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "total": self._total,
                "kernels": dict(self._counts),
                "recent": list(self._events)[-32:],
                "seconds": self.compile_hist.summary(),
            }


LEDGER = CompileLedger()

# A/B switch for the bench overhead block: with the watch disabled the
# wrapped kernels skip signature computation entirely (the exact
# sampled-path cost the <=2% acceptance bound covers)
_ENABLED = True


def set_watch_enabled(enabled: bool) -> None:
    global _ENABLED
    _ENABLED = bool(enabled)


def watch_enabled() -> bool:
    return _ENABLED


class KernelWatch:
    """Wrap a jitted callable; time the first call per new signature and
    note it as a compile event. The steady-state cost is one signature
    tuple per *batch* (not per message) plus a set lookup."""

    __slots__ = ("kernel", "fn", "ledger", "_seen", "_lock")

    def __init__(self, kernel: str, fn: Callable, ledger: Optional[CompileLedger] = None) -> None:
        self.kernel = kernel
        self.fn = fn
        self.ledger = LEDGER if ledger is None else ledger
        self._seen: set = set()
        self._lock = threading.Lock()  # anonymous: guards _seen only, never calls out

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self.fn(*args, **kwargs)
        key = _sig_of(args, kwargs)
        if key in self._seen:
            return self.fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self.fn(*args, **kwargs)
        seconds = time.perf_counter() - t0
        with self._lock:
            new = key not in self._seen
            self._seen.add(key)
        if new:
            self.ledger.note_compile(self.kernel, _shape_bucket(args, kwargs), seconds)
        return out


def skew_of(tile_hits) -> float:
    """max/mean over per-tile hit counts — 1.0 is a perfectly balanced
    mesh, ``n_tiles`` is one hot tile doing all the work, 0.0 means no
    hits yet (no skew claim before traffic)."""
    arr = np.asarray(tile_hits, dtype=np.float64)
    if arr.size == 0:
        return 0.0
    mean = float(arr.mean())
    if mean <= 0.0:
        return 0.0
    return float(arr.max()) / mean


class DeviceStatsPlane:
    """The per-device snapshot/surface layer: owns the HBM gauges and
    the skew gauge, binds the ledger to the broker's registry, and
    renders the JSON for /devices, $SYS/broker/devices/#, and the
    trigger-dump sibling. Stateless beyond its attachment points — all
    live numbers come from jax, the profiler, the matcher, and the
    ledger at read time."""

    def __init__(
        self,
        registry=None,
        hbm_watermark: float = 0.9,
        ledger: Optional[CompileLedger] = None,
    ) -> None:
        self.registry = registry
        self.hbm_watermark = float(hbm_watermark)
        self.ledger = LEDGER if ledger is None else ledger
        self.profiler = None  # tracing.DeviceProfiler, per-device windows
        self.matcher = None  # ShardedTpuMatcher for tile/skew state
        self._devices: list = []
        try:
            import jax

            self._devices = list(jax.devices())
        except Exception:  # brokerlint: ok=R4 no jax backend: the plane degrades to ledger-only rather than failing broker boot
            self._devices = []
        if registry is not None:
            self.ledger.bind_registry(registry)
            for d in self._devices:
                did = str(getattr(d, "id", 0))
                for name, key in (
                    ("mqtt_tpu_device_hbm_live_bytes", "bytes_in_use"),
                    ("mqtt_tpu_device_hbm_peak_bytes", "peak_bytes_in_use"),
                    ("mqtt_tpu_device_hbm_limit_bytes", "bytes_limit"),
                ):
                    registry.gauge(
                        name,
                        "Per-device HBM occupancy via memory_stats() "
                        "(-1: backend cannot answer)",
                        fn=lambda d=d, k=key: self._mem(d, k),
                        device=did,
                    )
                registry.gauge(
                    "mqtt_tpu_device_hbm_ratio",
                    "live/limit HBM occupancy per device (0.0 unknown) — "
                    "the HBM-watermark SLO source",
                    fn=lambda d=d: self._mem_ratio(d),
                    device=did,
                )
            registry.gauge(
                "mqtt_tpu_device_skew_ratio",
                "max/mean per-tile hit counts across the shard mesh "
                "(1.0 balanced, 0.0 no traffic)",
                fn=self.skew_ratio,
            )

    # -- HBM ---------------------------------------------------------------

    @staticmethod
    def _mem(device, key: str) -> float:
        try:
            stats = device.memory_stats()
        except Exception:  # brokerlint: ok=R4 memory_stats is per-backend best effort (CPU-jax raises); sentinel keeps the scrape alive
            return HBM_UNKNOWN
        if not stats or key not in stats:
            return HBM_UNKNOWN
        return float(stats[key])

    @classmethod
    def _mem_ratio(cls, device) -> float:
        live = cls._mem(device, "bytes_in_use")
        limit = cls._mem(device, "bytes_limit")
        if live < 0.0 or limit <= 0.0:
            return 0.0
        return live / limit

    def hbm_ratio(self) -> float:
        """The worst (max) per-device live/limit ratio — what the
        watermark objective and the /healthz degraded entry read."""
        ratios = [self._mem_ratio(d) for d in self._devices]
        return max(ratios) if ratios else 0.0

    def hbm_degraded(self) -> bool:
        ratio = self.hbm_ratio()
        # a backend that cannot answer (ratio 0.0) is never degraded
        return ratio > 0.0 and ratio >= self.hbm_watermark

    # -- attachments -------------------------------------------------------

    def attach_profiler(self, profiler) -> None:
        self.profiler = profiler

    def attach_matcher(self, matcher) -> None:
        """Adopt a matcher's tile-skew state (ShardedTpuMatcher exports
        tile_hit_counts/tile_fill_hists; a single-device TpuMatcher has
        neither and the skew gauge stays 0.0)."""
        self.matcher = matcher
        hists = getattr(matcher, "tile_fill_hists", None)
        if self.registry is not None and hists:
            for t, h in enumerate(hists):
                self.registry.counter(
                    "mqtt_tpu_device_tile_hits_total",
                    "Cumulative matcher hits landing on each batch tile",
                    fn=lambda m=matcher, t=t: int(m.tile_hit_counts()[t]),
                    tile=str(t),
                )
                self.registry.histogram(
                    "mqtt_tpu_device_tile_fill_ratio",
                    "Per-batch fill of each tile's compact capacity",
                    bounds=h.bounds,
                    fn=lambda h=h: h,
                    tile=str(t),
                )

    def skew_ratio(self) -> float:
        m = self.matcher
        if m is None:
            return 0.0
        fn = getattr(m, "device_skew_ratio", None)
        return float(fn()) if fn is not None else 0.0

    # -- renders -----------------------------------------------------------

    def snapshot(self) -> dict:
        """The /devices + dump-sibling JSON body."""
        prof = self.profiler
        windows = prof.device_snapshot() if prof is not None else {}
        devices = []
        for d in self._devices:
            did = int(getattr(d, "id", 0))
            live = self._mem(d, "bytes_in_use")
            peak = self._mem(d, "peak_bytes_in_use")
            limit = self._mem(d, "bytes_limit")
            entry: dict = {
                "id": did,
                "platform": str(getattr(d, "platform", "unknown")),
                "hbm": {
                    "live_bytes": None if live < 0 else int(live),
                    "peak_bytes": None if peak < 0 else int(peak),
                    "limit_bytes": None if limit < 0 else int(limit),
                    "ratio": round(self._mem_ratio(d), 6),
                },
            }
            entry.update(
                windows.get(
                    did,
                    {
                        "duty_cycle": 0.0,
                        "overlap_ratio": 0.0,
                        "batches": 0,
                        "d2h_bytes_total": 0,
                        "issue_p99_ms": 0.0,
                        "d2h_p99_ms": 0.0,
                        "idle_gap_p99_ms": 0.0,
                    },
                )
            )
            devices.append(entry)
        m = self.matcher
        tile_hits = (
            [int(x) for x in m.tile_hit_counts()]
            if m is not None and hasattr(m, "tile_hit_counts")
            else []
        )
        return {
            "time_unix": int(time.time()),  # brokerlint: ok=R3 wall-clock snapshot stamp, not an interval
            "n_devices": len(self._devices),
            "devices": devices,
            "skew": {
                "ratio": round(self.skew_ratio(), 6),
                "tile_hits": tile_hits,
            },
            "hbm": {
                "watermark": self.hbm_watermark,
                "ratio": round(self.hbm_ratio(), 6),
                "degraded": self.hbm_degraded(),
            },
            "compiles": self.ledger.snapshot(),
        }

    def sys_tree(self) -> dict:
        """Flat ``suffix -> value`` rows for ``$SYS/broker/devices/#``."""
        out: dict[str, Any] = {}
        snap = self.snapshot()
        for dev in snap["devices"]:
            base = str(dev["id"])
            hbm = dev["hbm"]
            out[f"{base}/hbm_live_bytes"] = (
                -1 if hbm["live_bytes"] is None else hbm["live_bytes"]
            )
            out[f"{base}/hbm_ratio"] = hbm["ratio"]
            out[f"{base}/duty_cycle"] = round(float(dev["duty_cycle"]), 6)
            out[f"{base}/d2h_bytes_total"] = int(dev["d2h_bytes_total"])
            out[f"{base}/batches"] = int(dev["batches"])
        out["skew_ratio"] = snap["skew"]["ratio"]
        out["hbm_watermark_degraded"] = int(snap["hbm"]["degraded"])
        out["compiles/total"] = snap["compiles"]["total"]
        for kernel, n in sorted(snap["compiles"]["kernels"].items()):
            out[f"compiles/{kernel}"] = n
        return out
