"""Device-resident retained matching: the publish CSR walk run in reverse.

A wildcard SUBSCRIBE against millions of retained topics is the mirror
image of the publish hot path: PUBLISH asks "which of P patterns match
this one topic", retained delivery asks "which of B topics match this one
pattern". Both are the same hash-join the flat kernel (mqtt_tpu.ops.flat)
already computes — so this engine reuses it verbatim with the roles
swapped: the single SUBSCRIBE filter becomes a one-pattern flat index
(``build_flat_index`` over a throwaway one-subscription trie) and the
retained topic NAMES become the tokenized topic batch. One packed H2D
transfer, one ``flat_match_packed`` dispatch, and the totals column names
every retained topic the filter reaches.

Correctness is anchored to the HOST walk (``TopicsIndex.messages``), the
same way the publish matcher is anchored to ``subscribers()``:

- **Namespace partitioning.** The retained corpus is kept per tenant
  namespace (mqtt_tpu.topics ``NS_CHAR`` scoping) with LOCALIZED names, so
  the walk's structural guards — a global wildcard never enters a
  namespace subtree, a tenant filter never leaves one — hold by
  construction instead of by kernel emulation.
- **``$SYS`` protection.** The walk hides the ``$SYS`` subtree from
  top-level wildcards ([MQTT-4.7.1-1/2]) but walks into other
  ``$``-prefixed roots. The kernel's dollar rule is driven by the
  tokenizer's ``is_dollar`` flag, so the engine OVERRIDES it to "first
  LOCAL level == $SYS" — bit-identical to the walk's guard, including the
  ``$other/...`` corner the plain ``startswith("$")`` flag would get
  wrong.
- **``#`` base-topic divergence.** Spec 4.7.1.2 (and the kernel) lets
  ``a/#`` match the topic ``a`` itself; the retained walk deliberately
  collects only strictly-deeper children. A host-side post-filter drops
  hits whose level count equals a ``#``-filter's base depth, restoring
  the walk's semantics exactly.
- **Fallback classes.** Anything the kernel geometry cannot represent —
  corpus topics or filters deeper than ``max_levels``, kernel probe
  overflow, a filter the one-pattern index could not seat — routes the
  whole call to the host walk and is COUNTED per class; capacity is never
  a correctness event.
- **Differential oracle + breaker.** Every Nth served match replays the
  host walk and compares topic-name sets (the established
  matcher/predicate/recrypt oracle pattern). The host wins any mismatch,
  which feeds a :class:`~mqtt_tpu.resilience.CircuitBreaker`; an open
  breaker degrades ALL retained matching to the host walk and heals
  through fully-verified probes — a device fault storm costs throughput,
  never a missed retained delivery.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from ..packets import Subscription
from ..resilience import CircuitBreaker
from ..topics import NS_CHAR, TopicsIndex, ns_local, ns_tenant
from .flat import build_flat_index, flat_match_packed
from .hashing import tokenize_topics

# host-fallback classes (counted; mirrors flat.py's fallback accounting)
FALLBACK_CLASSES = ("depth", "filter", "overflow", "error", "breaker")

_MIN_CAPACITY = 1024  # padded corpus floor: bounds JIT shape churn


def _is_sys_local(name: str) -> bool:
    """The walk's guard predicate: first LOCAL level is exactly $SYS."""
    return name == "$SYS" or name.startswith("$SYS/")


class _NsCorpus:
    """One namespace's retained-name corpus with an incrementally-built
    packed token matrix. Tombstoned rows keep their stale tokens (dropped
    host-side by the ``names[i] is None`` check) until the tombstone
    ratio forces a compaction rebuild."""

    __slots__ = ("names", "pos", "tombstones", "packed", "overflow", "n_tok")

    def __init__(self) -> None:
        self.names: List[Optional[str]] = []
        self.pos: Dict[str, int] = {}
        self.tombstones = 0
        self.packed: Optional[np.ndarray] = None
        self.overflow: Optional[np.ndarray] = None
        self.n_tok = 0  # rows of `names` covered by `packed`

    def active(self) -> int:
        return len(self.names) - self.tombstones


class RetainedMatchEngine:
    """Batched retained-topic matching for wildcard SUBSCRIBE, device
    kernel first, host walk as oracle and refuge."""

    def __init__(
        self,
        index: TopicsIndex,
        max_levels: int = 8,
        oracle_sample: int = 16,
        breaker: Optional[CircuitBreaker] = None,
        min_capacity: int = _MIN_CAPACITY,
        rebuild_ratio: float = 0.25,
    ) -> None:
        self.index = index
        self.max_levels = max_levels
        # 1-in-N differential sampling (0 disables the sampled oracle;
        # probe re-closes always verify fully)
        self.oracle_sample = max(0, oracle_sample)
        self.breaker = breaker or CircuitBreaker()
        self.min_capacity = max(1, min_capacity)
        self.rebuild_ratio = rebuild_ratio
        self._corpora: Dict[str, _NsCorpus] = {}
        self._fidx_cache: Dict[str, Any] = {}  # local filter -> FlatIndex
        self._lock = threading.Lock()  # anonymous: corpus/cache bookkeeping
        self._calls = 0
        self.device_matches = 0
        self.oracle_checks = 0
        self.oracle_mismatches = 0
        self.fallbacks: Dict[str, int] = {k: 0 for k in FALLBACK_CLASSES}

    # -- corpus maintenance --------------------------------------------------

    def note_retained(self, topic: str, retained: bool) -> None:
        """Track one scoped retained-topic mutation (server calls this
        from ``retain_message`` and the restore path)."""
        ns = ns_tenant(topic)
        local = ns_local(topic)
        with self._lock:
            c = self._corpora.get(ns)
            if c is None:
                if not retained:
                    return
                c = self._corpora[ns] = _NsCorpus()
            if retained:
                if local not in c.pos:
                    c.pos[local] = len(c.names)
                    c.names.append(local)
            else:
                i = c.pos.pop(local, None)
                if i is not None:
                    c.names[i] = None
                    c.tombstones += 1
                    if c.tombstones > self.rebuild_ratio * max(1, len(c.names)):
                        self._compact(c)

    def reseed(self) -> int:
        """Rebuild every corpus from the trie's retained store (restart
        restore / drift repair). Returns the corpus size."""
        snapshot = self.index.retained.get_all()
        corpora: Dict[str, _NsCorpus] = {}
        for topic in snapshot:
            ns = ns_tenant(topic)
            c = corpora.get(ns)
            if c is None:
                c = corpora[ns] = _NsCorpus()
            local = ns_local(topic)
            c.pos[local] = len(c.names)
            c.names.append(local)
        with self._lock:
            self._corpora = corpora
        return len(snapshot)

    def _compact(self, c: _NsCorpus) -> None:
        """Drop tombstones and force retokenization (lock held)."""
        c.names = [n for n in c.names if n is not None]
        c.pos = {n: i for i, n in enumerate(c.names) if n is not None}
        c.tombstones = 0
        c.packed = None
        c.overflow = None
        c.n_tok = 0

    def _ensure_tokens(self, c: _NsCorpus) -> None:
        """Tokenize rows appended since the last match (lock held). The
        packed matrix is padded to a power-of-two capacity (zero rows:
        harmless, never read host-side) so kernel shapes — and therefore
        JIT compilations — stay bounded."""
        n = len(c.names)
        width = 2 * self.max_levels + 2
        cap = self.min_capacity
        while cap < n:
            cap *= 2
        if c.packed is None or c.packed.shape[0] < cap:
            packed = np.zeros((cap, width), dtype=np.int32)
            overflow = np.zeros(cap, dtype=bool)
            if c.packed is not None and c.n_tok:
                packed[: c.n_tok] = c.packed[: c.n_tok]
                overflow[: c.n_tok] = c.overflow[: c.n_tok]  # type: ignore[index]
            c.packed, c.overflow = packed, overflow
        if c.n_tok < n:
            fresh = [x if x is not None else "" for x in c.names[c.n_tok : n]]
            tok1, tok2, lengths, _dollar, over = tokenize_topics(
                fresh, self.max_levels, 0
            )
            # the $SYS guard override (module docstring): NOT startswith("$")
            dollar = np.fromiter(
                (_is_sys_local(x) for x in fresh), dtype=bool, count=len(fresh)
            )
            L = self.max_levels
            assert c.packed is not None and c.overflow is not None
            c.packed[c.n_tok : n, :L] = tok1.view(np.int32)
            c.packed[c.n_tok : n, L : 2 * L] = tok2.view(np.int32)
            c.packed[c.n_tok : n, 2 * L] = lengths.astype(np.int32)
            c.packed[c.n_tok : n, 2 * L + 1] = dollar.astype(np.int32)
            c.overflow[c.n_tok : n] = over
            c.n_tok = n

    # -- filter index --------------------------------------------------------

    def _filter_index(self, local_filter: str):
        """A one-pattern flat index for the SUBSCRIBE filter (cached —
        fleets re-subscribe the same wildcard filters constantly), or
        None when the kernel cannot represent it."""
        fidx = self._fidx_cache.get(local_filter)
        if fidx is not None:
            return fidx
        tmp = TopicsIndex()
        tmp.subscribe("\x00probe", Subscription(filter=local_filter, qos=0))
        fidx = build_flat_index(
            tmp, max_levels=self.max_levels, salt=0, min_buckets=64
        )
        if fidx.n_entries != 1 or fidx.salt != 0:
            return None  # over-deep filter omitted, or salt re-rolled
        if len(self._fidx_cache) >= 512:
            self._fidx_cache.pop(next(iter(self._fidx_cache)))
        self._fidx_cache[local_filter] = fidx
        return fidx

    # -- matching ------------------------------------------------------------

    def _host_names(self, filter: str) -> List[str]:
        return [pk.topic_name for pk in self.index.messages(filter)]

    def _device_names(self, filter: str) -> Optional[List[str]]:
        """The kernel leg: scoped retained names matching ``filter``, or
        None with the fallback class counted."""
        ns = ns_tenant(filter)
        local = ns_local(filter)
        if len(local.split("/")) > self.max_levels:
            self.fallbacks["depth"] += 1
            return None
        with self._lock:
            c = self._corpora.get(ns)
            if c is None or c.active() == 0:
                return []
            self._ensure_tokens(c)
            assert c.packed is not None and c.overflow is not None
            n = len(c.names)
            if bool(c.overflow[:n].any()):
                # an over-deep retained topic exists in this namespace:
                # the kernel cannot see its deep levels, so the walk
                # serves the whole namespace
                self.fallbacks["depth"] += 1
                return None
            names = list(c.names)
            packed = c.packed
        fidx = self._filter_index(local)
        if fidx is None:
            self.fallbacks["filter"] += 1
            return None
        out = np.asarray(
            flat_match_packed(
                fidx.table,
                fidx.pat_kind,
                fidx.pat_depth,
                fidx.pat_mask,
                packed,
                max_levels=self.max_levels,
            )
        )
        p = fidx.pat_kind.shape[0]
        totals = out[: len(names), 2 * p]
        if bool(out[: len(names), 2 * p + 1].any()):
            self.fallbacks["overflow"] += 1
            return None
        hits = [i for i in range(len(names)) if names[i] is not None and totals[i] > 0]
        if local == "#" or local.endswith("/#"):
            # the walk's strictly-deeper `#` semantics (module docstring)
            base = len(local.split("/")) - 1
            hits = [
                i
                for i in hits
                if len(names[i].split("/")) != base  # type: ignore[union-attr]
            ]
        self.device_matches += 1
        if ns:
            return [NS_CHAR + ns + "/" + names[i] for i in hits]  # type: ignore[operator]
        return [names[i] for i in hits]  # type: ignore[misc]

    def match(self, filter: str) -> Optional[List[str]]:
        """Scoped retained topic names matching a scoped WILDCARD
        filter, or None when the caller must run the host walk itself
        (breaker open, capacity fallback, non-wildcard filter)."""
        local = ns_local(filter)
        if "+" not in local and "#" not in local:
            return None  # exact filters take the walk's O(1) fast path
        if local.startswith("$SHARE/"):
            return None  # shared filters get no retained delivery
        if not self.breaker.allow():
            if not self.breaker.acquire_probe():
                self.fallbacks["breaker"] += 1
                return None
            # probe: serve device but verify FULLY against the walk
            try:
                names = self._device_names(filter)
            except Exception:
                self.breaker.record_probe_failure("error")
                self.fallbacks["error"] += 1
                return None
            if names is None:
                self.breaker.record_probe_failure("fallback")
                return None
            host = self._host_names(filter)
            if sorted(host) != sorted(names):
                self.oracle_mismatches += 1
                self.breaker.record_probe_failure("mismatch")
                return host  # host wins the disagreement
            self.breaker.record_probe_success()
            return names
        try:
            names = self._device_names(filter)
        except Exception:
            self.log_error()
            self.breaker.record_failure("error")
            self.fallbacks["error"] += 1
            return None
        if names is None:
            return None
        self._calls += 1
        if self.oracle_sample and self._calls % self.oracle_sample == 0:
            self.oracle_checks += 1
            host = self._host_names(filter)
            if sorted(host) != sorted(names):
                self.oracle_mismatches += 1
                self.breaker.record_failure("mismatch")
                return host  # host wins; breaker counts the fault
            self.breaker.record_success()
        return names

    def log_error(self) -> None:  # split out so tests can silence it
        import logging

        logging.getLogger("mqtt_tpu.ops").exception(
            "retained device match failed; host walk serves"
        )

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            corpus = sum(c.active() for c in self._corpora.values())
        return {
            "corpus": corpus,
            "device_matches": self.device_matches,
            "oracle_checks": self.oracle_checks,
            "oracle_mismatches": self.oracle_mismatches,
            "fallbacks": dict(self.fallbacks),
            "breaker_state": self.breaker.state,
        }
