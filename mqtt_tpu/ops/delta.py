"""Delta-staged device matcher: serve from a frozen CSR snapshot while the
trie churns, stay bit-identical, rebuild in the background.

The plain :class:`~mqtt_tpu.ops.matcher.TpuMatcher` recompiles the whole CSR
index whenever the trie version moves — a full rebuild is seconds at 1M
subscriptions, which no live broker can afford on every SUBSCRIBE. The
reference never has this problem because its walk reads the live trie under
a mutex (topics.go:593-628); the device index trades that for snapshot
semantics, so this module supplies the staleness story (SURVEY.md §7
stage 5, hard part #2):

- The device keeps serving the last compiled snapshot.
- Every trie mutation (via ``TopicsIndex.add_observer``) records the mutated
  filter in a host-side *delta overlay*: an append log plus a mini-trie of
  just the mutated filters. Client/shared mutations are recorded as client
  subscriptions and inline mutations as inline subscriptions, so the
  overlay applies the same $-topic exclusion rules [MQTT-4.7.1-1/2] as the
  real walk (an inline delta on ``#`` must flag ``$SYS/...`` topics even
  though a client delta on ``#`` must not).
- Per matched topic, the mini-trie answers "could any mutation since the
  snapshot affect this topic's subscriber set?" — a topic that matches no
  delta filter has, by construction, an identical subscriber set in the
  snapshot and the live trie, so the device result is served; affected
  topics re-walk the live host trie. Results are therefore bit-identical to
  ``TopicsIndex.subscribers`` at every instant, at any rebuild cadence.
- A background thread recompiles the CSR when the overlay grows past
  ``rebuild_after`` filters (or on demand via :meth:`flush`); the overlay
  generation swaps atomically and carries over only the mutations that
  arrived while the walk ran.

Because the overlay mini-trie IS a ``TopicsIndex``, its walk applies every
matching rule — including the parent-inline quirk (topics.go:615) — so the
affected-check is exact: a topic is routed to the host walk iff some
recorded mutation can actually reach it.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..packets import Subscription
from ..topics import InlineSubscription, Mutation, Subscribers, TopicsIndex
from .matcher import TpuMatcher

_DELTA_CLIENT = "\x00delta"  # mini-trie marker client; never a real client id
_log = logging.getLogger("mqtt_tpu.ops.delta")


def _noop_handler(*_a) -> None:  # pragma: no cover - marker, never invoked
    pass


class _Snapshot(TpuMatcher):
    """A TpuMatcher that never self-rebuilds: the delta overlay makes
    serving a stale snapshot safe, so staleness is frozen off."""

    @property
    def stale(self) -> bool:  # noqa: D401 - see class docstring
        return False


def _sharded_snapshot_cls():
    """The mesh-sharded analog of _Snapshot (imported lazily: mqtt_tpu.ops
    must not pull jax.sharding machinery unless a mesh is actually used)."""
    from ..parallel.sharded import ShardedTpuMatcher

    class _ShardedSnapshot(ShardedTpuMatcher):
        @property
        def stale(self) -> bool:
            return False

    return _ShardedSnapshot


class _Gen:
    """One snapshot generation: the compiled device index plus the overlay
    of filters mutated since its build started."""

    __slots__ = ("snap", "delta_trie", "deltas", "seen")

    def __init__(self, snap: _Snapshot, deltas: list[tuple[str, str]]) -> None:
        self.snap = snap
        self.delta_trie = TopicsIndex()
        self.deltas: list[tuple[str, str]] = []
        self.seen: set[tuple[str, str]] = set()
        for f, kind in deltas:
            self.record(f, kind)

    def record(self, filter: str, kind: str) -> None:
        key = (filter, kind)
        self.deltas.append(key)
        if key in self.seen:
            return
        self.seen.add(key)
        if filter:
            if kind == "inline":
                # inline markers follow inline gather rules (no $-exclusion)
                self.delta_trie.inline_subscribe(
                    InlineSubscription(filter=filter, identifier=1, handler=_noop_handler)
                )
            else:
                self.delta_trie.subscribe(_DELTA_CLIENT, Subscription(filter=filter))

    def affected(self, topic: str) -> bool:
        """True when some mutation since the snapshot may change ``topic``'s
        subscriber set."""
        if not self.deltas:
            return False
        s = self.delta_trie.subscribers(topic)
        return bool(s.subscriptions or s.shared or s.inline_subscriptions)

    def affected_batch(self, topics: list[str]) -> list[int]:
        """Indices of topics the overlay may affect. The batch form lets
        the resolver skip the per-topic predicate loop entirely when no
        mutations are pending — the common case for a broker whose
        subscriptions arrive at connect time."""
        if not self.deltas:
            return []
        affected = self.affected
        return [i for i, t in enumerate(topics) if t and affected(t)]


class DeltaMatcher:
    """Drop-in for ``TopicsIndex.subscribers`` that serves device matches
    from a snapshot + host delta overlay and rebuilds off the hot path.

    Parameters
    ----------
    rebuild_after:
        Overlay size (mutation events) that triggers an immediate background
        recompile. The overlay stays correct at any size — this only tunes
        how much traffic takes the slower host path.
    rebuild_interval:
        The background thread additionally folds a NON-empty overlay every
        this many seconds, so a quiet broker (e.g. all subscribes at connect
        time, publishes after) drains its overlay instead of serving the
        host path forever below the count threshold.
    background:
        When True (default), rebuilds run on a daemon thread; when False,
        call :meth:`flush` to recompile synchronously (tests, benchmarks).
    mesh:
        When given, the snapshot is a mesh-sharded matcher
        (``mqtt_tpu.parallel.ShardedTpuMatcher``) whose incremental rebuild
        recompiles only the shards touched since the last fold — the same
        overlay correctness story at per-shard rebuild cost.
    """

    def __init__(
        self,
        topics: TopicsIndex,
        max_levels: int = 8,
        frontier: int = 16,
        out_slots: int = 64,
        rebuild_after: int = 1024,
        rebuild_interval: float = 1.0,
        background: bool = True,
        mesh=None,
        transfer_slots: Optional[int] = None,
        window: int = 16,
        compact: bool = True,
        compact_capacity: int = 0,
        hits_estimate: float = 2.0,
        lazy: bool = True,
    ) -> None:
        self.topics = topics
        self.max_levels = max_levels
        self.frontier = frontier
        self.out_slots = out_slots
        self.window = window
        self.rebuild_after = rebuild_after
        self.rebuild_interval = rebuild_interval
        self.background = background
        self._lock = threading.Lock()  # guards generation swap + delta append
        self._rebuild_lock = threading.Lock()  # one rebuild at a time
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ONE snapshot matcher reused across generations: both matcher kinds
        # swap their compiled state atomically, and the sharded one folds
        # deltas incrementally (per-shard) instead of recompiling the world
        if mesh is not None:
            snap = _sharded_snapshot_cls()(
                topics,
                mesh=mesh,
                max_levels=max_levels,
                out_slots=out_slots,
                window=window,
                compact=compact,
                compact_capacity=compact_capacity,
                hits_estimate=hits_estimate,
                lazy=lazy,
            )
        else:
            snap = _Snapshot(
                topics,
                max_levels,
                frontier,
                out_slots,
                transfer_slots=transfer_slots,
                window=window,
                # background rebuilds must not starve the serving thread's
                # match latency for the build duration (churn p99)
                cooperative=background,
                compact=compact,
                compact_capacity=compact_capacity,
                hits_estimate=hits_estimate,
                lazy=lazy,
            )
        snap.rebuild()
        self._snap = snap
        self._gen = _Gen(snap, [])
        topics.add_observer(self._on_mutation)
        if background:
            self._thread = threading.Thread(
                target=self._rebuild_loop, name="mqtt-tpu-csr-rebuild", daemon=True
            )
            self._thread.start()

    @property
    def stats(self):
        """The underlying matcher's observability counters."""
        return self._snap.stats

    # -- delta stream --------------------------------------------------------

    def _on_mutation(self, m: Mutation) -> None:
        with self._lock:
            gen = self._gen
            gen.record(m.filter, m.kind)
            pending = len(gen.deltas)
        if pending >= self.rebuild_after:
            self._wake.set()

    @property
    def pending_deltas(self) -> int:
        with self._lock:
            return len(self._gen.deltas)

    # -- rebuild -------------------------------------------------------------

    def _rebuild_snapshot(self, filters=None) -> None:
        """Fold the live trie into the snapshot without holding its lock;
        concurrent structural mutations can tear the walk (RuntimeError from
        a mutated dict iteration, KeyError from a node inserted mid-walk),
        in which case retry — every mutation racing the walk is in the delta
        overlay, so a successful walk is always safe to serve.

        When the pending mutations' filter set is known, the single-device
        snapshot first attempts an incremental fold (TpuMatcher.fold):
        per-bucket in-place edits plus a ~KB device scatter instead of a
        full rebuild + table upload — the difference between multi-second
        and sub-ms p99 under churn on a slow host<->device link."""
        if filters is not None and hasattr(self._snap, "fold"):
            try:
                if self._snap.fold(filters):
                    return
            except (RuntimeError, KeyError):
                pass  # torn reads: fall through to the retried full path
        if getattr(self._snap, "handles_tears", False):
            # the sharded snapshot retries tears (and quiesces) internally;
            # its rebuild takes its rebuild mutex BEFORE the trie lock, so
            # wrapping it in `with self.topics._lock` here would invert
            # that order and deadlock against a concurrent rebuild
            self._snap.rebuild()
            return
        for _ in range(8):
            try:
                self._snap.rebuild()
                return
            except (RuntimeError, KeyError):
                continue
        with self.topics._lock:  # mutation storm: build quiesced
            self._snap.rebuild()

    def _rebuild_once(self) -> None:
        with self._rebuild_lock:
            with self._lock:
                old = self._gen
                k = len(old.deltas)
            if k == 0:
                return
            self._rebuild_snapshot(filters={f for f, _ in old.deltas[:k]})
            with self._lock:
                # mutations that raced the walk (appended after index k)
                # might be missing from the new snapshot: carry them over
                self._gen = _Gen(self._snap, old.deltas[k:])

    def flush(self) -> None:
        """Synchronously fold all pending deltas into a fresh snapshot."""
        self._rebuild_once()

    def _rebuild_loop(self) -> None:
        while not self._stop.is_set():
            # wake on overflow OR on the interval tick, so a quiet overlay
            # still drains (count threshold alone could starve forever)
            self._wake.wait(timeout=self.rebuild_interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self._rebuild_once()
            except Exception:
                # never let the rebuild thread die: a degraded matcher keeps
                # serving (host path), a dead one degrades forever
                _log.exception("background CSR rebuild failed; will retry")
                self._stop.wait(1.0)
                self._wake.set()

    def close(self) -> None:
        self.topics.remove_observer(self._on_mutation)
        if hasattr(self._snap, "close"):
            self._snap.close()  # detach the sharded snapshot's own observer
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    # -- matching ------------------------------------------------------------

    def match_topics_async(self, topics: list[str], profile=None):
        """Issue one batch; the returned resolver yields the results.
        The generation (snapshot + overlay) is captured at issue time; the
        generation object itself is the route-to-host authority (it
        exposes both the per-topic ``affected`` predicate and the batch
        form the C materializer prefers). ``profile`` is the caller's
        optional per-batch BatchProfile (mqtt_tpu.tracing), forwarded to
        the snapshot matcher."""
        gen = self._gen  # atomic read: one generation per call
        return gen.snap.match_topics_async(topics, route_to_host=gen, profile=profile)

    def match_topics(self, topics: list[str]) -> list[Subscribers]:
        """Match a batch of topics, bit-identical to the live host trie."""
        return self.match_topics_async(topics)()

    def subscribers(self, topic: str) -> Subscribers:
        """Drop-in for ``TopicsIndex.subscribers`` (batch of one)."""
        return self.match_topics([topic])[0]
