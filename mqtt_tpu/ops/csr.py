"""Compile the host trie into device-resident CSR arrays.

The subscription trie (``mqtt_tpu.topics.TopicsIndex``) becomes a static
node table with three edge classes per node — sorted literal edges (binary
searched by token hash), one ``+`` child, one ``#`` child — plus two CSR
subscription lists per node:

- ``reg``  — client and shared subscriptions attached at the node
- ``inl``  — inline subscriptions (kept separate so the terminal child-``#``
  gather can exclude them, replicating reference topics.go:615)

Sub ids index a host-side :class:`SubEntry` table carrying the client/group
metadata (QoS, identifiers, NoLocal...) — the device returns ids only and
the host performs merge / shared-group selection, preserving reference
semantics (SURVEY.md §7 stage 4).

Building walks the *actual* host trie, so the device index is structurally
identical to the oracle by construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..topics import TopicsIndex, _Particle
from .hashing import hash_token

KIND_CLIENT = 0  # a normal client subscription
KIND_SHARED = 1  # a $SHARE group member
KIND_INLINE = 2  # an in-process inline subscription


@dataclass
class SubEntry:
    """Host-side metadata for one device sub id."""

    kind: int
    client: str  # client id (CLIENT/SHARED) or "" (INLINE)
    group_filter: str  # full $SHARE filter (SHARED only)
    subscription: Any  # packets.Subscription or topics.InlineSubscription


@dataclass
class CsrIndex:
    """The device-side CSR encoding of the subscription trie."""

    # node tables, length N (+1 for the CSR pointers)
    edge_ptr: np.ndarray  # int32[N+1] — literal-edge range per node
    edge_tok1: np.ndarray  # uint32[E] — sorted within each node's range
    edge_tok2: np.ndarray  # uint32[E] — verification hash per edge
    edge_dest: np.ndarray  # int32[E]
    plus_child: np.ndarray  # int32[N], -1 if none
    hash_child: np.ndarray  # int32[N], -1 if none
    reg_ptr: np.ndarray  # int32[N+1] — client+shared sub ids per node
    reg_ids: np.ndarray  # int32[R]
    inl_ptr: np.ndarray  # int32[N+1] — inline sub ids per node
    inl_ids: np.ndarray  # int32[I]
    top_wild: np.ndarray  # bool[S] — client sub whose filter starts with +/#
    # host-side
    subs: list[SubEntry] = field(default_factory=list)
    salt: int = 0
    max_degree: int = 0

    @property
    def num_nodes(self) -> int:
        return len(self.plus_child)

    @property
    def num_subs(self) -> int:
        return len(self.subs)


def build_csr(index: TopicsIndex, salt: int = 0, _retries: int = 4) -> CsrIndex:
    """Walk the host trie and emit the CSR index.

    Retries with a new hash salt if two distinct sibling edge tokens collide
    on hash1 (probability ~degree^2/2^33 per node).
    """
    nodes: list[_Particle] = []
    node_id: dict[int, int] = {}  # id(particle) -> dense id

    # iterative walk (deep tries must not recurse)
    stack = [index.root]
    while stack:
        p = stack.pop()
        node_id[id(p)] = len(nodes)
        nodes.append(p)
        stack.extend(p.particles.values())

    n = len(nodes)
    subs: list[SubEntry] = []
    top_wild_flags: list[bool] = []

    def add_sub(entry: SubEntry, top_wild: bool) -> int:
        subs.append(entry)
        top_wild_flags.append(top_wild)
        return len(subs) - 1

    edge_ptr = np.zeros(n + 1, dtype=np.int32)
    reg_ptr = np.zeros(n + 1, dtype=np.int32)
    inl_ptr = np.zeros(n + 1, dtype=np.int32)
    edge_tok1: list[int] = []
    edge_tok2: list[int] = []
    edge_dest: list[int] = []
    reg_ids: list[int] = []
    inl_ids: list[int] = []
    plus_child = np.full(n, -1, dtype=np.int32)
    hash_child = np.full(n, -1, dtype=np.int32)
    max_degree = 0

    for nid, p in enumerate(nodes):
        literals = []
        for key, child in p.particles.items():
            cid = node_id[id(child)]
            if key == "+":
                plus_child[nid] = cid
            elif key == "#":
                hash_child[nid] = cid
            else:
                h1, h2 = hash_token(key, salt)
                literals.append((h1, h2, cid))
        literals.sort()
        for i in range(1, len(literals)):
            if literals[i][0] == literals[i - 1][0]:
                if _retries <= 0:
                    raise RuntimeError("sibling edge hash collision; exhausted salts")
                return build_csr(index, salt=salt + 1, _retries=_retries - 1)
        max_degree = max(max_degree, len(literals))
        for h1, h2, cid in literals:
            edge_tok1.append(h1)
            edge_tok2.append(h2)
            edge_dest.append(cid)
        edge_ptr[nid + 1] = len(edge_tok1)

        for client, sub in p.subscriptions.get_all().items():
            top = bool(sub.filter) and sub.filter[0] in "+#"
            reg_ids.append(
                add_sub(SubEntry(KIND_CLIENT, client, "", sub), top)
            )
        for group_filter_subs in p.shared.get_all().values():
            for client, sub in group_filter_subs.items():
                # the $-exclusion never applies to shared subs
                reg_ids.append(
                    add_sub(SubEntry(KIND_SHARED, client, sub.filter, sub), False)
                )
        reg_ptr[nid + 1] = len(reg_ids)
        for ident, inline_sub in p.inline_subscriptions.get_all().items():
            inl_ids.append(add_sub(SubEntry(KIND_INLINE, "", "", inline_sub), False))
        inl_ptr[nid + 1] = len(inl_ids)

    return CsrIndex(
        edge_ptr=edge_ptr,
        edge_tok1=np.asarray(edge_tok1, dtype=np.uint32),
        edge_tok2=np.asarray(edge_tok2, dtype=np.uint32),
        edge_dest=np.asarray(edge_dest, dtype=np.int32),
        plus_child=plus_child,
        hash_child=hash_child,
        reg_ptr=reg_ptr,
        reg_ids=np.asarray(reg_ids, dtype=np.int32),
        inl_ptr=inl_ptr,
        inl_ids=np.asarray(inl_ids, dtype=np.int32),
        top_wild=np.asarray(top_wild_flags, dtype=bool),
        subs=subs,
        salt=salt,
        max_degree=max_degree,
    )
